"""Evolution Strategies (OpenAI-ES, Salimans et al. 2017).

Reference parity: rllib/algorithms/es/ (es.py driver + worker fleet,
shared-noise-table perturbations, centered-rank utilities, antithetic
pairs).  The design here is TPU-first rather than a translation:

* **Noise by seed, not by table**: workers regenerate each perturbation
  from its integer seed (`default_rng(seed)`), so only scalars cross
  the wire — the reference's 250MB shared noise table becomes ~8 bytes
  per direction.
* **Batched evaluation as one vmapped program**: a worker evaluates ALL
  its perturbations simultaneously — the policy forward is
  `vmap`-ed over a [2K, dim] parameter matrix against a 2K-env vector
  env, so the whole population rollout is a single jitted computation
  per step (MXU-batched on TPU; the reference steps one gym env per
  perturbation in Python).
* Episodes are masked, not restarted: each lane accumulates reward
  until its FIRST done; lanes then go inactive (the auto-reset obs
  keeps shapes static for XLA).

The evaluation worker is shared with ARS (ars.py).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.env import make_vector_env


# ---------------------------------------------------------------------------
# Flat-vector MLP policy (pure functions over a single flat param vector —
# the ES/ARS search space).
# ---------------------------------------------------------------------------

def _mlp_shapes(obs_dim: int, hidden: Tuple[int, ...], out_dim: int):
    dims = (obs_dim,) + tuple(hidden) + (out_dim,)
    return [(dims[i], dims[i + 1]) for i in range(len(dims) - 1)]


def _init_flat(obs_dim: int, hidden: Tuple[int, ...], out_dim: int,
               seed: int) -> np.ndarray:
    rng = np.random.default_rng(seed)
    parts = []
    for n_in, n_out in _mlp_shapes(obs_dim, hidden, out_dim):
        parts.append((rng.standard_normal((n_in, n_out))
                      / np.sqrt(n_in)).astype(np.float32).ravel())
        parts.append(np.zeros(n_out, np.float32))
    return np.concatenate(parts)


def _make_apply(obs_dim: int, hidden: Tuple[int, ...], out_dim: int):
    """Returns jitted batched_apply(P, obs) -> outputs, where P is a
    [B, dim] parameter matrix and obs is [B, obs_dim]: lane i runs the
    policy with ITS OWN parameters P[i] (vmap over params AND obs)."""
    import jax
    import jax.numpy as jnp

    shapes = _mlp_shapes(obs_dim, hidden, out_dim)

    def apply_one(flat, x):
        off = 0
        for i, (n_in, n_out) in enumerate(shapes):
            w = flat[off:off + n_in * n_out].reshape(n_in, n_out)
            off += n_in * n_out
            b = flat[off:off + n_out]
            off += n_out
            x = x @ w + b
            if i < len(shapes) - 1:
                x = jnp.tanh(x)
        return x

    return jax.jit(jax.vmap(apply_one))


# ---------------------------------------------------------------------------


@ray_tpu.remote
class EvalWorker:
    """Evaluates perturbed parameter vectors for full (masked) episodes.

    One call = one jitted rollout of the whole assigned population slice
    (antithetic pairs: lanes 2i / 2i+1 run theta +/- sigma*eps_i)."""

    def __init__(self, env: Any, hidden: Tuple[int, ...], seed: int,
                 horizon: int = 500):
        self._env_spec = env
        self._hidden = tuple(hidden)
        self._seed = seed
        self._horizon = horizon
        self._envs: Dict[int, Any] = {}   # lane count -> VectorEnv
        self._apply = None
        probe = make_vector_env(env, 1, seed=seed)
        self.obs_dim = probe.observation_dim
        self.num_actions = probe.num_actions
        self.action_dim = getattr(probe, "action_dim", 0)

    def _get_env(self, lanes: int):
        env = self._envs.get(lanes)
        if env is None:
            env = make_vector_env(self._env_spec, lanes, seed=self._seed)
            self._envs[lanes] = env
        return env

    def evaluate(self, theta: np.ndarray, seeds: List[int], sigma: float,
                 obs_stats: Optional[Tuple[np.ndarray, np.ndarray]] = None
                 ) -> Dict[str, Any]:
        """Antithetic evaluation: returns per-seed (r_plus, r_minus),
        episode lengths, and observation moments (for ARS-V2 filters).
        `obs_stats=(mean, std)` normalizes observations when given."""
        theta = np.asarray(theta, np.float32)
        dim = theta.size
        k = len(seeds)
        eps = np.stack([
            np.random.default_rng(s).standard_normal(dim).astype(np.float32)
            for s in seeds])                                   # [K, dim]
        pop = np.empty((2 * k, dim), np.float32)
        pop[0::2] = theta[None, :] + sigma * eps
        pop[1::2] = theta[None, :] - sigma * eps
        if self._apply is None:
            self._apply = _make_apply(self.obs_dim, self._hidden,
                                      self.num_actions or self.action_dim)
        env = self._get_env(2 * k)
        obs = env.reset_all(seed=self._seed)
        active = np.ones(2 * k, bool)
        returns = np.zeros(2 * k, np.float64)
        lengths = np.zeros(2 * k, np.int64)
        o_sum = np.zeros(self.obs_dim, np.float64)
        o_sq = np.zeros(self.obs_dim, np.float64)
        o_n = 0
        for _ in range(self._horizon):
            o_sum += obs[active].sum(0)
            o_sq += (obs[active] ** 2).sum(0)
            o_n += int(active.sum())
            x = obs
            if obs_stats is not None:
                x = (obs - obs_stats[0]) / obs_stats[1]
            out = np.asarray(self._apply(pop, x.astype(np.float32)))
            actions = (out.argmax(-1) if self.num_actions
                       else np.tanh(out))
            _obs, rew, term, trunc = env.step(actions)
            returns += rew * active
            lengths += active
            active &= ~(term | trunc)
            obs = _obs
            if not active.any():
                break
        env.drain_episode_metrics()  # masked lanes: driver uses `returns`
        return {"r_plus": returns[0::2], "r_minus": returns[1::2],
                "lengths": lengths, "obs_sum": o_sum, "obs_sq": o_sq,
                "obs_n": o_n}


def centered_ranks(x: np.ndarray) -> np.ndarray:
    """Rank transform to [-0.5, 0.5] (reference: es/utils.py
    compute_centered_ranks) — scale-free utilities make the update
    invariant to reward magnitude."""
    flat = x.ravel()
    ranks = np.empty(flat.size, dtype=np.float64)
    ranks[flat.argsort()] = np.arange(flat.size)
    ranks = ranks / (flat.size - 1) - 0.5
    return ranks.reshape(x.shape)


class ESConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=ES)
        self.num_rollout_workers = 2
        self.episodes_per_batch = 32     # perturbation DIRECTIONS per iter
        self.noise_stdev = 0.05
        self.lr = 0.02
        self.l2_coeff = 0.005
        self.episode_horizon = 500
        self.model_hidden = (32, 32)


class ES(Algorithm):
    """Driver: sample direction seeds -> fan out to the worker fleet ->
    centered-rank gradient estimate -> Adam step on the flat vector."""

    def setup(self) -> None:
        cfg = self.config
        self.theta = _init_flat(self.obs_dim, tuple(cfg.model_hidden),
                                self.num_actions or self.action_dim,
                                cfg.seed)
        self._rng = np.random.default_rng(cfg.seed)
        self._adam_m = np.zeros_like(self.theta)
        self._adam_v = np.zeros_like(self.theta)
        self._adam_t = 0
        self.workers = [
            EvalWorker.options(num_cpus=cfg.num_cpus_per_worker).remote(
                cfg.env, tuple(cfg.model_hidden), cfg.seed + 7919 * (i + 1),
                cfg.episode_horizon)
            for i in range(max(1, cfg.num_rollout_workers))]

    def _fan_out(self, seeds: np.ndarray, obs_stats=None):
        n = len(self.workers)
        shards = np.array_split(seeds, n)
        refs = [w.evaluate.remote(self.theta, [int(s) for s in shard],
                                  self.config.noise_stdev, obs_stats)
                for w, shard in zip(self.workers, shards) if len(shard)]
        return ray_tpu.get(refs, timeout=600), [s for s in shards if len(s)]

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        n_dir = cfg.episodes_per_batch
        seeds = self._rng.integers(0, 2 ** 31 - 1, size=n_dir)
        results, shards = self._fan_out(seeds)
        r_plus = np.concatenate([r["r_plus"] for r in results])
        r_minus = np.concatenate([r["r_minus"] for r in results])
        used = np.concatenate(shards)
        # Utilities from the CENTERED RANKS of all 2n returns.
        ranks = centered_ranks(np.stack([r_plus, r_minus]))
        weights = ranks[0] - ranks[1]                          # [n_dir]
        eps = np.stack([
            np.random.default_rng(int(s)).standard_normal(self.theta.size)
            .astype(np.float32) for s in used])
        grad = (weights[:, None] * eps).sum(0) / (
            n_dir * cfg.noise_stdev)
        grad = grad - cfg.l2_coeff * self.theta                # weight decay
        # Adam ascent on the flat vector (reference: es/optimizers.py).
        self._adam_t += 1
        b1, b2, eps_ = 0.9, 0.999, 1e-8
        self._adam_m = b1 * self._adam_m + (1 - b1) * grad
        self._adam_v = b2 * self._adam_v + (1 - b2) * grad * grad
        mh = self._adam_m / (1 - b1 ** self._adam_t)
        vh = self._adam_v / (1 - b2 ** self._adam_t)
        self.theta += cfg.lr * mh / (np.sqrt(vh) + eps_)

        all_returns = np.concatenate([r_plus, r_minus])
        lengths = np.concatenate([r["lengths"] for r in results])
        self._episode_returns.extend(all_returns.tolist())
        self._episode_lengths.extend(lengths.tolist())
        self.total_env_steps += int(lengths.sum())
        return {"episodes_this_iter": int(all_returns.size),
                "update_norm": float(np.linalg.norm(grad)),
                "theta_norm": float(np.linalg.norm(self.theta))}

    def save_to_dict(self) -> Dict[str, Any]:
        return {"theta": self.theta, "adam_m": self._adam_m,
                "adam_v": self._adam_v, "adam_t": self._adam_t}

    def restore_from_dict(self, state: Dict[str, Any]) -> None:
        self.theta = state["theta"]
        self._adam_m = state["adam_m"]
        self._adam_v = state["adam_v"]
        self._adam_t = state["adam_t"]

    def stop(self) -> None:
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
