"""SampleBatch: the experience container moved between rollout workers and
learners.

Reference parity: rllib/policy/sample_batch.py (SampleBatch, concat_samples).
Columns are numpy arrays with a shared leading dimension; helper methods
cover concatenation, shuffling, and fixed-size minibatch slicing (the shapes
the JAX learner needs are static, so `to_minibatches` pads/truncates to an
exact multiple).
"""

from __future__ import annotations

from typing import Dict, Iterator, List

import numpy as np


class SampleBatch(dict):
    """A dict of columns (numpy arrays) with equal leading dimension."""

    OBS = "obs"
    ACTIONS = "actions"
    REWARDS = "rewards"
    TERMINATEDS = "terminateds"
    TRUNCATEDS = "truncateds"
    ACTION_LOGP = "action_logp"
    ACTION_LOGITS = "action_logits"
    VF_PREDS = "vf_preds"
    ADVANTAGES = "advantages"
    VALUE_TARGETS = "value_targets"

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        for k, v in list(self.items()):
            if not isinstance(v, np.ndarray):
                self[k] = np.asarray(v)

    @property
    def count(self) -> int:
        for v in self.values():
            return len(v)
        return 0

    def __len__(self) -> int:  # len(batch) == row count, as in the reference
        return self.count

    def shuffle(self, rng: np.random.Generator) -> "SampleBatch":
        perm = rng.permutation(self.count)
        return SampleBatch({k: v[perm] for k, v in self.items()})

    def slice(self, start: int, end: int) -> "SampleBatch":
        return SampleBatch({k: v[start:end] for k, v in self.items()})

    def to_minibatches(self, minibatch_size: int) -> Iterator["SampleBatch"]:
        n = (self.count // minibatch_size) * minibatch_size
        for i in range(0, n, minibatch_size):
            yield self.slice(i, i + minibatch_size)

    @staticmethod
    def concat_samples(batches: List["SampleBatch"]) -> "SampleBatch":
        if not batches:
            return SampleBatch()
        keys = batches[0].keys()
        return SampleBatch(
            {k: np.concatenate([b[k] for b in batches], axis=0) for k in keys})

    def size_bytes(self) -> int:
        return sum(v.nbytes for v in self.values())


def compute_gae(rewards: np.ndarray, values: np.ndarray, dones: np.ndarray,
                bootstrap_value: np.ndarray, gamma: float, lam: float):
    """Generalized Advantage Estimation over time-major fragments.

    rewards/values/dones: [T, B]; bootstrap_value: [B] (value of the obs
    after the last step, used when the fragment ends mid-episode).
    Returns (advantages, value_targets), both [T, B].

    Reference behavior: rllib/evaluation/postprocessing.py
    (compute_advantages, use_gae=True).
    """
    T = rewards.shape[0]
    advantages = np.zeros_like(rewards, dtype=np.float32)
    not_done = 1.0 - dones.astype(np.float32)
    next_value = bootstrap_value.astype(np.float32)
    gae = np.zeros_like(next_value)
    for t in range(T - 1, -1, -1):
        delta = rewards[t] + gamma * next_value * not_done[t] - values[t]
        gae = delta + gamma * lam * not_done[t] * gae
        advantages[t] = gae
        next_value = values[t]
    value_targets = advantages + values.astype(np.float32)
    return advantages, value_targets
