"""Policy server + client: RL for environments that live OUTSIDE the
cluster (games, simulators, real systems).

Reference parity: rllib/env/policy_server_input.py (the HTTP server an
external env connects to) + rllib/env/policy_client.py (start_episode /
get_action / log_returns / end_episode).  The server hosts the current
policy for inference, accumulates the episodes the clients drive, and
hands completed experience to the algorithm as SampleBatches — external
envs replace rollout workers as the sample source.

Transport is plain HTTP/JSON over the standard library (urllib client,
http.server on a thread) so external processes need zero dependencies.
GAE postprocessing happens server-side at episode end, matching the
rollout worker's math.
"""

from __future__ import annotations

import json
import threading
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.rllib.policy import JaxPolicy
from ray_tpu.rllib.sample_batch import SampleBatch, compute_gae


class _Episode:
    def __init__(self):
        self.obs: List[np.ndarray] = []
        self.actions: List[int] = []
        self.logp: List[float] = []
        self.vf: List[float] = []
        self.rewards: List[float] = []
        self.total_reward = 0.0


class PolicyServer:
    """Serves actions to external envs; collects their episodes.

    Endpoints (JSON bodies):
      POST /start_episode              -> {episode_id}
      POST /get_action {episode_id, obs}        -> {action}
      POST /log_returns {episode_id, reward}    -> {}
      POST /end_episode {episode_id, obs}       -> {}
    """

    def __init__(self, obs_dim: int, num_actions: int, *,
                 hidden=(64, 64), seed: int = 0, gamma: float = 0.99,
                 lam: float = 0.95, host: str = "127.0.0.1", port: int = 0):
        self.policy = JaxPolicy(obs_dim, num_actions, hidden, seed=seed)
        self.gamma, self.lam = gamma, lam
        self._episodes: Dict[str, _Episode] = {}
        self._completed: List[SampleBatch] = []
        self._returns: List[float] = []
        self._lock = threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(n) or b"{}")
                try:
                    out = outer._dispatch(self.path, body)
                    data = json.dumps(out).encode()
                    self.send_response(200)
                except Exception as e:  # noqa: BLE001
                    data = json.dumps({"error": str(e)}).encode()
                    self.send_response(400)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self.address = f"http://{host}:{self.port}"
        threading.Thread(target=self._httpd.serve_forever, daemon=True,
                         name="policy-server").start()

    # -- protocol ----------------------------------------------------------

    def _dispatch(self, path: str, body: dict) -> dict:
        if path == "/start_episode":
            eid = uuid.uuid4().hex[:12]
            with self._lock:
                self._episodes[eid] = _Episode()
            return {"episode_id": eid}
        eid = body["episode_id"]
        with self._lock:
            ep = self._episodes.get(eid)
        if ep is None:
            raise ValueError(f"unknown episode {eid}")
        if path == "/get_action":
            obs = np.asarray(body["obs"], np.float32)
            a, logp, vf, _ = self.policy.compute_actions(obs[None])
            with self._lock:
                ep.obs.append(obs)
                ep.actions.append(int(a[0]))
                ep.logp.append(float(logp[0]))
                ep.vf.append(float(vf[0]))
            return {"action": int(a[0])}
        if path == "/log_returns":
            with self._lock:
                ep.rewards.append(float(body["reward"]))
                ep.total_reward += float(body["reward"])
            return {}
        if path == "/end_episode":
            with self._lock:
                self._episodes.pop(eid, None)
            self._finish_episode(ep)
            return {}
        raise ValueError(f"unknown endpoint {path}")

    def _finish_episode(self, ep: _Episode) -> None:
        steps = min(len(ep.obs), len(ep.rewards))
        if steps == 0:
            return
        rewards = np.asarray(ep.rewards[:steps], np.float32)[:, None]
        values = np.asarray(ep.vf[:steps], np.float32)[:, None]
        dones = np.zeros((steps, 1), np.float32)
        dones[-1, 0] = 1.0   # episode ended -> no bootstrap past the end
        adv, targets = compute_gae(rewards, values, dones,
                                   np.zeros(1, np.float32),
                                   self.gamma, self.lam)
        batch = SampleBatch({
            SampleBatch.OBS: np.stack(ep.obs[:steps]),
            SampleBatch.ACTIONS: np.asarray(ep.actions[:steps], np.int32),
            SampleBatch.ACTION_LOGP: np.asarray(ep.logp[:steps],
                                                np.float32),
            SampleBatch.VF_PREDS: values[:, 0],
            SampleBatch.ADVANTAGES: adv[:, 0],
            SampleBatch.VALUE_TARGETS: targets[:, 0],
        })
        with self._lock:
            self._completed.append(batch)
            self._returns.append(ep.total_reward)

    # -- training-side API -------------------------------------------------

    def to_sample_batch(self, min_rows: int = 1
                        ) -> Optional[Tuple[SampleBatch, List[float]]]:
        """Drain completed episodes; None until min_rows accumulated."""
        with self._lock:
            rows = sum(b.count for b in self._completed)
            if rows < min_rows:
                return None
            batches, self._completed = self._completed, []
            returns, self._returns = self._returns, []
        return SampleBatch.concat_samples(batches), returns

    def set_weights(self, weights) -> None:
        self.policy.set_weights(weights)

    def get_weights(self):
        return self.policy.get_weights()

    def stop(self) -> None:
        self._httpd.shutdown()


class PolicyClient:
    """External-env side (reference: policy_client.py) — stdlib only, so
    any process can drive training without installing this framework."""

    def __init__(self, address: str, timeout: float = 30.0):
        self.address = address.rstrip("/")
        self.timeout = timeout

    def _post(self, path: str, body: dict) -> dict:
        import urllib.request
        req = urllib.request.Request(
            self.address + path, data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return json.loads(r.read())

    def start_episode(self) -> str:
        return self._post("/start_episode", {})["episode_id"]

    def get_action(self, episode_id: str, obs) -> int:
        return self._post("/get_action", {
            "episode_id": episode_id,
            "obs": np.asarray(obs, np.float32).tolist()})["action"]

    def log_returns(self, episode_id: str, reward: float) -> None:
        self._post("/log_returns", {"episode_id": episode_id,
                                    "reward": float(reward)})

    def end_episode(self, episode_id: str, obs) -> None:
        self._post("/end_episode", {
            "episode_id": episode_id,
            "obs": np.asarray(obs, np.float32).tolist()})
