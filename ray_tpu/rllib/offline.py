"""Offline RL: experience IO + learning from logged data.

Reference parity: rllib/offline/ — JsonWriter/JsonReader (experiences
logged as JSON-lines of SampleBatches, read back for training, optionally
through Ray Data: dataset_reader.py) and the BC/MARWIL family
(rllib/algorithms/bc — supervised policy learning on logged actions).
"""

from __future__ import annotations

import base64
import json
import os
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

from ray_tpu.rllib.sample_batch import SampleBatch


def _encode_array(arr: np.ndarray) -> dict:
    return {"__npy__": base64.b64encode(
        np.ascontiguousarray(arr).tobytes()).decode(),
        "dtype": str(arr.dtype), "shape": list(arr.shape)}


def _decode_array(obj: dict) -> np.ndarray:
    return np.frombuffer(
        base64.b64decode(obj["__npy__"]),
        dtype=np.dtype(obj["dtype"])).reshape(obj["shape"]).copy()


class JsonWriter:
    """Append SampleBatches as JSON lines (reference:
    rllib/offline/json_writer.py)."""

    def __init__(self, path: str, max_file_size: int = 64 << 20):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self._max = max_file_size
        self._index = 0
        self._file = None

    def _rotate(self):
        if self._file is not None:
            self._file.close()
        name = os.path.join(self.path, f"output-{self._index:05d}.json")
        self._index += 1
        self._file = open(name, "a")

    def write(self, batch: SampleBatch) -> None:
        if self._file is None or self._file.tell() > self._max:
            self._rotate()
        record = {k: _encode_array(np.asarray(v)) for k, v in batch.items()}
        self._file.write(json.dumps(record) + "\n")
        self._file.flush()

    def close(self):
        if self._file is not None:
            self._file.close()
            self._file = None


class JsonReader:
    """Iterate SampleBatches back from a JsonWriter directory (reference:
    rllib/offline/json_reader.py)."""

    def __init__(self, path: str):
        self.path = path
        self._files = sorted(
            os.path.join(path, f) for f in os.listdir(path)
            if f.endswith(".json"))
        if not self._files:
            raise ValueError(f"no .json experience files under {path!r}")

    def __iter__(self) -> Iterator[SampleBatch]:
        for fname in self._files:
            with open(fname) as f:
                for line in f:
                    if not line.strip():
                        continue
                    record = json.loads(line)
                    yield SampleBatch({k: _decode_array(v)
                                       for k, v in record.items()})

    def read_all(self) -> SampleBatch:
        return SampleBatch.concat_samples(list(self))

    def to_dataset(self):
        """Experiences as a ray_tpu Dataset (reference:
        offline/dataset_reader.py — offline data flows through Data)."""
        from ray_tpu import data as rdata
        rows: List[Dict[str, Any]] = []
        for batch in self:
            n = batch.count
            for i in range(n):
                rows.append({k: np.asarray(v[i]).tolist()
                             for k, v in batch.items()})
        return rdata.from_items(rows)


class BCConfig:
    """Behavior cloning config (reference: rllib/algorithms/bc)."""

    def __init__(self):
        self.lr = 1e-3
        self.train_batch_size = 256
        self.num_epochs = 1
        self.model_hidden = (64, 64)
        self.seed = 0


class BC:
    """Behavior cloning: supervised max-likelihood on logged actions —
    the offline-RL baseline (reference: bc.py; MARWIL with beta=0)."""

    def __init__(self, obs_dim: int, num_actions: int,
                 config: Optional[BCConfig] = None):
        import jax
        import optax

        from ray_tpu.rllib.models import make_model

        self.config = config or BCConfig()
        cfg = self.config
        init_params, self.apply = make_model(obs_dim, num_actions,
                                             cfg.model_hidden)
        self.params = init_params(jax.random.key(cfg.seed))
        self.tx = optax.adam(cfg.lr)
        self.opt_state = self.tx.init(self.params)
        self._rng = np.random.default_rng(cfg.seed)
        apply = self.apply

        def loss(params, obs, actions):
            import jax.numpy as jnp
            logits, _ = apply(params, obs)
            logp = jax.nn.log_softmax(logits)
            nll = -jnp.take_along_axis(
                logp, actions[:, None].astype(jnp.int32), axis=1)[:, 0]
            return nll.mean()

        def step(params, opt_state, obs, actions):
            l, grads = jax.value_and_grad(loss)(params, obs, actions)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, l

        self._step = jax.jit(step)

    def train_on(self, batch: SampleBatch) -> Dict[str, float]:
        """num_epochs of minibatch SGD over the logged experiences."""
        import jax.numpy as jnp

        cfg = self.config
        obs = np.asarray(batch[SampleBatch.OBS], np.float32)
        actions = np.asarray(batch[SampleBatch.ACTIONS])
        if obs.ndim > 2:  # time-major fragments flatten to rows
            obs = obs.reshape(-1, obs.shape[-1])
            actions = actions.reshape(-1)
        n = len(obs)
        last = 0.0
        for _ in range(cfg.num_epochs):
            perm = self._rng.permutation(n)
            for lo in range(0, n, cfg.train_batch_size):
                idx = perm[lo:lo + cfg.train_batch_size]
                self.params, self.opt_state, last = self._step(
                    self.params, self.opt_state,
                    jnp.asarray(obs[idx]), jnp.asarray(actions[idx]))
        return {"bc_loss": float(last), "samples": n}

    def compute_actions(self, obs: np.ndarray) -> np.ndarray:
        import jax.numpy as jnp
        logits, _ = self.apply(self.params, jnp.asarray(obs, jnp.float32))
        return np.asarray(jnp.argmax(logits, axis=-1))

    def get_weights(self):
        import jax
        return jax.device_get(self.params)


# ---------------------------------------------------------------------------
# Data-native experience IO (reference: rllib/offline/dataset_reader.py —
# offline data flows through the Data layer: Parquet files, parallel block
# reads, streaming batches into the learner instead of one monolithic
# in-memory SampleBatch).
# ---------------------------------------------------------------------------


class ParquetWriter:
    """Append SampleBatches as Parquet files — the Data-native experience
    format (columnar, compressed, parallel-readable).  Multi-dim columns
    (observations) are stored as nested lists; shapes reconstruct on
    read."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self._index = 0

    def write(self, batch: SampleBatch) -> None:
        import pyarrow as pa
        import pyarrow.parquet as pq
        cols = {}
        for k, v in batch.items():
            arr = np.asarray(v)
            cols[k] = (arr.tolist() if arr.ndim > 1 else arr)
        table = pa.table(cols)
        pq.write_table(table, os.path.join(
            self.path, f"part-{self._index:05d}.parquet"))
        self._index += 1

    def close(self) -> None:
        pass


def _numpy_batch_to_sample(batch: Dict[str, Any]) -> SampleBatch:
    out = {}
    for k, v in batch.items():
        arr = np.asarray(v)
        if arr.dtype == object:          # nested-list column -> ndarray
            arr = np.asarray([np.asarray(x) for x in v])
        out[k] = arr
    return SampleBatch(out)


class DatasetReader:
    """Stream SampleBatches out of a `ray_tpu.data` Dataset (reference:
    offline/dataset_reader.py).  Blocks are read by data-plane tasks in
    parallel and flow through iter_batches with prefetch — the learner
    never materializes the whole log."""

    def __init__(self, dataset, batch_size: int = 1024):
        self._ds = dataset
        self._batch_size = batch_size

    @classmethod
    def from_path(cls, path: str, batch_size: int = 1024) -> "DatasetReader":
        from ray_tpu import data as rdata
        return cls(rdata.read_parquet(path), batch_size)

    def __iter__(self) -> Iterator[SampleBatch]:
        for b in self._ds.iter_batches(batch_size=self._batch_size,
                                       batch_format="numpy"):
            yield _numpy_batch_to_sample(b)

    def read_all(self) -> SampleBatch:
        return SampleBatch.concat_samples(list(self))
