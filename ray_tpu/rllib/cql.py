"""CQL: Conservative Q-Learning for offline RL (Kumar et al. 2020).

Reference parity: rllib/algorithms/cql/ (cql.py extends SAC with the
conservative regularizer; cql_torch_policy.py adds
alpha * E[ logsumexp_a Q(s,a) - Q(s, a_logged) ] to the critic loss).
Here the discrete-action form is implemented over single-Q TD with a
target-network max — i.e. DQN-style bootstrapping, not double-Q
decoupling of argmax and evaluation (the CQL(H) objective, eq. 4 of the
paper, whose inner max has the closed logsumexp form for finite action
sets — no OOD action sampler needed).  The conservative term pushes down Q on actions the
behavior policy never logged, so the greedy policy stays inside the
data's support — the property the offline setting needs and plain TD
lacks.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ray_tpu.rllib.sample_batch import SampleBatch


class CQLConfig:
    def __init__(self):
        self.cql_alpha = 1.0       # conservative penalty weight (0 = TD)
        self.gamma = 0.99
        self.lr = 5e-4
        self.train_batch_size = 256
        self.num_epochs = 1
        self.target_update_interval = 50   # jitted-step count
        self.model_hidden = (64, 64)
        self.seed = 0


class CQL:
    """Offline trainer over logged transitions (obs, action, reward,
    next_obs via the following row, done)."""

    def __init__(self, obs_dim: int, num_actions: int,
                 config: Optional[CQLConfig] = None):
        import jax
        import optax

        from ray_tpu.rllib.models import make_model

        self.config = config or CQLConfig()
        self.num_actions = num_actions
        cfg = self.config
        # Q-network: reuse the actor-critic trunk, logits head = Q values.
        init_params, self.apply = make_model(obs_dim, num_actions,
                                             cfg.model_hidden)
        self.params = init_params(jax.random.key(cfg.seed))
        self.target_params = jax.tree_util.tree_map(lambda x: x,
                                                    self.params)
        self.tx = optax.adam(cfg.lr)
        self.opt_state = self.tx.init(self.params)
        self._rng = np.random.default_rng(cfg.seed)
        self._steps = 0
        apply = self.apply
        gamma, alpha = cfg.gamma, cfg.cql_alpha

        def loss(params, target_params, obs, actions, rewards, next_obs,
                 dones):
            import jax.numpy as jnp
            q, _ = apply(params, obs)                       # [B, A]
            q_a = jnp.take_along_axis(
                q, actions[:, None].astype(jnp.int32), axis=1)[:, 0]
            q_next, _ = apply(target_params, next_obs)
            target = rewards + gamma * (1.0 - dones) * q_next.max(-1)
            td = ((q_a - jax.lax.stop_gradient(target)) ** 2).mean()
            # CQL(H) regularizer: logsumexp over ALL actions minus the
            # logged action's Q — minimized when out-of-support actions
            # score below the data's.
            conservative = (jax.scipy.special.logsumexp(q, axis=-1)
                            - q_a).mean()
            return td + alpha * conservative, (td, conservative)

        def step(params, target_params, opt_state, obs, actions, rewards,
                 next_obs, dones):
            (l, aux), grads = jax.value_and_grad(loss, has_aux=True)(
                params, target_params, obs, actions, rewards, next_obs,
                dones)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, l, aux

        self._step = jax.jit(step)

    def train_on(self, batch: SampleBatch) -> Dict[str, float]:
        """Run `num_epochs` of minibatch CQL updates over a logged batch.

        Input contract: rows are TIME-ORDERED transitions, episodes laid
        out back to back, with done flags (terminateds|truncateds) marking
        each episode's last row.  next_obs for row t is row t+1's obs —
        valid precisely because a done row's bootstrap target is masked by
        `(1 - dones)`, so the cross-episode splice at each boundary is
        never read.  Shuffled or subsampled logs violate the contract and
        must carry an explicit "next_obs" column instead.
        """
        import jax.numpy as jnp

        cfg = self.config
        obs = np.asarray(batch[SampleBatch.OBS], np.float32)
        actions = np.asarray(batch[SampleBatch.ACTIONS])
        rewards = np.asarray(batch[SampleBatch.REWARDS], np.float32)
        if len(obs) == 0:
            raise ValueError("CQL.train_on: empty batch")
        if not (len(actions) == len(rewards) == len(obs)):
            raise ValueError(
                "CQL.train_on: ragged batch (obs/actions/rewards rows "
                f"{len(obs)}/{len(actions)}/{len(rewards)})")
        term = np.asarray(batch.get(SampleBatch.TERMINATEDS,
                                    np.zeros(len(obs))), bool)
        trunc = np.asarray(batch.get(SampleBatch.TRUNCATEDS,
                                     np.zeros(len(obs))), bool)
        dones = (term | trunc)
        if "next_obs" in batch:
            # Explicit column: no ordering assumption needed.
            next_obs = np.asarray(batch["next_obs"], np.float32)
            if len(next_obs) != len(obs):
                raise ValueError("CQL.train_on: next_obs rows "
                                 f"{len(next_obs)} != obs rows {len(obs)}")
        else:
            # next_obs = following row inside an episode; a done row
            # bootstraps nothing so its next_obs is arbitrary (masked).
            next_obs = np.concatenate([obs[1:], obs[-1:]], 0)
            dones[-1] = True   # the log's tail cannot bootstrap
        n = len(obs)
        last = {}
        for _ in range(cfg.num_epochs):
            perm = self._rng.permutation(n)
            for lo in range(0, n, cfg.train_batch_size):
                idx = perm[lo:lo + cfg.train_batch_size]
                self.params, self.opt_state, l, aux = self._step(
                    self.params, self.target_params, self.opt_state,
                    jnp.asarray(obs[idx]), jnp.asarray(actions[idx]),
                    jnp.asarray(rewards[idx]), jnp.asarray(next_obs[idx]),
                    jnp.asarray(dones[idx], jnp.float32))
                self._steps += 1
                if self._steps % cfg.target_update_interval == 0:
                    self.target_params = self.params
                td, conservative = aux
                last = {"total_loss": float(l), "td_loss": float(td),
                        "cql_loss": float(conservative)}
        last["samples"] = n
        return last

    def q_values(self, obs: np.ndarray) -> np.ndarray:
        import jax.numpy as jnp
        q, _ = self.apply(self.params, jnp.asarray(obs, jnp.float32))
        return np.asarray(q)

    def compute_actions(self, obs: np.ndarray) -> np.ndarray:
        return self.q_values(obs).argmax(-1)

    def get_weights(self):
        import jax
        return jax.device_get(self.params)
