"""Algorithm: the RL training driver, runnable standalone or under Tune.

Reference parity: rllib/algorithms/algorithm.py:149 (extends tune.Trainable;
setup:503, step:754, evaluate:847, save/restore) and
rllib/algorithms/algorithm_config.py (typed fluent config).
"""

from __future__ import annotations

import collections
import time
from typing import Any, Dict, Optional

import numpy as np

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.rllib.env import make_vector_env
from ray_tpu.rllib.worker_set import WorkerSet


class AlgorithmConfig:
    """Fluent config.  Reference: algorithm_config.py
    (.environment().rollouts().training().resources())."""

    def __init__(self, algo_class=None):
        self.algo_class = algo_class
        self.env: Any = "CartPole-v1"
        self.num_rollout_workers = 2
        self.num_envs_per_worker = 8
        self.rollout_fragment_length = 64
        self.num_cpus_per_worker = 1.0
        self.gamma = 0.99
        self.lambda_ = 0.95
        self.lr = 3e-4
        self.grad_clip = 0.5
        self.train_batch_size = 1024
        self.sgd_minibatch_size = 128
        self.num_sgd_iter = 8
        self.model_hidden = (64, 64)
        # Recurrent model (reference: model config use_lstm/lstm_cell_size
        # + max_seq_len; here the rollout fragment IS the training chunk).
        self.use_lstm = False
        self.lstm_size = 64
        self.seed = 0
        # Data-parallel learner group: a jax Mesh whose "data" axis spans
        # the learner chips (reference: LearnerGroup learner_group.py:51).
        self.learner_mesh: Any = None
        # Multi-agent (reference: algorithm_config.py .multi_agent):
        # policies = iterable of policy ids (None = single-agent);
        # policy_mapping_fn: agent_id -> policy_id (default: identity).
        self.policies: Any = None
        self.policy_mapping_fn: Any = None
        self.extra: Dict[str, Any] = {}

    # fluent setters ------------------------------------------------------
    def environment(self, env) -> "AlgorithmConfig":
        self.env = env
        return self

    def rollouts(self, *, num_rollout_workers: Optional[int] = None,
                 num_envs_per_worker: Optional[int] = None,
                 rollout_fragment_length: Optional[int] = None
                 ) -> "AlgorithmConfig":
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        if num_envs_per_worker is not None:
            self.num_envs_per_worker = num_envs_per_worker
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kwargs) -> "AlgorithmConfig":
        for k, v in kwargs.items():
            if hasattr(self, k):
                setattr(self, k, v)
            else:
                self.extra[k] = v
        return self

    def resources(self, *, num_cpus_per_worker: Optional[float] = None,
                  learner_mesh: Any = None) -> "AlgorithmConfig":
        if num_cpus_per_worker is not None:
            self.num_cpus_per_worker = num_cpus_per_worker
        if learner_mesh is not None:
            self.learner_mesh = learner_mesh
        return self

    def multi_agent(self, *, policies, policy_mapping_fn=None
                    ) -> "AlgorithmConfig":
        """Declare the policy map (reference: algorithm_config.py
        .multi_agent(policies=..., policy_mapping_fn=...))."""
        self.policies = list(policies)
        self.policy_mapping_fn = policy_mapping_fn
        return self

    def debugging(self, *, seed: Optional[int] = None) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        return self

    def to_dict(self) -> Dict[str, Any]:
        d = {k: v for k, v in self.__dict__.items()
             if k not in ("algo_class", "extra", "learner_mesh",
                          "policy_mapping_fn")}
        d.update(self.extra)
        return d

    def build(self) -> "Algorithm":
        if self.algo_class is None:
            raise ValueError("config has no algo_class; use PPOConfig() etc.")
        return self.algo_class(self)


class Algorithm:
    """Base RL driver: owns a WorkerSet + learner; .train() = one iteration."""

    def __init__(self, config: AlgorithmConfig):
        self.config = config
        self.multi_agent = config.policies is not None
        if self.multi_agent:
            from ray_tpu.rllib.multi_agent import make_multi_agent_env
            probe = make_multi_agent_env(config.env, 1, seed=config.seed)
            mapping = config.policy_mapping_fn or (lambda aid: aid)
            # Per-policy model sizing from the agents each policy serves.
            self.policy_specs: Dict[str, tuple] = {}
            for a in probe.agent_ids:
                pid = mapping(a)
                self.policy_specs[pid] = (probe.observation_dims[a],
                                          probe.num_actions_by_agent[a])
            self.obs_dim = self.num_actions = self.action_dim = 0
            self.continuous = False
        else:
            # Probe the env spec once, locally, to size the model.
            probe = make_vector_env(config.env, 1, seed=config.seed)
            self.obs_dim = probe.observation_dim
            self.num_actions = probe.num_actions
            self.action_dim = getattr(probe, "action_dim", 0)
            self.continuous = self.num_actions == 0 and self.action_dim > 0
        self.iteration = 0
        self.total_env_steps = 0
        self._episode_returns: collections.deque = collections.deque(
            maxlen=100)
        self._episode_lengths: collections.deque = collections.deque(
            maxlen=100)
        self._start = time.time()
        self.setup()

    # -- subclass hooks ----------------------------------------------------
    def setup(self) -> None:
        raise NotImplementedError

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    # -- public ------------------------------------------------------------
    def train(self) -> Dict[str, Any]:
        """One training iteration.  Reference: Algorithm.step:754."""
        result = self.training_step()
        self.iteration += 1
        rets = list(self._episode_returns)
        result.update({
            "training_iteration": self.iteration,
            "timesteps_total": self.total_env_steps,
            "episode_reward_mean": float(np.mean(rets)) if rets else np.nan,
            "episode_reward_max": float(np.max(rets)) if rets else np.nan,
            "episode_reward_min": float(np.min(rets)) if rets else np.nan,
            "episode_len_mean": (float(np.mean(self._episode_lengths))
                                 if self._episode_lengths else np.nan),
            "episodes_this_iter": result.get("episodes_this_iter", 0),
            "time_total_s": time.time() - self._start,
        })
        return result

    def _record_metrics(self, metrics_list) -> int:
        """Fold worker sample metrics into the running episode window."""
        episodes = 0
        for m in metrics_list:
            self._episode_returns.extend(m.get("episode_returns", []))
            self._episode_lengths.extend(m.get("episode_lengths", []))
            episodes += len(m.get("episode_returns", []))
            self.total_env_steps += m.get("env_steps", 0)
        return episodes

    def save_to_dict(self) -> Dict[str, Any]:
        raise NotImplementedError

    def restore_from_dict(self, state: Dict[str, Any]) -> None:
        raise NotImplementedError

    def save(self) -> Checkpoint:
        """Reference: Algorithm.save / rllib/utils/checkpoints.py."""
        state = self.save_to_dict()
        state["iteration"] = self.iteration
        state["total_env_steps"] = self.total_env_steps
        return Checkpoint.from_dict(state)

    def restore(self, checkpoint: Checkpoint) -> None:
        state = checkpoint.to_dict()
        self.iteration = state.get("iteration", 0)
        self.total_env_steps = state.get("total_env_steps", 0)
        self.restore_from_dict(state)

    def stop(self) -> None:
        if getattr(self, "workers", None) is not None:
            self.workers.stop()

    # -- Tune integration --------------------------------------------------
    @classmethod
    def as_trainable(cls, config: AlgorithmConfig, *,
                     stop_iters: int = 1000,
                     stop_reward: Optional[float] = None):
        """Wrap into a Tune function trainable.

        Reference: Algorithm IS a tune.Trainable (algorithm.py:149); here
        Tune's unit is a session function, so the adapter loops train() and
        reports each iteration.
        """
        from ray_tpu import tune

        def _trainable(tune_config: Dict[str, Any]):
            cfg = config
            for k, v in (tune_config or {}).items():
                if hasattr(cfg, k):
                    setattr(cfg, k, v)
            algo = cls(cfg)
            try:
                for _ in range(stop_iters):
                    result = algo.train()
                    tune.report(result)
                    if (stop_reward is not None
                            and result["episode_reward_mean"] >= stop_reward):
                        break
            finally:
                algo.stop()
        return _trainable
