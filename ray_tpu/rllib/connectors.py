"""Connectors: composable transforms between env and policy.

Reference parity: rllib/connectors/ — agent connectors transform
observations on the way INTO the policy (connectors/agent/), action
connectors transform the policy's output on the way OUT
(connectors/action/), assembled into pipelines that travel with the
policy so serving and training preprocess identically.  Vectorized:
every transform is one numpy op over the env batch.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np


class Connector:
    def __call__(self, x: np.ndarray) -> np.ndarray:
        raise NotImplementedError


class ConnectorPipeline(Connector):
    """Ordered composition (reference: connectors/connector_pipeline_v2)."""

    def __init__(self, connectors: Optional[List[Connector]] = None):
        self.connectors = list(connectors or [])

    def append(self, c: Connector) -> "ConnectorPipeline":
        self.connectors.append(c)
        return self

    def __call__(self, x):
        for c in self.connectors:
            x = c(x)
        return x


# -- agent (observation) connectors ----------------------------------------

class FlattenObs(Connector):
    """[B, ...] -> [B, prod(...)] (reference: FlattenObservations)."""

    def __call__(self, obs):
        obs = np.asarray(obs)
        return obs.reshape(obs.shape[0], -1)


class NormalizeObs(Connector):
    """Running mean/std observation filter (reference: MeanStdFilter,
    rllib/utils/filter.py) with Welford updates over env batches."""

    def __init__(self, clip: float = 10.0, update: bool = True):
        self.clip = clip
        self.update = update
        self._count = 1e-4
        self._mean: Optional[np.ndarray] = None
        self._m2: Optional[np.ndarray] = None

    def __call__(self, obs):
        obs = np.asarray(obs, np.float64)
        if self._mean is None:
            self._mean = np.zeros(obs.shape[1:])
            self._m2 = np.ones(obs.shape[1:])
        if self.update:
            b = len(obs)
            bmean = obs.mean(0)
            bvar = obs.var(0)
            delta = bmean - self._mean
            tot = self._count + b
            self._mean = self._mean + delta * b / tot
            self._m2 = (self._m2 * self._count + bvar * b
                        + delta ** 2 * self._count * b / tot)
            self._m2 /= tot
            self._count = tot
        std = np.sqrt(self._m2) + 1e-8
        out = (obs - self._mean) / std
        return np.clip(out, -self.clip, self.clip).astype(np.float32)

    # Filters travel with weights so remote workers normalize identically.
    def get_state(self):
        return {"count": self._count, "mean": self._mean, "m2": self._m2}

    def set_state(self, st):
        self._count = st["count"]
        self._mean = st["mean"]
        self._m2 = st["m2"]


class ClipObs(Connector):
    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def __call__(self, obs):
        return np.clip(obs, self.low, self.high)


# -- action connectors ------------------------------------------------------

class ClipActions(Connector):
    """Clip continuous actions to env bounds (reference:
    connectors/action/ clip_actions — the env must never see
    out-of-range samples even though training stores the raw ones)."""

    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def __call__(self, actions):
        return np.clip(actions, self.low, self.high)


class UnsquashActions(Connector):
    """[-1, 1] policy output -> env bounds (reference: unsquash_actions)."""

    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def __call__(self, actions):
        a = np.asarray(actions)
        return self.low + (a + 1.0) * 0.5 * (self.high - self.low)
