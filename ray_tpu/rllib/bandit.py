"""Contextual bandits: LinUCB and Linear Thompson Sampling.

Reference parity: rllib/algorithms/bandit/ (bandit_linucb.py,
bandit_lints.py over discrete-action contextual envs).  The linear
models are the closed-form disjoint estimators (Li et al. 2010 for
LinUCB; Agrawal & Goyal 2013 for LinTS) — per-arm ridge regression
A_a = lambda*I + sum x x^T, b_a = sum r x, with arm choice by UCB
(theta.x + alpha*sqrt(x A^-1 x)) or by posterior sampling
(theta ~ N(A^-1 b, v^2 A^-1)).

Bandits are ONLINE, cheap, and driver-local (no worker fleet) — the
batch of contexts steps through a VectorEnv whose every step is a
terminal one-step episode, so the Algorithm-base metrics surface
(episode_reward_mean) is the per-decision reward.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.env import VectorEnv, make_vector_env, register_env


class LinearBanditVector(VectorEnv):
    """Synthetic contextual bandit: context x ~ U[-1,1]^d, arm a's
    expected reward = theta_a . x (+ Gaussian noise); every step is a
    one-step episode.  The optimal arm depends on the context, so a
    non-contextual strategy cannot win."""

    observation_dim = 4
    num_actions = 3
    NOISE = 0.05

    def __init__(self, num_envs: int, seed: int = 0):
        super().__init__(num_envs)
        self._rng = np.random.default_rng(seed)
        d, k = self.observation_dim, self.num_actions
        # Fixed arm parameters (drawn once from the env seed).
        self.theta = np.random.default_rng(1234).standard_normal((k, d))
        self._ctx = np.zeros((num_envs, d), np.float32)

    def _draw(self):
        self._ctx = self._rng.uniform(
            -1, 1, (self.num_envs, self.observation_dim)).astype(np.float32)

    def reset_all(self, seed=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._draw()
        return self._ctx.copy()

    def expected_rewards(self) -> np.ndarray:
        """[n, k] expected reward per arm for the CURRENT contexts
        (oracle surface for regret measurement in tests)."""
        return self._ctx @ self.theta.T

    def step_batch(self, actions):
        exp = self.expected_rewards()
        rew = (exp[np.arange(self.num_envs), actions]
               + self.NOISE * self._rng.standard_normal(self.num_envs))
        term = np.ones(self.num_envs, bool)
        self._draw()                      # auto-reset: next contexts
        return self._ctx.copy(), rew, term, np.zeros(self.num_envs, bool)


register_env("LinearBandit-v0", LinearBanditVector)


class _LinearModel:
    """Per-arm ridge state with rank-1-maintained inverse."""

    def __init__(self, n_arms: int, dim: int, lam: float = 1.0):
        self.n_arms, self.dim = n_arms, dim
        self.A_inv = np.stack([np.eye(dim) / lam for _ in range(n_arms)])
        self.b = np.zeros((n_arms, dim))

    def theta(self) -> np.ndarray:                       # [k, d]
        return np.einsum("kij,kj->ki", self.A_inv, self.b)

    def update(self, arms: np.ndarray, xs: np.ndarray, rs: np.ndarray):
        for a, x, r in zip(arms, xs, rs):
            Ai = self.A_inv[a]
            Aix = Ai @ x
            # Sherman-Morrison: (A + x x^T)^-1
            self.A_inv[a] = Ai - np.outer(Aix, Aix) / (1.0 + x @ Aix)
            self.b[a] += r * x


class LinUCBConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=LinUCB)
        self.env = "LinearBandit-v0"
        self.num_envs_per_worker = 16
        self.steps_per_iteration = 8
        self.alpha = 1.0       # exploration bonus scale
        self.lambda_reg = 1.0


class LinUCB(Algorithm):
    """Disjoint LinUCB (Li et al. 2010, Algorithm 1)."""

    def setup(self) -> None:
        cfg = self.config
        self.env = make_vector_env(cfg.env, cfg.num_envs_per_worker,
                                   seed=cfg.seed)
        self.model = _LinearModel(self.num_actions, self.obs_dim,
                                  getattr(cfg, "lambda_reg", 1.0))
        self._obs = self.env.reset_all(seed=cfg.seed)
        self.workers = None

    def _choose(self, obs: np.ndarray) -> np.ndarray:
        theta = self.model.theta()                        # [k, d]
        mean = obs @ theta.T                              # [n, k]
        # sqrt(x^T A_a^-1 x) for every (context, arm):
        var = np.einsum("ni,kij,nj->nk", obs, self.model.A_inv, obs)
        return (mean + self.config.alpha * np.sqrt(np.maximum(var, 0))
                ).argmax(-1)

    def training_step(self) -> Dict[str, Any]:
        rewards = []
        for _ in range(self.config.steps_per_iteration):
            arms = self._choose(self._obs)
            obs, rew, term, trunc = self.env.step(arms)
            self.model.update(arms, self._obs.astype(np.float64), rew)
            rewards.append(rew)
            self._obs = obs
        rets, lens = self.env.drain_episode_metrics()
        self._episode_returns.extend(rets)
        self._episode_lengths.extend(lens)
        n = sum(len(r) for r in rewards)
        self.total_env_steps += n
        return {"episodes_this_iter": len(rets),
                "mean_reward": float(np.concatenate(rewards).mean())}

    def compute_actions(self, obs: np.ndarray) -> np.ndarray:
        return self._choose(np.atleast_2d(obs))

    def save_to_dict(self) -> Dict[str, Any]:
        return {"A_inv": self.model.A_inv, "b": self.model.b}

    def restore_from_dict(self, state: Dict[str, Any]) -> None:
        self.model.A_inv = state["A_inv"]
        self.model.b = state["b"]


class LinTSConfig(LinUCBConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = LinTS
        self.posterior_scale = 0.3   # v: posterior stddev multiplier


class LinTS(LinUCB):
    """Linear Thompson Sampling (Agrawal & Goyal 2013): choose the arm
    maximizing x . theta_tilde with theta_tilde ~ N(theta_a, v^2 A_a^-1)
    per arm."""

    def setup(self) -> None:
        super().setup()
        self._ts_rng = np.random.default_rng(self.config.seed + 99)

    def _choose(self, obs: np.ndarray) -> np.ndarray:
        v = self.config.posterior_scale
        theta = self.model.theta()
        sampled = np.stack([
            self._ts_rng.multivariate_normal(
                theta[a], v * v * self.model.A_inv[a])
            for a in range(self.model.n_arms)])           # [k, d]
        return (obs @ sampled.T).argmax(-1)
