"""Environment abstractions for the RL stack.

Reference parity: rllib/env/ (BaseEnv, VectorEnv, gym registration).  The
reference delegates env implementations to OpenAI gym; this image has no
gym, so classic-control environments are implemented here natively — and
natively *vectorized*: a VectorEnv steps all sub-environments in one batched
numpy computation rather than looping Python-per-env (the TPU-first analogue
of rllib/env/vector_env.py:VectorEnvWrapper, which loops).

The single-env protocol mirrors the gymnasium 5-tuple step API so user envs
written against gymnasium drop in unchanged.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np


class Env:
    """Minimal gymnasium-style environment protocol.

    reset(seed) -> (obs, info); step(a) -> (obs, reward, terminated,
    truncated, info).  Discrete envs declare num_actions; continuous envs
    declare action_dim (+ action_low/high) and set num_actions = 0.
    """

    observation_dim: int
    num_actions: int = 0          # discrete action count (0 = continuous)
    action_dim: int = 0           # continuous action dimension
    action_low: float = -1.0
    action_high: float = 1.0

    def reset(self, seed: Optional[int] = None) -> Tuple[np.ndarray, dict]:
        raise NotImplementedError

    def step(self, action) -> Tuple[np.ndarray, float, bool, bool, dict]:
        raise NotImplementedError


class VectorEnv:
    """Batched environment: steps N environments as one numpy computation.

    Auto-resets finished sub-environments (obs returned for a done step is
    the *reset* observation, as in gymnasium's AutoResetWrapper) and tracks
    completed-episode returns/lengths for metrics.
    """

    def __init__(self, num_envs: int):
        self.num_envs = num_envs
        self._ep_return = np.zeros(num_envs, np.float64)
        self._ep_len = np.zeros(num_envs, np.int64)
        self.completed_returns: list = []
        self.completed_lengths: list = []

    # -- subclass interface ------------------------------------------------
    def reset_all(self, seed: Optional[int] = None) -> np.ndarray:
        raise NotImplementedError

    def step_batch(self, actions: np.ndarray
                   ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Step every env; returns (obs, rewards, terminated, truncated).

        Implementations must auto-reset done envs internally.
        """
        raise NotImplementedError

    # -- common bookkeeping ------------------------------------------------
    def step(self, actions: np.ndarray):
        obs, rew, term, trunc = self.step_batch(np.asarray(actions))
        self._ep_return += rew
        self._ep_len += 1
        done = term | trunc
        if done.any():
            for i in np.nonzero(done)[0]:
                self.completed_returns.append(float(self._ep_return[i]))
                self.completed_lengths.append(int(self._ep_len[i]))
            self._ep_return[done] = 0.0
            self._ep_len[done] = 0
        return obs, rew, term, trunc

    def drain_episode_metrics(self) -> Tuple[list, list]:
        rets, lens = self.completed_returns, self.completed_lengths
        self.completed_returns, self.completed_lengths = [], []
        return rets, lens


class CartPoleVector(VectorEnv):
    """Vectorized CartPole-v1 (classic control, standard published dynamics).

    Physics constants and termination bounds are the classic cart-pole
    control problem (Barto/Sutton/Anderson 1983) as standardized by the
    CartPole-v1 task: episode caps at 500 steps, reward 1.0 per step.
    """

    observation_dim = 4
    num_actions = 2

    GRAVITY = 9.8
    MASSCART = 1.0
    MASSPOLE = 0.1
    LENGTH = 0.5          # half pole length
    FORCE_MAG = 10.0
    TAU = 0.02
    THETA_THRESHOLD = 12 * 2 * np.pi / 360
    X_THRESHOLD = 2.4
    MAX_STEPS = 500

    def __init__(self, num_envs: int, seed: int = 0):
        super().__init__(num_envs)
        self._rng = np.random.default_rng(seed)
        self._state = np.zeros((num_envs, 4), np.float64)
        self._steps = np.zeros(num_envs, np.int64)

    def _sample_initial(self, n: int) -> np.ndarray:
        return self._rng.uniform(-0.05, 0.05, size=(n, 4))

    def reset_all(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._state = self._sample_initial(self.num_envs)
        self._steps[:] = 0
        self._ep_return[:] = 0.0
        self._ep_len[:] = 0
        return self._state.astype(np.float32)

    def step_batch(self, actions: np.ndarray):
        x, x_dot, theta, theta_dot = self._state.T
        force = np.where(actions == 1, self.FORCE_MAG, -self.FORCE_MAG)
        costheta, sintheta = np.cos(theta), np.sin(theta)
        total_mass = self.MASSCART + self.MASSPOLE
        polemass_length = self.MASSPOLE * self.LENGTH

        temp = (force + polemass_length * theta_dot**2 * sintheta) / total_mass
        thetaacc = (self.GRAVITY * sintheta - costheta * temp) / (
            self.LENGTH * (4.0 / 3.0 - self.MASSPOLE * costheta**2 / total_mass))
        xacc = temp - polemass_length * thetaacc * costheta / total_mass

        x = x + self.TAU * x_dot
        x_dot = x_dot + self.TAU * xacc
        theta = theta + self.TAU * theta_dot
        theta_dot = theta_dot + self.TAU * thetaacc
        self._state = np.stack([x, x_dot, theta, theta_dot], axis=1)
        self._steps += 1

        terminated = (np.abs(x) > self.X_THRESHOLD) | (
            np.abs(theta) > self.THETA_THRESHOLD)
        truncated = (~terminated) & (self._steps >= self.MAX_STEPS)
        rewards = np.ones(self.num_envs, np.float32)

        done = terminated | truncated
        if done.any():
            n = int(done.sum())
            self._state[done] = self._sample_initial(n)
            self._steps[done] = 0
        return (self._state.astype(np.float32), rewards, terminated, truncated)


class PendulumVector(VectorEnv):
    """Vectorized Pendulum-v1 (classic continuous control: swing-up with
    bounded torque; standard published dynamics/reward).  Episodes
    truncate at 200 steps; reward = -(theta^2 + 0.1*thetadot^2 +
    0.001*torque^2)."""

    observation_dim = 3
    num_actions = 0
    action_dim = 1
    action_low = -2.0
    action_high = 2.0

    MAX_SPEED = 8.0
    MAX_TORQUE = 2.0
    DT = 0.05
    G = 10.0
    M = 1.0
    L = 1.0
    MAX_STEPS = 200

    def __init__(self, num_envs: int, seed: int = 0):
        super().__init__(num_envs)
        self._rng = np.random.default_rng(seed)
        self._theta = np.zeros(num_envs)
        self._thetadot = np.zeros(num_envs)
        self._steps = np.zeros(num_envs, np.int64)

    def _obs(self) -> np.ndarray:
        return np.stack([np.cos(self._theta), np.sin(self._theta),
                         self._thetadot], axis=1).astype(np.float32)

    def reset_all(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._theta = self._rng.uniform(-np.pi, np.pi, self.num_envs)
        self._thetadot = self._rng.uniform(-1.0, 1.0, self.num_envs)
        self._steps[:] = 0
        self._ep_return[:] = 0.0
        self._ep_len[:] = 0
        return self._obs()

    def step_batch(self, actions: np.ndarray):
        u = np.clip(np.asarray(actions, np.float64).reshape(self.num_envs),
                    -self.MAX_TORQUE, self.MAX_TORQUE)
        th, thdot = self._theta, self._thetadot
        norm_th = ((th + np.pi) % (2 * np.pi)) - np.pi
        costs = norm_th ** 2 + 0.1 * thdot ** 2 + 0.001 * u ** 2
        newthdot = thdot + (3 * self.G / (2 * self.L) * np.sin(th)
                            + 3.0 / (self.M * self.L ** 2) * u) * self.DT
        newthdot = np.clip(newthdot, -self.MAX_SPEED, self.MAX_SPEED)
        self._theta = th + newthdot * self.DT
        self._thetadot = newthdot
        self._steps += 1
        truncated = self._steps >= self.MAX_STEPS
        terminated = np.zeros(self.num_envs, bool)
        if truncated.any():
            n = int(truncated.sum())
            self._theta[truncated] = self._rng.uniform(-np.pi, np.pi, n)
            self._thetadot[truncated] = self._rng.uniform(-1.0, 1.0, n)
            self._steps[truncated] = 0
        return (self._obs(), (-costs).astype(np.float32), terminated,
                truncated)


class SyntheticPixelVector(VectorEnv):
    """Synthetic [84, 84, 4]-observation env at Atari frame shapes.

    Stands in for gym Atari (not in this image) wherever the QUESTION is
    pixel-pipeline throughput and conv-policy plumbing rather than game
    dynamics (reference: tuned_examples' Atari configs; VERDICT r2 weak 7).
    A bright 8x8 patch moves over a fixed textured background; the agent
    is rewarded for naming the patch's quadrant (4 actions), so policies
    CAN learn signal from pixels, while obs generation stays cheap enough
    (one tile overlay per step) that the framework, not numpy, is what a
    throughput run measures.  uint8 observations end to end — buffers and
    transport move 1 byte/px; the conv net scales to [0,1] on device.
    """

    observation_dim = (84, 84, 4)
    num_actions = 4
    MAX_STEPS = 128
    PATCH = 8

    def __init__(self, num_envs: int, seed: int = 0):
        super().__init__(num_envs)
        self._rng = np.random.default_rng(seed)
        # One shared textured background (fixed; regenerating 84*84*4*B
        # pixels per step would benchmark numpy instead of the runtime).
        self._bg = self._rng.integers(
            0, 64, size=(84, 84, 4), dtype=np.uint8)
        self._pos = np.zeros((num_envs, 2), np.int64)
        self._steps = np.zeros(num_envs, np.int64)

    def _roll_pos(self, mask=None):
        fresh = self._rng.integers(0, 84 - self.PATCH,
                                   size=(self.num_envs, 2))
        if mask is None:
            self._pos = fresh
        else:
            self._pos = np.where(mask[:, None], fresh, self._pos)

    def _obs(self) -> np.ndarray:
        obs = np.broadcast_to(
            self._bg, (self.num_envs, 84, 84, 4)).copy()
        p = self.PATCH
        for i in range(self.num_envs):   # p*p*4 writes per env, cheap
            y, x = self._pos[i]
            obs[i, y:y + p, x:x + p, :] = 255
        return obs

    def _quadrant(self) -> np.ndarray:
        cy = (self._pos[:, 0] + self.PATCH // 2) >= 42
        cx = (self._pos[:, 1] + self.PATCH // 2) >= 42
        return (cy.astype(np.int64) * 2 + cx.astype(np.int64))

    def reset_all(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._roll_pos()
        self._steps[:] = 0
        self._ep_return[:] = 0.0
        self._ep_len[:] = 0
        return self._obs()

    def step_batch(self, actions: np.ndarray):
        rewards = (np.asarray(actions) == self._quadrant()
                   ).astype(np.float32)
        self._steps += 1
        truncated = self._steps >= self.MAX_STEPS
        terminated = np.zeros(self.num_envs, bool)
        self._roll_pos()
        if truncated.any():
            self._steps[truncated] = 0
        return self._obs(), rewards, terminated, truncated


class RepeatPrevVector(VectorEnv):
    """Memory probe: at every step the agent sees a one-hot symbol and is
    rewarded for emitting the PREVIOUS step's symbol.  The current
    observation carries zero information about the correct action, so a
    feedforward policy is capped at chance (1/K) while one step of
    memory solves it exactly — the standard separation task for
    recurrent policies (reference: rllib's RepeatAfterMeEnv,
    examples/env/repeat_after_me_env.py, used by the LSTM examples)."""

    K = 3
    MAX_STEPS = 48
    observation_dim = 3   # == K
    num_actions = 3       # == K

    def __init__(self, num_envs: int, seed: int = 0):
        super().__init__(num_envs)
        self._rng = np.random.default_rng(seed)
        self._sym = np.zeros(num_envs, np.int64)
        self._prev = np.zeros(num_envs, np.int64)
        self._steps = np.zeros(num_envs, np.int64)

    def _obs(self) -> np.ndarray:
        return np.eye(self.K, dtype=np.float32)[self._sym]

    def reset_all(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._sym = self._rng.integers(0, self.K, self.num_envs)
        self._prev[:] = self._sym   # step 0: reward "repeat what you see"
        self._steps[:] = 0
        self._ep_return[:] = 0.0
        self._ep_len[:] = 0
        return self._obs()

    def step_batch(self, actions: np.ndarray):
        rewards = (np.asarray(actions) == self._prev).astype(np.float32)
        self._prev = self._sym
        self._sym = self._rng.integers(0, self.K, self.num_envs)
        self._steps += 1
        truncated = self._steps >= self.MAX_STEPS
        terminated = np.zeros(self.num_envs, bool)
        if truncated.any():
            self._steps[truncated] = 0
            self._prev[truncated] = self._sym[truncated]
        return self._obs(), rewards, terminated, truncated


_ENV_REGISTRY: Dict[str, Callable[..., VectorEnv]] = {
    "CartPole-v1": CartPoleVector,
    "Pendulum-v1": PendulumVector,
    "SyntheticPixel-v0": SyntheticPixelVector,
    "RepeatPrev-v0": RepeatPrevVector,
}


def register_env(name: str, creator: Callable[..., VectorEnv]) -> None:
    """Register a vector-env creator: creator(num_envs, seed) -> VectorEnv.

    Reference: ray.tune.registry.register_env.
    """
    _ENV_REGISTRY[name] = creator


def make_vector_env(name_or_creator: Any, num_envs: int,
                    seed: int = 0) -> VectorEnv:
    if callable(name_or_creator):
        return name_or_creator(num_envs, seed)
    if name_or_creator in _ENV_REGISTRY:
        return _ENV_REGISTRY[name_or_creator](num_envs, seed=seed)
    raise ValueError(f"unknown env {name_or_creator!r}; "
                     f"registered: {sorted(_ENV_REGISTRY)}")
