"""DQN: deep Q-learning with replay and a target network.

Reference parity: rllib/algorithms/dqn/ (dqn.py training_step: store
rollouts into the replay buffer, sample minibatches, TD update, periodic
target sync; simple_q loss with optional double-Q).  TPU-first: the TD
update (gather Q, double-Q target, huber loss, optimizer step) is one
jitted XLA program; epsilon-greedy exploration runs on the rollout
actors via per-worker epsilon schedules.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.models import make_model
from ray_tpu.rllib.replay_buffer import ReplayBuffer
from ray_tpu.rllib.sample_batch import SampleBatch
from ray_tpu.rllib.worker_set import WorkerSet


class DQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=DQN)
        self.lr = 1e-3
        self.grad_clip = 10.0
        self.replay_buffer_capacity = 50_000
        self.learning_starts = 1_000
        self.train_batch_size = 128
        self.updates_per_step = 32
        self.target_update_freq = 250      # updates between target syncs
        self.double_q = True
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.05
        self.epsilon_decay_steps = 8_000
        self.n_step_gamma = None           # defaults to cfg.gamma


class _QLearner:
    """Jitted TD update over (s, a, r, s', done) minibatches."""

    def __init__(self, obs_dim: int, num_actions: int, cfg: DQNConfig,
                 hidden, seed: int):
        init_params, self.apply = make_model(obs_dim, num_actions, hidden)
        # The ActorCritic's logits head doubles as Q-values; the value
        # head is unused here.
        self.params = init_params(jax.random.key(seed))
        # JAX arrays are immutable and updates REPLACE params, so plain
        # aliasing is a correct target-network snapshot.
        self.target_params = self.params
        self.tx = optax.chain(
            optax.clip_by_global_norm(cfg.grad_clip),
            optax.adam(cfg.lr, eps=1e-5))
        self.opt_state = self.tx.init(self.params)
        self.num_updates = 0
        gamma = cfg.n_step_gamma or cfg.gamma
        double_q = cfg.double_q
        apply = self.apply

        def loss(params, target_params, batch):
            q_all, _ = apply(params, batch["obs"])
            actions = batch["actions"].astype(jnp.int32)
            q = jnp.take_along_axis(q_all, actions[:, None], axis=1)[:, 0]
            q_next_t, _ = apply(target_params, batch["next_obs"])
            if double_q:
                q_next_online, _ = apply(params, batch["next_obs"])
                best = jnp.argmax(q_next_online, axis=1)
            else:
                best = jnp.argmax(q_next_t, axis=1)
            q_target_next = jnp.take_along_axis(
                q_next_t, best[:, None], axis=1)[:, 0]
            target = batch["rewards"] + gamma * q_target_next * (
                1.0 - batch["dones"].astype(jnp.float32))
            td = q - jax.lax.stop_gradient(target)
            huber = jnp.where(jnp.abs(td) < 1.0, 0.5 * td ** 2,
                              jnp.abs(td) - 0.5)
            return huber.mean(), {"td_error_mean": jnp.abs(td).mean(),
                                  "q_mean": q.mean()}

        def step(params, opt_state, target_params, batch):
            (total, metrics), grads = jax.value_and_grad(
                loss, has_aux=True)(params, target_params, batch)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            metrics["loss"] = total
            return params, opt_state, metrics

        self._step = jax.jit(step)

    def update(self, batch: SampleBatch) -> Dict[str, float]:
        jb = {k: jnp.asarray(v) for k, v in batch.items()}
        self.params, self.opt_state, metrics = self._step(
            self.params, self.opt_state, self.target_params, jb)
        self.num_updates += 1
        return {k: float(v) for k, v in metrics.items()}

    def sync_target(self) -> None:
        self.target_params = self.params

    def get_weights(self):
        return jax.device_get(self.params)

    def get_state(self):
        return {"params": jax.device_get(self.params),
                "target_params": jax.device_get(self.target_params),
                "opt_state": jax.device_get(self.opt_state)}

    def set_state(self, state):
        self.params = jax.device_put(state["params"])
        self.target_params = jax.device_put(state["target_params"])
        self.opt_state = jax.device_put(state["opt_state"])


def _to_transitions(batch: SampleBatch) -> SampleBatch:
    """Time-major fragment [T, B] -> flat (s, a, r, s', done) rows.  The
    next obs within a fragment is the next timestep; the last timestep
    bootstraps from the fragment's bootstrap_obs."""
    obs = batch[SampleBatch.OBS]                     # [T, B, D]
    next_obs = np.concatenate(
        [obs[1:], batch["bootstrap_obs"][None]], axis=0)
    # Only true termination zeroes the bootstrap term; a TRUNCATED episode
    # (time limit) still bootstraps from next_obs — treating it as
    # terminal would teach Q that surviving to the limit is worthless.
    done = batch[SampleBatch.TERMINATEDS]
    flat = lambda x: x.reshape((-1,) + x.shape[2:])
    return SampleBatch({
        "obs": flat(obs), "next_obs": flat(next_obs),
        "actions": flat(batch[SampleBatch.ACTIONS]),
        "rewards": flat(batch[SampleBatch.REWARDS]),
        "dones": flat(done),
    })


class DQN(Algorithm):
    def setup(self) -> None:
        cfg = self.config
        self.workers = WorkerSet(
            num_workers=cfg.num_rollout_workers,
            num_cpus_per_worker=cfg.num_cpus_per_worker,
            worker_kwargs=dict(
                env=cfg.env, num_envs=cfg.num_envs_per_worker,
                rollout_fragment_length=cfg.rollout_fragment_length,
                gamma=cfg.gamma, lam=cfg.lambda_,
                hidden=cfg.model_hidden, seed=cfg.seed,
                postprocess=False,
                epsilon_schedule=(cfg.epsilon_initial, cfg.epsilon_final,
                                  cfg.epsilon_decay_steps)))
        self.learner = _QLearner(self.obs_dim, self.num_actions, cfg,
                                 cfg.model_hidden, cfg.seed)
        self.buffer = ReplayBuffer(cfg.replay_buffer_capacity,
                                   seed=cfg.seed)
        self.workers.sync_weights(self.learner.get_weights())

    def training_step(self) -> Dict[str, Any]:
        """Reference: dqn.py training_step — sample -> store -> N TD
        updates -> periodic target sync -> weight broadcast."""
        cfg = self.config
        batches, metrics_list = self.workers.sample_sync()
        episodes = self._record_metrics(metrics_list)
        for b in batches:
            self.buffer.add(_to_transitions(b))

        learner_metrics: Dict[str, float] = {}
        updates = 0
        if len(self.buffer) >= cfg.learning_starts:
            for _ in range(cfg.updates_per_step):
                learner_metrics = self.learner.update(
                    self.buffer.sample(cfg.train_batch_size))
                updates += 1
                if self.learner.num_updates % cfg.target_update_freq == 0:
                    self.learner.sync_target()
            self.workers.sync_weights(self.learner.get_weights())

        return {"episodes_this_iter": episodes,
                "buffer_size": len(self.buffer),
                "learner_updates_total": self.learner.num_updates,
                "updates_this_iter": updates,
                **{f"learner/{k}": v for k, v in learner_metrics.items()}}

    def save_to_dict(self) -> Dict[str, Any]:
        return {"learner_state": self.learner.get_state(),
                "config": self.config.to_dict()}

    def restore_from_dict(self, state: Dict[str, Any]) -> None:
        self.learner.set_state(state["learner_state"])
        self.workers.sync_weights(self.learner.get_weights())
