"""APPO: asynchronous PPO.

Reference parity: rllib/algorithms/appo/appo.py — IMPALA's async
actor-learner architecture (rollout actors run ahead, a learner thread
consumes fragment queues, weights broadcast back) with PPO's clipped
importance-ratio surrogate computed on V-trace-corrected advantages,
which tolerates the policy lag the async pipeline introduces.  The TPU
build composes it literally: the IMPALA driver + learner thread, with
`clip_param` switching the jitted V-trace loss to the clipped surrogate
(impala.py _VTraceLearner).
"""

from __future__ import annotations

from ray_tpu.rllib.impala import IMPALA, IMPALAConfig


class APPOConfig(IMPALAConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = APPO
        # PPO-side knobs (reference: appo.py defaults; lr/clip tuned on
        # the in-tree CartPole gate — 0.3/5e-4 oscillated, 0.2/3e-4
        # learns monotonically).
        self.clip_param = 0.2
        self.lr = 3e-4
        self.entropy_coeff = 0.005
        self.min_updates_per_step = 4


class APPO(IMPALA):
    """All behavior inherited: the config's clip_param engages the
    clipped surrogate inside the V-trace learner."""
