"""Exploration strategies, decoupled from algorithms.

Reference parity: rllib/utils/exploration/ — EpsilonGreedy
(epsilon_greedy.py), GaussianNoise (gaussian_noise.py),
OrnsteinUhlenbeckNoise (ornstein_uhlenbeck_noise.py), Random (random.py),
and the schedule machinery of rllib/utils/schedules/.  An Exploration
object post-processes the policy's proposed actions given the current
timestep; rollout workers call it once per vectorized step (one numpy op
for the whole env batch — the TPU-first vectorization carried through).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


# ---------------------------------------------------------------------------
# Schedules (reference: rllib/utils/schedules/)
# ---------------------------------------------------------------------------

class Schedule:
    def value(self, t: int) -> float:
        raise NotImplementedError

    def __call__(self, t: int) -> float:
        return self.value(t)


class ConstantSchedule(Schedule):
    def __init__(self, v: float):
        self.v = float(v)

    def value(self, t: int) -> float:
        return self.v


class LinearSchedule(Schedule):
    """initial -> final over horizon steps, then flat."""

    def __init__(self, initial: float, final: float, horizon: int):
        self.initial, self.final, self.horizon = initial, final, max(horizon, 1)

    def value(self, t: int) -> float:
        frac = min(1.0, t / self.horizon)
        return self.initial + (self.final - self.initial) * frac


class PiecewiseSchedule(Schedule):
    """[(t, v), ...] endpoints with linear interpolation between them."""

    def __init__(self, endpoints: Sequence[Tuple[int, float]]):
        self.points = sorted(endpoints)

    def value(self, t: int) -> float:
        pts = self.points
        if t <= pts[0][0]:
            return pts[0][1]
        for (t0, v0), (t1, v1) in zip(pts, pts[1:]):
            if t0 <= t < t1:
                frac = (t - t0) / max(t1 - t0, 1)
                return v0 + (v1 - v0) * frac
        return pts[-1][1]


# ---------------------------------------------------------------------------
# Exploration strategies
# ---------------------------------------------------------------------------

class Exploration:
    """Post-processes a batch of proposed actions.

    apply(actions, timestep, rng) -> actions.  `actions` is the policy's
    proposal for the whole env batch; implementations return the batch to
    actually execute."""

    def apply(self, actions: np.ndarray, timestep: int,
              rng: np.random.Generator) -> np.ndarray:
        raise NotImplementedError


class EpsilonGreedy(Exploration):
    """Uniform-random action with probability epsilon(t) (reference:
    epsilon_greedy.py; the default for value-based algorithms)."""

    def __init__(self, num_actions: int,
                 initial: float = 1.0, final: float = 0.02,
                 horizon: int = 10_000,
                 schedule: Optional[Schedule] = None):
        self.num_actions = num_actions
        self.schedule = schedule or LinearSchedule(initial, final, horizon)

    def apply(self, actions, timestep, rng):
        eps = self.schedule(timestep)
        b = len(actions)
        mask = rng.random(b) < eps
        return np.where(mask, rng.integers(0, self.num_actions, b), actions)


class GaussianNoise(Exploration):
    """Additive N(0, scale(t)) noise on continuous actions, clipped to
    bounds (reference: gaussian_noise.py; TD3's default)."""

    def __init__(self, low: float, high: float, scale: float = 0.1,
                 schedule: Optional[Schedule] = None):
        self.low, self.high = low, high
        self.schedule = schedule or ConstantSchedule(scale)

    def apply(self, actions, timestep, rng):
        scale = self.schedule(timestep)
        noise = rng.normal(0.0, scale, size=np.shape(actions))
        return np.clip(actions + noise, self.low, self.high)


class OrnsteinUhlenbeckNoise(Exploration):
    """Temporally-correlated OU noise (reference:
    ornstein_uhlenbeck_noise.py; the classic DDPG exploration): state
    follows dx = theta*(mu - x)*dt + sigma*sqrt(dt)*N(0,1) per env."""

    def __init__(self, low: float, high: float, *, theta: float = 0.15,
                 sigma: float = 0.2, dt: float = 1.0, mu: float = 0.0):
        self.low, self.high = low, high
        self.theta, self.sigma, self.dt, self.mu = theta, sigma, dt, mu
        self._state: Optional[np.ndarray] = None

    def apply(self, actions, timestep, rng):
        actions = np.asarray(actions, np.float64)
        if self._state is None or self._state.shape != actions.shape:
            self._state = np.zeros_like(actions)
        self._state = (self._state
                       + self.theta * (self.mu - self._state) * self.dt
                       + self.sigma * np.sqrt(self.dt)
                       * rng.normal(size=actions.shape))
        return np.clip(actions + self._state, self.low, self.high)


class Random(Exploration):
    """Fully random actions (reference: random.py; warmup phases)."""

    def __init__(self, num_actions: int = 0, action_dim: int = 0,
                 low: float = -1.0, high: float = 1.0):
        self.num_actions = num_actions
        self.action_dim = action_dim
        self.low, self.high = low, high

    def apply(self, actions, timestep, rng):
        b = len(actions)
        if self.num_actions:
            return rng.integers(0, self.num_actions, b)
        return rng.uniform(self.low, self.high,
                           size=(b, self.action_dim)).astype(np.float32)
