"""CheckpointManager: step-indexed layout, retention, and GC.

Directory layout under one root:

    <root>/checkpoint_000000/   (committed)
    <root>/checkpoint_000001/   (committed)
    <root>/checkpoint_000002/   (no COMMIT marker -> torn, ignored)

Retention is the union of three sets over COMMITTED steps: the last
`keep_last_k`, the best `keep_best_k` by `best_metric` (read back from
each manifest, so keep-best survives restarts), and always the latest.
Uncommitted directories are invisible to `steps()`/`latest_step()` and
are GC'd once a committed step at or past them exists (never before —
one may be an in-flight save by a peer process).
"""

from __future__ import annotations

import os
import re
import shutil
from typing import Any, Dict, List, Optional

from ray_tpu.checkpoint import sharded
from ray_tpu.checkpoint.async_writer import AsyncCheckpointer, SaveHandle

_STEP_RE = re.compile(r"^checkpoint_(\d+)$")


class CheckpointManager:
    PREFIX = "checkpoint_"

    def __init__(self, root: str, *, keep_last_k: Optional[int] = None,
                 keep_best_k: Optional[int] = None,
                 best_metric: Optional[str] = None, best_mode: str = "max",
                 save_id: str = "0"):
        if best_mode not in ("max", "min"):
            raise ValueError(f"best_mode must be max|min, got {best_mode!r}")
        self.root = root
        self.keep_last_k = keep_last_k
        self.keep_best_k = keep_best_k
        self.best_metric = best_metric
        self.best_mode = best_mode
        self.save_id = str(save_id)
        self._ckptr = AsyncCheckpointer()
        self._metrics: Dict[int, dict] = {}
        os.makedirs(root, exist_ok=True)

    # -------- layout --------

    def step_dir(self, step: int) -> str:
        return os.path.join(self.root, f"{self.PREFIX}{step:06d}")

    def _scan(self) -> Dict[int, bool]:
        """{step: committed} for every checkpoint-shaped directory."""
        out: Dict[int, bool] = {}
        try:
            names = os.listdir(self.root)
        except FileNotFoundError:
            return out
        for name in names:
            m = _STEP_RE.match(name)
            if m and os.path.isdir(os.path.join(self.root, name)):
                out[int(m.group(1))] = sharded.is_committed(
                    os.path.join(self.root, name))
        return out

    def steps(self) -> List[int]:
        """Committed steps, ascending."""
        return sorted(s for s, ok in self._scan().items() if ok)

    def latest_step(self) -> Optional[int]:
        steps = self.steps()
        return steps[-1] if steps else None

    # -------- save --------

    def save(self, step: int, tree: Any, *, metrics: Optional[dict] = None,
             sync: bool = False) -> SaveHandle:
        """Save `tree` as `step` (async by default; the returned handle
        can ride session.report to the driver).  Force-joins the previous
        save first, so at most one write is ever in flight."""
        if metrics:
            self._metrics[int(step)] = dict(metrics)
        handle = self._ckptr.save(
            self.step_dir(step), tree, step=int(step), metrics=metrics,
            save_id=self.save_id, sync=sync)
        if sync:
            self.gc()
        return handle

    def track(self, step: int, metrics: Optional[dict] = None) -> None:
        """Bookkeeping for a save performed elsewhere (training workers
        writing under this root): record metrics for keep-best and run
        retention against whatever has committed so far."""
        if metrics:
            self._metrics[int(step)] = dict(metrics)
        self.gc()

    def wait_until_finished(self) -> None:
        """Barrier on the in-flight save, then retention/GC."""
        self._ckptr.wait_until_finished()
        self.gc()

    @property
    def in_flight(self) -> Optional[SaveHandle]:
        return self._ckptr.in_flight

    # -------- restore --------

    def restore(self, step: Optional[int] = None, *, mesh=None,
                shardings=None) -> Any:
        """Re-materialize a committed step (default: latest) under the
        CURRENT mesh/shardings — see sharded.restore_sharded."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no committed checkpoint under {self.root}")
        return sharded.restore_sharded(self.step_dir(step), mesh=mesh,
                                       shardings=shardings)

    def restore_latest(self, *, mesh=None, shardings=None) -> Any:
        return self.restore(None, mesh=mesh, shardings=shardings)

    def latest_checkpoint(self):
        """The latest committed step as an air.Checkpoint (None if no
        step has committed)."""
        step = self.latest_step()
        if step is None:
            return None
        from ray_tpu.air.checkpoint import Checkpoint
        return Checkpoint.from_sharded_dir(self.step_dir(step))

    def metrics_for(self, step: int) -> Optional[dict]:
        if step in self._metrics:
            return self._metrics[step]
        path = self.step_dir(step)
        try:
            meta = sharded.checkpoint_metadata(path)
        except Exception:
            return None
        self._metrics[step] = meta.get("metrics") or {}
        return self._metrics[step]

    # -------- retention / GC --------

    def _keep_set(self, committed: List[int]) -> set:
        keep: set = set()
        if committed:
            keep.add(committed[-1])   # the latest always survives
        if self.keep_last_k is not None:
            keep.update(committed[-self.keep_last_k:]
                        if self.keep_last_k > 0 else [])
        if self.best_metric is not None:
            scored = []
            for s in committed:
                m = self.metrics_for(s) or {}
                if self.best_metric in m:
                    scored.append((float(m[self.best_metric]), s))
            scored.sort(reverse=(self.best_mode == "max"))
            k = self.keep_best_k if self.keep_best_k is not None \
                else len(scored)
            keep.update(s for _, s in scored[:k])
        if self.keep_last_k is None and self.best_metric is None:
            return set(committed)     # retention off: keep everything
        return keep

    def gc(self) -> List[int]:
        """Apply retention to committed steps and delete torn
        directories that a committed step has overtaken.  Returns the
        steps removed."""
        scan = self._scan()
        committed = sorted(s for s, ok in scan.items() if ok)
        keep = self._keep_set(committed)
        latest = committed[-1] if committed else None
        removed = []
        for step, ok in scan.items():
            doomed = (ok and step not in keep) or \
                (not ok and latest is not None and step <= latest)
            if doomed:
                shutil.rmtree(self.step_dir(step), ignore_errors=True)
                removed.append(step)
                self._metrics.pop(step, None)
        return sorted(removed)
