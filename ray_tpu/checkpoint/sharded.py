"""Sharded pytree save/restore: each host writes only its shards.

Save layout (one directory per checkpoint):

    checkpoint_000042/
      manifest.json        tree skeleton + per-array shape/dtype/spec +
                           chunk->file inventory (written by process 0)
      a0_c0.bin            raw C-order bytes of array 0, chunk 0
      a0_c1.bin            ...one file per UNIQUE chunk: a shard that is
      a1_c0.bin            replicated across devices/hosts is written once,
                           by the process holding its replica_id==0 shard
      DONE.0.<save_id>     per-process completion markers
      DONE.1.<save_id>
      COMMIT               atomic commit marker — written only after every
                           process' DONE marker is present AND the chunk
                           inventory verifies; a directory without COMMIT
                           is torn and is never restored from

Crash safety: every file lands via tmp + fsync + atomic rename, and the
COMMIT rename is the linearization point — kill the process anywhere
before it and the directory is ignored (and later GC'd) by the manager.

Multi-host commit needs no barrier: each process, after writing its own
DONE marker, checks whether it completed the set and, if so, verifies
the inventory and performs the commit rename (idempotent — replace).
`save_id` disambiguates incarnations: markers from a dead run that
crashed into the same directory carry a different save_id and are
ignored, and process 0 clears such torn leftovers before re-staging.

Elastic restore: the manifest records GLOBAL shapes, so the tree can be
re-materialized under any current mesh/sharding — each device's shard is
assembled from whichever saved chunks overlap it.
"""

from __future__ import annotations

import math
import os
import shutil
import sys
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.checkpoint.manifest import (
    COMMIT_FILE, FORMAT, MANIFEST_FILE, LeafRef, decode_tree, encode_tree,
    fsync_dir, read_manifest, resolve_dtype, skeleton_refs,
    write_bytes_atomic, write_json_atomic)

Index = Tuple[Tuple[int, int], ...]   # ((start, stop) per dim)


def _process_info() -> Tuple[int, int]:
    """(process_index, process_count) — from the jax.distributed fabric
    when jax is live in this process, else (0, 1).  sys.modules guard:
    a host saving a plain numpy tree must not drag a backend up."""
    jx = sys.modules.get("jax")
    if jx is None:
        return 0, 1
    try:
        return jx.process_index(), jx.process_count()
    except Exception:
        return 0, 1


def _normalize_index(idx, shape) -> Index:
    out = []
    for sl, dim in zip(idx, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append((start, stop))
    return tuple(out)


def _index_shape(index: Index) -> Tuple[int, ...]:
    return tuple(stop - start for start, stop in index)


def _spec_json(arr) -> Optional[list]:
    """Logical partition spec as JSON: one entry per dim, each None or a
    list of mesh-axis names.  Recorded for elastic re-sharding; restore
    re-binds the names to whatever axes the CURRENT mesh has."""
    sharding = getattr(arr, "sharding", None)
    spec = getattr(sharding, "spec", None)
    if spec is None:
        return None
    out = []
    for entry in spec:
        if entry is None:
            out.append(None)
        elif isinstance(entry, str):
            out.append([entry])
        else:
            out.append([str(a) for a in entry])
    return out


@dataclass
class Staged:
    """A device-to-host snapshot ready for the (background) writer."""

    manifest: dict
    local_chunks: List[Tuple[str, np.ndarray]]
    process_index: int
    process_count: int
    save_id: str = "0"
    directory: str = ""
    committed: bool = field(default=False)


def stage(tree: Any, *, save_id: str = "0", step: Optional[int] = None,
          metrics: Optional[dict] = None) -> Staged:
    """The synchronous half of a save: fetch this host's addressable
    shards to host memory and build the manifest.  Runs at the step
    boundary; everything after (serialization, I/O, commit) can happen
    on a background thread against the snapshot."""
    from ray_tpu.util import spans
    # Durational span: stage() runs AT the step boundary, so its length
    # is exactly the checkpoint tax on training (the async writer hides
    # the rest).
    tok = spans.begin("ckpt", "stage", save_id=str(save_id), step=step)
    pidx, pcount = _process_info()
    skeleton, leaves = encode_tree(tree)
    arrays = []
    local: List[Tuple[str, np.ndarray]] = []
    for i, arr in enumerate(leaves):
        dtype = np.dtype(arr.dtype)
        shape = tuple(int(s) for s in arr.shape)
        sharding = getattr(arr, "sharding", None)
        if sharding is not None and hasattr(arr, "addressable_shards"):
            # Unique chunk set over the GLOBAL array — identical on every
            # host, so the manifest (written by process 0) can inventory
            # chunks other hosts write.
            index_map = sharding.devices_indices_map(shape)
            unique = sorted({_normalize_index(idx, shape)
                             for idx in index_map.values()})
            ordinal = {idx: n for n, idx in enumerate(unique)}
            seen: set = set()
            for shard in arr.addressable_shards:
                if shard.replica_id != 0:
                    continue    # replicated shard: exactly one global owner
                idx = _normalize_index(shard.index, shape)
                if idx in seen:
                    continue
                seen.add(idx)
                local.append((f"a{i}_c{ordinal[idx]}.bin",
                              np.asarray(shard.data)))
        else:
            # Host array (numpy): one full-extent chunk, owned by rank 0.
            unique = [tuple((0, d) for d in shape)]
            if pidx == 0:
                local.append((f"a{i}_c0.bin", np.asarray(arr)))
        arrays.append({
            "id": i,
            "path": _leaf_path(skeleton, i),
            "shape": list(shape),
            "dtype": dtype.name,
            "spec": _spec_json(arr),
            "chunks": [{
                "file": f"a{i}_c{n}.bin",
                "index": [[s, e] for s, e in idx],
                "nbytes": int(math.prod(_index_shape(idx)) * dtype.itemsize),
            } for n, idx in enumerate(unique)],
        })
    manifest = {
        "format": FORMAT,
        "save_id": str(save_id),
        "process_count": pcount,
        "step": step,
        "metrics": dict(metrics) if metrics else {},
        "tree": skeleton,
        "arrays": arrays,
    }
    spans.end(tok, chunks=len(local))
    return Staged(manifest=manifest, local_chunks=local,
                  process_index=pidx, process_count=pcount,
                  save_id=str(save_id))


def _leaf_path(skeleton: dict, leaf_id: int) -> str:
    stack = [skeleton]
    while stack:
        node = stack.pop()
        kind = node["kind"]
        if kind == "array" and node["id"] == leaf_id:
            return node["path"]
        if kind == "dict":
            stack.extend(node["items"].values())
        elif kind in ("list", "tuple", "namedtuple"):
            stack.extend(node["items"])
    return ""


def write_staged(staged: Staged, path: str, *, commit: bool = True) -> str:
    """The I/O half of a save (background-thread safe): write chunks,
    manifest, DONE marker; then attempt the commit rename."""
    staged.directory = path
    if staged.process_index == 0 and os.path.isdir(path) \
            and not is_committed(path):
        # A torn directory from a dead incarnation: clear it rather than
        # letting its stale DONE markers/chunks alias into this save.
        try:
            stale = read_manifest(path).get("save_id")
        except Exception:
            stale = None
        if stale != staged.save_id:
            shutil.rmtree(path, ignore_errors=True)
    os.makedirs(path, exist_ok=True)
    for fname, data in staged.local_chunks:
        data = np.ascontiguousarray(data)
        write_bytes_atomic(os.path.join(path, fname), data.tobytes())
    if staged.process_index == 0:
        write_json_atomic(os.path.join(path, MANIFEST_FILE), staged.manifest)
    write_bytes_atomic(
        os.path.join(path, f"DONE.{staged.process_index}.{staged.save_id}"),
        b"")
    fsync_dir(path)
    if commit:
        staged.committed = maybe_commit(path, staged.save_id,
                                        staged.process_count)
    return path


def maybe_commit(path: str, save_id: str, process_count: int) -> bool:
    """Write COMMIT iff every process' DONE marker (for THIS save_id) is
    present and the manifest's chunk inventory verifies.  Idempotent and
    safe to race: os.replace makes the marker appear exactly once."""
    if is_committed(path):
        return True
    try:
        man = read_manifest(path)
    except Exception:
        return False
    if man.get("save_id") != save_id:
        return False
    for i in range(process_count):
        if not os.path.isfile(os.path.join(path, f"DONE.{i}.{save_id}")):
            return False
    for entry in man["arrays"]:
        for chunk in entry["chunks"]:
            f = os.path.join(path, chunk["file"])
            try:
                if os.path.getsize(f) != chunk["nbytes"]:
                    return False
            except OSError:
                return False
    # Chaos interposition: "kill mid-async-save" lands HERE — after the
    # data is fully written but before the commit rename, the worst
    # possible instant.  A restore must never see this directory.
    from ray_tpu._private.fault_injection import get_chaos
    from ray_tpu.util import events
    chaos = get_chaos()
    if chaos is not None and chaos.kill_ckpt_commit():
        events.record("ckpt", "chaos_kill", path=path, save_id=save_id)
        events.dump_crash("chaos_kill_ckpt_commit")
        os._exit(1)
    write_bytes_atomic(os.path.join(path, COMMIT_FILE),
                       b'{"save_id": "%s"}\n' % save_id.encode())
    fsync_dir(path)
    events.record("ckpt", "commit", path=path, save_id=save_id)
    return True


def is_committed(path: str) -> bool:
    return os.path.isfile(os.path.join(path, COMMIT_FILE))


def save_sharded(path: str, tree: Any, *, save_id: str = "0",
                 step: Optional[int] = None, metrics: Optional[dict] = None,
                 commit: bool = True) -> str:
    """Synchronous sharded save (the async path runs the same two halves
    on either side of a thread hop — see async_writer.AsyncCheckpointer).

    `commit=False` is for tests that need a deliberately torn directory.
    """
    staged = stage(tree, save_id=save_id, step=step, metrics=metrics)
    return write_staged(staged, path, commit=commit)


# ---------------------------------------------------------------------------
# Restore
# ---------------------------------------------------------------------------


class _LeafReader:
    """Assembles arbitrary index windows of one saved array from its
    chunk files (memory-mapped, so restoring a small shard of a large
    array reads only the overlapping bytes)."""

    def __init__(self, directory: str, entry: dict):
        self.dir = directory
        self.shape = tuple(entry["shape"])
        self.dtype = resolve_dtype(entry["dtype"])
        self.chunks = [(tuple((s, e) for s, e in c["index"]), c["file"])
                       for c in entry["chunks"]]
        self._maps: Dict[str, np.ndarray] = {}

    def _chunk_data(self, index: Index, fname: str) -> np.ndarray:
        m = self._maps.get(fname)
        if m is None:
            full = os.path.join(self.dir, fname)
            shape = _index_shape(index)
            if math.prod(shape) == 0:
                m = np.empty(shape, self.dtype)
            elif len(shape) == 0:
                with open(full, "rb") as f:
                    m = np.frombuffer(f.read(), self.dtype).reshape(())
            else:
                m = np.memmap(full, dtype=self.dtype, mode="r", shape=shape)
            self._maps[fname] = m
        return m

    def read(self, index) -> np.ndarray:
        """Materialize the window `index` (tuple of slices) as a host
        array, gathering from every overlapping chunk."""
        req = _normalize_index(index, self.shape)
        out = np.empty(_index_shape(req), self.dtype)
        if out.size == 0:
            return out
        for cidx, fname in self.chunks:
            inter = tuple((max(rs, cs), min(re, ce))
                          for (rs, re), (cs, ce) in zip(req, cidx))
            if any(s >= e for s, e in inter):
                continue
            src = self._chunk_data(cidx, fname)
            src_sl = tuple(slice(s - cs, e - cs)
                           for (s, e), (cs, _) in zip(inter, cidx))
            dst_sl = tuple(slice(s - rs, e - rs)
                           for (s, e), (rs, _) in zip(inter, req))
            out[dst_sl] = src[src_sl]
        return out

    def read_full(self) -> np.ndarray:
        return self.read(tuple(slice(0, d) for d in self.shape))


def _spec_for_mesh(entry: dict, mesh):
    """Re-bind the SAVED partition spec to the CURRENT mesh: axis names
    that don't exist (or have size 1) on this mesh are dropped, so a
    tree saved on a 4-device ("data","tensor") mesh restores onto a
    2-device ("data",) mesh with the tensor split simply gone."""
    from jax.sharding import PartitionSpec as P
    spec = entry.get("spec")
    if spec is None:
        return P()
    out = []
    for dim in spec:
        axes = tuple(a for a in (dim or [])
                     if mesh.shape.get(a, 1) > 1)
        if not axes:
            out.append(None)
        elif len(axes) == 1:
            out.append(axes[0])
        else:
            out.append(axes)
    while out and out[-1] is None:
        out.pop()
    return P(*out)


def restore_sharded(path: str, *, mesh=None, shardings=None,
                    allow_uncommitted: bool = False) -> Any:
    """Re-materialize a saved pytree from `path`.

    - default: host numpy arrays (replicated view of the global tree)
    - ``mesh=``: jax arrays, each re-sharded onto `mesh` by re-binding
      its saved logical spec (elastic restore across device counts)
    - ``shardings=``: a single Sharding or a pytree of Shardings
      congruent with the saved structure — full caller control

    Only committed directories restore unless `allow_uncommitted`.
    """
    if not allow_uncommitted and not is_committed(path):
        raise FileNotFoundError(
            f"{path}: no COMMIT marker — checkpoint is torn or still "
            f"being written (pass allow_uncommitted=True to override)")
    man = read_manifest(path)
    per_leaf_sharding: Dict[int, Any] = {}
    if shardings is not None:
        import jax
        from jax.sharding import Sharding
        if isinstance(shardings, Sharding):
            per_leaf_sharding = {e["id"]: shardings for e in man["arrays"]}
        else:
            refs = skeleton_refs(man["tree"])

            def record(ref, sh):
                if isinstance(ref, LeafRef):
                    per_leaf_sharding[ref.id] = sh

            jax.tree.map(record, refs, shardings)
    leaf_values: Dict[int, Any] = {}
    for entry in man["arrays"]:
        reader = _LeafReader(path, entry)
        if mesh is None and entry["id"] not in per_leaf_sharding:
            leaf_values[entry["id"]] = reader.read_full()
            continue
        import jax
        from jax.sharding import NamedSharding
        sharding = per_leaf_sharding.get(entry["id"])
        if sharding is None:
            sharding = NamedSharding(mesh, _spec_for_mesh(entry, mesh))
        leaf_values[entry["id"]] = jax.make_array_from_callback(
            reader.shape, sharding, reader.read)
    return decode_tree(man["tree"], leaf_values)


def checkpoint_metadata(path: str) -> dict:
    """step/metrics/save_id/process_count of a saved directory, without
    touching any chunk data."""
    man = read_manifest(path)
    return {k: man.get(k) for k in
            ("step", "metrics", "save_id", "process_count")}
