"""Async save path: device-to-host at the step boundary, I/O off-thread.

`AsyncCheckpointer.save()` does the orbax-style split: the blocking part
is only the device-to-host shard fetch (`sharded.stage`), after which the
training step loop can continue mutating the live arrays; serialization,
file writes, fsyncs, and the commit rename run on a background writer
thread against the host snapshot.

Staleness is bounded two ways: `wait_until_finished()` is an explicit
barrier, and each `save()` force-joins the previous one first — at most
ONE checkpoint is ever in flight, so a crash loses at most the newest
save (the previous one is already committed).
"""

from __future__ import annotations

import threading
from typing import Any, Optional

from ray_tpu.checkpoint import sharded


class CheckpointWriteError(RuntimeError):
    """A background checkpoint write failed (raised at the next barrier:
    wait_until_finished() or the force-join inside the next save())."""


class SaveHandle:
    """Ticket for one (possibly in-flight) checkpoint write.

    Cheap to pickle: crossing a process boundary (session.report ships
    handles from training workers to the driver) keeps only (directory,
    step) — the receiving side observes progress through the COMMIT
    marker on the shared filesystem, never through the origin thread.
    """

    def __init__(self, directory: str, step: Optional[int] = None):
        self.directory = directory
        self.step = step
        self._event = threading.Event()
        self._error: Optional[BaseException] = None

    def done(self) -> bool:
        """True once the local writer thread finished (success or not)."""
        return self._event.is_set()

    def committed(self) -> bool:
        """True once the COMMIT marker exists — the only signal that is
        meaningful across processes."""
        return sharded.is_committed(self.directory)

    def wait(self, timeout: Optional[float] = None) -> str:
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"checkpoint write to {self.directory} still in flight "
                f"after {timeout}s")
        if self._error is not None:
            raise CheckpointWriteError(
                f"checkpoint write to {self.directory} failed"
            ) from self._error
        return self.directory

    def __reduce__(self):
        return (_remote_handle, (self.directory, self.step))

    def __repr__(self):
        state = ("committed" if self.committed()
                 else "done" if self.done() else "in-flight")
        return f"SaveHandle({self.directory}, step={self.step}, {state})"


def _remote_handle(directory: str, step) -> "SaveHandle":
    h = SaveHandle(directory, step)
    h._event.set()   # no local writer on this side; committed() is truth
    return h


class AsyncCheckpointer:
    """One background writer; at most one save in flight."""

    def __init__(self):
        self._thread: Optional[threading.Thread] = None
        self._handle: Optional[SaveHandle] = None
        self._lock = threading.Lock()

    def save(self, directory: str, tree: Any, *, step: Optional[int] = None,
             metrics: Optional[dict] = None, save_id: str = "0",
             sync: bool = False, commit: bool = True) -> SaveHandle:
        """Snapshot `tree` to host and hand the write to the background
        thread; returns as soon as the snapshot exists.  Force-joins any
        previous in-flight save first (bounding staleness to one step);
        `sync=True` degrades to a fully blocking save."""
        with self._lock:
            self.wait_until_finished()
            staged = sharded.stage(tree, save_id=save_id, step=step,
                                   metrics=metrics)
            handle = SaveHandle(directory, step)

            def _write():
                try:
                    sharded.write_staged(staged, directory, commit=commit)
                except BaseException as e:  # noqa: BLE001
                    handle._error = e
                finally:
                    handle._event.set()

            if sync:
                _write()
                self._handle = handle
                if handle._error is not None:
                    handle.wait(0)
            else:
                t = threading.Thread(
                    target=_write, daemon=True,
                    name=f"ckpt-writer-{step if step is not None else ''}")
                self._thread = t
                self._handle = handle
                t.start()
            return handle

    def wait_until_finished(self) -> None:
        """Barrier: block until the in-flight write (if any) hits disk;
        re-raises its failure, once."""
        t, h = self._thread, self._handle
        if t is not None:
            t.join()
            self._thread = None
        if h is not None and h.done() and h._error is not None:
            self._handle = None
            h.wait(0)   # raises CheckpointWriteError

    @property
    def in_flight(self) -> Optional[SaveHandle]:
        h = self._handle
        return h if h is not None and not h.done() else None
