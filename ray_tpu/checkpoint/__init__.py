"""ray_tpu.checkpoint — distributed sharded async checkpointing.

Orbax-shaped, ray_tpu-native: each host writes only its addressable
shards of a jax pytree (replicated params deduped to one writer), a JSON
manifest records the global tree, and a crash-safe COMMIT marker makes
torn directories impossible to restore from.  The async path overlaps
serialization/I/O with training; `CheckpointManager` adds step-indexed
layout, keep-last-K / keep-best retention, and GC.

    from ray_tpu import checkpoint as ckpt

    mgr = ckpt.CheckpointManager(root, keep_last_k=3)
    handle = mgr.save(step, {"params": params, "opt_state": opt_state})
    ...                                   # training continues immediately
    mgr.wait_until_finished()             # explicit barrier when needed

    state = mgr.restore_latest(mesh=mesh)  # elastic: ANY current mesh

No reference counterpart — Ray delegates checkpointing to hosted
frameworks; here (as with sharding) it is a core subsystem.
"""

from ray_tpu.checkpoint.async_writer import (  # noqa: F401
    AsyncCheckpointer,
    CheckpointWriteError,
    SaveHandle,
)
from ray_tpu.checkpoint.manager import CheckpointManager  # noqa: F401
from ray_tpu.checkpoint.manifest import (  # noqa: F401
    COMMIT_FILE,
    MANIFEST_FILE,
)
from ray_tpu.checkpoint.sharded import (  # noqa: F401
    checkpoint_metadata,
    is_committed,
    restore_sharded,
    save_sharded,
)
