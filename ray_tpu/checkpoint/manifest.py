"""Manifest codec for sharded checkpoints: pytree structure as JSON.

A sharded checkpoint directory holds one `manifest.json` plus one raw
binary file per *unique* array chunk.  The manifest records everything
needed to re-materialize the tree on a DIFFERENT topology: the tree
skeleton (dict/list/tuple/namedtuple nesting with scalars inlined), and
per-array global shape, dtype, logical partition spec, and the chunk ->
file map with byte sizes (the commit-time inventory).

Orbax keeps this metadata in a msgpack'd "checkpoint" + per-array
TensorStore specs; here it is one human-readable JSON file, which is
also what makes torn directories diagnosable by `ls` + `cat`.

No jax import at module level — the numpy-only restore path (and the
manager's directory scans) must work on hosts without an initialized
backend.
"""

from __future__ import annotations

import importlib
import json
import os
from typing import Any, Dict, List, Tuple

import numpy as np

FORMAT = "ray_tpu.sharded_ckpt.v1"
MANIFEST_FILE = "manifest.json"
COMMIT_FILE = "COMMIT"

_SCALARS = (bool, int, float, str, type(None))


class LeafRef:
    """Placeholder standing where array leaf `id` goes in a decoded
    skeleton — lets callers tree-map shardings onto the saved structure
    before any data is read."""

    __slots__ = ("id",)

    def __init__(self, id: int):
        self.id = id

    def __repr__(self):
        return f"LeafRef({self.id})"


def _is_array(x) -> bool:
    return hasattr(x, "shape") and hasattr(x, "dtype")


def encode_tree(tree: Any) -> Tuple[dict, List[Any]]:
    """(skeleton, leaves): JSON-able skeleton with array leaves replaced
    by {"kind": "array", "id": i}; `leaves[i]` is the original array."""
    leaves: List[Any] = []

    def enc(node, path):
        if _is_array(node):
            i = len(leaves)
            leaves.append(node)
            return {"kind": "array", "id": i, "path": path}
        if isinstance(node, _SCALARS):
            return {"kind": "scalar", "value": node}
        if isinstance(node, tuple) and hasattr(node, "_fields"):
            cls = type(node)
            return {"kind": "namedtuple",
                    "cls": f"{cls.__module__}:{cls.__qualname__}",
                    "fields": list(node._fields),
                    "items": [enc(v, f"{path}.{f}")
                              for f, v in zip(node._fields, node)]}
        if isinstance(node, dict):
            bad = [k for k in node if not isinstance(k, str)]
            if bad:
                raise TypeError(
                    f"sharded checkpoint dict keys must be str, got "
                    f"{bad[0]!r} at {path or '<root>'}")
            return {"kind": "dict",
                    "items": {k: enc(v, f"{path}.{k}" if path else k)
                              for k, v in node.items()}}
        if isinstance(node, tuple):
            return {"kind": "tuple",
                    "items": [enc(v, f"{path}[{i}]")
                              for i, v in enumerate(node)]}
        if isinstance(node, list):
            return {"kind": "list",
                    "items": [enc(v, f"{path}[{i}]")
                              for i, v in enumerate(node)]}
        raise TypeError(
            f"unsupported pytree node {type(node).__name__} at "
            f"{path or '<root>'} — sharded checkpoints support "
            f"dict/list/tuple/namedtuple containers, array leaves, and "
            f"python scalars")

    return enc(tree, ""), leaves


def decode_tree(skeleton: dict, leaf_values: Dict[int, Any]) -> Any:
    """Rebuild the tree; array placeholders resolve through
    `leaf_values` (pass {i: LeafRef(i)} to get the bare structure)."""

    def dec(node):
        kind = node["kind"]
        if kind == "array":
            return leaf_values[node["id"]]
        if kind == "scalar":
            return node["value"]
        if kind == "dict":
            return {k: dec(v) for k, v in node["items"].items()}
        if kind == "list":
            return [dec(v) for v in node["items"]]
        if kind == "tuple":
            return tuple(dec(v) for v in node["items"])
        if kind == "namedtuple":
            items = [dec(v) for v in node["items"]]
            mod, _, qual = node["cls"].partition(":")
            try:
                obj = importlib.import_module(mod)
                for part in qual.split("."):
                    obj = getattr(obj, part)
                return obj(*items)
            except Exception:
                # The defining class moved/vanished: degrade to a plain
                # tuple (field order preserved) rather than failing the
                # whole restore.
                return tuple(items)
        raise ValueError(f"unknown skeleton node kind {kind!r}")

    return dec(skeleton)


def skeleton_refs(skeleton: dict) -> Any:
    """The saved tree with LeafRef placeholders at every array leaf."""
    ids: Dict[int, LeafRef] = {}

    def collect(node):
        if node["kind"] == "array":
            ids[node["id"]] = LeafRef(node["id"])
        elif node["kind"] == "dict":
            for v in node["items"].values():
                collect(v)
        elif node["kind"] in ("list", "tuple", "namedtuple"):
            for v in node["items"]:
                collect(v)

    collect(skeleton)
    return decode_tree(skeleton, ids)


def resolve_dtype(name: str) -> np.dtype:
    """np.dtype by name, reaching into ml_dtypes for the TPU low-precision
    types (bfloat16, float8_*) numpy doesn't define."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


# ---------------------------------------------------------------------------
# Durable small-file writes
# ---------------------------------------------------------------------------


def fsync_dir(path: str) -> None:
    """fsync a directory so a rename into it survives power loss (no-op
    on platforms that refuse O_RDONLY dir fds)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def write_bytes_atomic(path: str, blob: bytes) -> None:
    """tmp-file + fsync + atomic rename: the file either exists complete
    or not at all."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def write_json_atomic(path: str, obj: Any) -> None:
    write_bytes_atomic(path, json.dumps(obj, indent=1).encode())


def read_manifest(path: str) -> dict:
    with open(os.path.join(path, MANIFEST_FILE)) as f:
        man = json.load(f)
    if man.get("format") != FORMAT:
        raise ValueError(
            f"{path}: not a {FORMAT} checkpoint "
            f"(format={man.get('format')!r})")
    return man


def has_manifest(path: str) -> bool:
    return os.path.isfile(os.path.join(path, MANIFEST_FILE))
