"""State observability API: list live cluster entities.

Reference parity: python/ray/experimental/state/api.py (list_actors,
list_nodes, list_placement_groups, list_workers, list_objects,
summarize_*) backed by dashboard/state_aggregator.py over GCS tables.
Here the GCS tables and per-node daemons are queried directly; works both
inside a connected driver (address=None) and standalone against a GCS
address (the CLI's mode).
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, List, Optional


def _run(coro):
    from ray_tpu import api
    if api._worker is not None:
        return api._worker.io.run(coro)
    return asyncio.run(coro)


def _gcs_address(address: Optional[str]) -> str:
    if address:
        return address
    from ray_tpu import api
    if api._worker is not None:
        return api._worker.gcs_address
    raise RuntimeError(
        "not connected: pass address= or call ray_tpu.init() first")


async def _gcs_call(address: str, method: str, req: dict | None = None):
    from ray_tpu._private.rpc import RpcClient
    from ray_tpu import api
    if api._worker is not None and address == api._worker.gcs_address:
        return await api._worker.gcs.call("Gcs", method, req or {})
    client = RpcClient(address)
    try:
        return await client.call("Gcs", method, req or {}, timeout=30)
    finally:
        await client.close()


def list_nodes(address: Optional[str] = None) -> List[Dict[str, Any]]:
    addr = _gcs_address(address)
    reply = _run(_gcs_call(addr, "get_nodes"))
    return [{
        "node_id": n.node_id.hex(),
        "address": n.address,
        "alive": n.alive,
        "is_head": n.is_head,
        "resources_total": dict(n.resources_total),
        "resources_available": dict(n.resources_available),
    } for n in reply["nodes"]]


def list_actors(address: Optional[str] = None) -> List[Dict[str, Any]]:
    addr = _gcs_address(address)
    reply = _run(_gcs_call(addr, "list_actors"))
    out = []
    for a in reply["actors"]:
        out.append({
            "actor_id": a.actor_id.hex(),
            "class_name": a.class_name,
            "state": a.state,
            "name": a.name or None,
            "namespace": a.namespace or None,
            "node_id": a.node_id.hex() if a.node_id else None,
            "num_restarts": a.num_restarts,
            "death_cause": a.death_cause or None,
        })
    return out


def list_placement_groups(address: Optional[str] = None
                          ) -> List[Dict[str, Any]]:
    addr = _gcs_address(address)
    reply = _run(_gcs_call(addr, "list_placement_groups"))
    return [{
        "placement_group_id": p.pg_id.hex(),
        "state": p.state,
        "strategy": p.strategy,
        "bundles": list(p.bundles),
        "bundle_nodes": [n.hex() if n else None for n in p.bundle_nodes],
    } for p in reply["placement_groups"]]


async def _each_node(address: str, service: str, method: str,
                     req: dict | None = None) -> Dict[str, Any]:
    from ray_tpu._private.rpc import RpcClient
    nodes = (await _gcs_call(address, "get_nodes"))["nodes"]
    out = {}
    for n in nodes:
        if not n.alive:
            continue
        client = RpcClient(n.address)
        try:
            out[n.node_id.hex()] = await client.call(
                service, method, req or {}, timeout=10)
        except Exception as e:
            out[n.node_id.hex()] = {"error": repr(e)}
        finally:
            await client.close()
    return out


def list_workers(address: Optional[str] = None) -> List[Dict[str, Any]]:
    """Worker processes across every alive node."""
    addr = _gcs_address(address)
    per_node = _run(_each_node(addr, "NodeManager", "ListWorkers"))
    out = []
    for node_id, reply in per_node.items():
        for w in reply.get("workers", []):
            out.append({"node_id": node_id, **w})
    return out


def list_objects(address: Optional[str] = None) -> List[Dict[str, Any]]:
    """Object-store summary per node (per-object enumeration requires the
    owner's table; the connected driver's own objects are included)."""
    addr = _gcs_address(address)
    per_node = _run(_each_node(addr, "NodeManager", "StoreStats"))
    out = [{"node_id": nid, **stats} for nid, stats in per_node.items()]
    from ray_tpu import api
    if api._worker is not None:
        w = api._worker
        for oid, st in list(w.objects.items()):
            out.append({
                "object_id": oid.hex(), "owner": "self",
                "pending": st.pending, "pins": st.pins,
                "local_refs": st.local_refs,
                "locations": [l.hex() if hasattr(l, "hex") else str(l)
                              for l in st.locations],
            })
    return out


def list_tasks(address: Optional[str] = None,
               limit: int = 10000) -> List[Dict[str, Any]]:
    """Recently executed tasks from the GCS task-event sink (reference:
    experimental/state/api.py list_tasks over task events)."""
    addr = _gcs_address(address)
    reply = _run(_gcs_call(addr, "get_task_events", {"limit": limit}))
    return list(reply.get("events", []))


# A node's clock may be ahead of the caller's by up to this much without
# its fresh events being pre-filtered away at the remote ring.  The raw
# `since` forwarded to each node is widened by this slack — it is only a
# bandwidth optimization; the authoritative cutoff is applied locally on
# the skew-adjusted ts_adj.
_SKEW_SLACK_S = 300.0


def _normalize_events_reply(reply: Dict[str, Any], node_id: str,
                            t0: float, t1: float) -> List[Dict[str, Any]]:
    """Put one node's CollectEvents reply on the caller's clock.

    The RPC midpoint approximates the remote `now` locally, so
    ``ts_adj = ts + (local_midpoint - remote_now)`` (NTP-grade, good
    enough to order cross-node decision sequences)."""
    mid = (t0 + t1) / 2.0
    offset = mid - reply.get("now", mid)
    out = []
    for e in reply.get("events", []):
        e = dict(e)
        e["node_id"] = node_id
        e["ts_adj"] = e["ts"] + offset
        out.append(e)
    return out


def _merge_event_streams(streams: List[List[Dict[str, Any]]], *,
                         plane: Optional[str] = None,
                         kind: Optional[str] = None,
                         trace_id: Optional[str] = None,
                         since: float = 0.0) -> List[Dict[str, Any]]:
    """Pure merge of already-normalized per-process event streams:
    dedup by (pid, seq) preferring live copies over crash-dump copies
    of the same event, apply every filter AFTER normalization (`since`
    compares ts_adj, never the raw per-process ts), order by ts_adj."""
    best: Dict[tuple, Dict[str, Any]] = {}
    extra: List[Dict[str, Any]] = []
    for stream in streams:
        for e in stream:
            key = (e.get("pid"), e.get("seq"))
            if key[0] is None or key[1] is None:
                extra.append(e)
                continue
            cur = best.get(key)
            if cur is None or (cur.get("source") == "crash"
                               and e.get("source") != "crash"):
                best[key] = e
    evs = list(best.values()) + extra
    evs = [e for e in evs
           if e.get("ts_adj", e["ts"]) >= since
           and (plane is None or e.get("plane") == plane)
           and (kind is None or e.get("kind") == kind)
           and (trace_id is None or e.get("trace_id") == trace_id)]
    evs.sort(key=lambda e: (e.get("ts_adj", e["ts"]),
                            str(e.get("pid")), e.get("seq") or 0))
    return evs


def events(address: Optional[str] = None, *, plane: Optional[str] = None,
           kind: Optional[str] = None, trace_id: Optional[str] = None,
           since: float = 0.0) -> List[Dict[str, Any]]:
    """Cluster-wide flight-recorder aggregation: every node's
    CollectEvents scrape (the hostd ring + live worker rings + crash
    dumps from dead processes) plus the connected driver's own ring,
    time-skew normalized and merged into one ordered stream.

    Filter semantics: `since` (like the ordering) applies to the
    skew-adjusted ``ts_adj`` after the merge — a node whose clock runs
    behind the caller's cannot leak stale events past the cutoff, and
    one running ahead cannot hide fresh ones.  The remote rings are
    pre-filtered with a widened window (`_SKEW_SLACK_S`) purely to
    bound reply size."""
    import os
    import time as _time

    addr = _gcs_address(address)
    pre_since = max(0.0, since - _SKEW_SLACK_S)

    async def _collect():
        from ray_tpu._private.rpc import RpcClient
        nodes = (await _gcs_call(addr, "get_nodes"))["nodes"]
        streams: List[List[Dict[str, Any]]] = []
        for n in nodes:
            if not n.alive:
                continue
            client = RpcClient(n.address)
            try:
                t0 = _time.time()
                reply = await client.call(
                    "NodeManager", "CollectEvents", {"since": pre_since},
                    timeout=10)
                t1 = _time.time()
            except Exception:
                continue
            finally:
                await client.close()
            streams.append(_normalize_events_reply(
                reply, n.node_id.hex(), t0, t1))
        # The GCS runs in its own process with its own ring (gcs/flush
        # spans, actor-manager events) that no hostd scrapes.
        client = RpcClient(addr)
        try:
            t0 = _time.time()
            reply = await client.call("Gcs", "collect_events",
                                      {"since": pre_since}, timeout=10)
            t1 = _time.time()
            streams.append(_normalize_events_reply(reply, "gcs", t0, t1))
        except Exception:
            pass
        finally:
            await client.close()
        return streams

    streams = _run(_collect())
    # The caller's own ring: serve routers and train drivers record from
    # the driver process, which no hostd scrapes.  The driver's clock IS
    # the reference clock, so ts_adj == ts.
    from ray_tpu import api
    from ray_tpu.util import events as ev
    # Included whenever this process is connected — even with an explicit
    # address (the in-process CLI path): the driver ring holds the
    # submit-side spans no hostd can see.
    if api._worker is not None:
        driver_pid = os.getpid()
        streams.append([
            dict(e, pid=driver_pid, source="live", node_id="driver",
                 ts_adj=e["ts"])
            for e in ev.snapshot(since=pre_since)])
    return _merge_event_streams(streams, plane=plane, kind=kind,
                                trace_id=trace_id, since=since)


# ---------------------------------------------------------------------------
# Spans: durational reconstruction over the merged event stream
# ---------------------------------------------------------------------------


def build_spans(evs: List[Dict[str, Any]],
                trace_id: Optional[str] = None
                ) -> tuple[Dict[str, Dict[str, Any]],
                           List[Dict[str, Any]]]:
    """Pair ``ph="B"``/``ph="E"`` events from a merged, ts_adj-ordered
    stream into span records and link them into trees.

    Tolerant by construction: events may arrive out of order (fields
    just fill in), a missing begin (ring overflow dropped it) marks the
    span ``truncated`` and back-dates its start from the end event's
    ``dur``, and a missing end marks it ``torn`` and terminates it at
    its process's crash-dump time (the black box pins when the process
    died) or, failing that, at the observation horizon.  Returns
    ``(spans_by_sid, roots)`` — roots are spans whose parent is absent
    from the stream (including spans orphaned by overflow)."""
    crash_time: Dict[Any, float] = {}
    horizon = 0.0
    for e in evs:
        t = e.get("ts_adj", e["ts"])
        if t > horizon:
            horizon = t
        if e.get("source") == "crash":
            p = e.get("pid")
            if t > crash_time.get(p, 0.0):
                crash_time[p] = t
    table: Dict[str, Dict[str, Any]] = {}
    for e in evs:
        pl = e.get("payload") or {}
        ph = pl.get("ph")
        if ph not in ("B", "E"):
            continue
        if trace_id is not None and e.get("trace_id") != trace_id:
            continue
        sid = e.get("span_id")
        if sid is None:
            continue
        rec = table.get(sid)
        if rec is None:
            rec = table[sid] = {
                "sid": sid, "trace_id": e.get("trace_id"),
                "plane": e.get("plane"), "kind": e.get("kind"),
                "parent": None, "start": None, "end": None, "dur": None,
                "pid": e.get("pid"), "node_id": e.get("node_id"),
                "torn": False, "truncated": False, "payload": {},
                "children": [],
            }
        if ph == "B":
            rec["start"] = e.get("ts_adj", e["ts"])
            rec["parent"] = pl.get("parent")
            rec["pid"] = e.get("pid")
            rec["node_id"] = e.get("node_id")
        else:
            rec["end"] = e.get("ts_adj", e["ts"])
            rec["dur"] = pl.get("dur")
        for k, v in pl.items():
            if k not in ("ph", "parent", "dur"):
                rec["payload"][k] = v
    for rec in table.values():
        if rec["start"] is None:
            rec["truncated"] = True
            if rec["end"] is not None and rec["dur"] is not None:
                rec["start"] = rec["end"] - rec["dur"]
            else:
                rec["start"] = rec["end"]
        if rec["end"] is None:
            rec["torn"] = True
            t = crash_time.get(rec["pid"])
            if t is not None and rec["start"] is not None \
                    and t >= rec["start"]:
                rec["end"] = t
            else:
                rec["end"] = max(horizon, rec["start"] or 0.0)
        if rec["dur"] is None and rec["start"] is not None \
                and rec["end"] is not None:
            rec["dur"] = rec["end"] - rec["start"]
    roots: List[Dict[str, Any]] = []
    ordered = sorted(table.values(),
                     key=lambda r: (r["start"] is None, r["start"] or 0.0))
    for rec in ordered:
        p = rec["parent"]
        if p is not None and p != rec["sid"] and p in table:
            table[p]["children"].append(rec)
        else:
            roots.append(rec)
    return table, roots


def spans(trace_id: str, address: Optional[str] = None, *,
          since: float = 0.0) -> Dict[str, Any]:
    """Cluster-wide span tree for one trace: scrape every ring + crash
    dump, normalize clocks, pair begins/ends, link parents.  The result
    is rooted (a synthetic root is added when the trace's own root span
    was lost) and annotated with torn/truncated markers."""
    evs = events(address, since=since)
    table, roots = build_spans(evs, trace_id)
    flat = sorted(table.values(), key=lambda r: r["start"] or 0.0)
    torn = sum(1 for r in flat if r["torn"])
    if not flat:
        return {"trace_id": trace_id, "root": None, "spans": [],
                "torn": 0}
    if len(roots) == 1:
        root = roots[0]
    else:
        root = {
            "sid": "(root)", "trace_id": trace_id, "plane": "proc",
            "kind": "trace", "parent": None,
            "start": min(r["start"] for r in flat),
            "end": max(r["end"] for r in flat),
            "pid": None, "node_id": None, "torn": False,
            "truncated": True, "payload": {}, "children": roots,
        }
        root["dur"] = root["end"] - root["start"]
    return {"trace_id": trace_id, "root": root, "spans": flat,
            "torn": torn}


def _critical_segments(node: Dict[str, Any], lo: float, hi: float,
                       segs: List[Dict[str, Any]], depth: int = 0) -> None:
    """Append segments attributing (lo, hi] along the critical path, in
    reverse time order: walk backward from `hi`, descend into the child
    that ends latest before the cursor, and charge gaps between
    children to the node itself."""
    if depth > 64 or hi - lo <= 0:
        return
    cursor = hi
    kids = [c for c in node.get("children", [])
            if c.get("start") is not None and c.get("end") is not None
            and c["end"] > lo and c["start"] < hi]
    while cursor - lo > 1e-9:
        best = None
        for c in kids:
            if c["start"] >= cursor:
                continue
            if best is None or min(c["end"], cursor) > \
                    min(best["end"], cursor):
                best = c
        if best is None:
            segs.append({"sid": node["sid"], "plane": node.get("plane"),
                         "kind": node["kind"], "start": lo, "end": cursor,
                         "torn": bool(node.get("torn"))})
            return
        ce = min(best["end"], cursor)
        if cursor - ce > 1e-9:
            segs.append({"sid": node["sid"], "plane": node.get("plane"),
                         "kind": node["kind"], "start": ce, "end": cursor,
                         "torn": bool(node.get("torn"))})
        cs = max(best["start"], lo)
        _critical_segments(best, cs, ce, segs, depth + 1)
        cursor = cs
        kids = [c for c in kids if c is not best and c["start"] < cursor]


def critical_path(trace_id: str, address: Optional[str] = None, *,
                  since: float = 0.0) -> Dict[str, Any]:
    """The sequence of spans that bound this trace's wall clock: at any
    instant, the deepest span covering it on the latest-ending-child
    walk.  Shrinking any segment on the path shrinks the trace."""
    tree = spans(trace_id, address, since=since)
    root = tree["root"]
    if root is None:
        return {"trace_id": trace_id, "wall": 0.0, "segments": [],
                "by_kind": {}, "torn": 0}
    segs: List[Dict[str, Any]] = []
    _critical_segments(root, root["start"], root["end"], segs)
    segs.reverse()
    by_kind: Dict[str, float] = {}
    for s in segs:
        k = f'{s["plane"]}:{s["kind"]}'
        by_kind[k] = by_kind.get(k, 0.0) + (s["end"] - s["start"])
    by_kind = dict(sorted(by_kind.items(), key=lambda kv: -kv[1]))
    return {"trace_id": trace_id, "wall": root["end"] - root["start"],
            "segments": segs, "by_kind": by_kind, "torn": tree["torn"]}


def _pctile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(q * len(sorted_vals) + 0.5) - 1))
    return sorted_vals[idx]


def build_breakdown(evs: List[Dict[str, Any]], *,
                    plane: Optional[str] = None,
                    trace_id: Optional[str] = None) -> Dict[str, Any]:
    """Aggregate per-(plane, kind) span durations from a merged stream:
    count / p50 / p95 / p99 / total seconds and fraction of the
    observed wall clock.  Root `trace` scopes are excluded (they span
    the whole window and would attribute everything twice)."""
    table, _ = build_spans(evs, trace_id)
    lo = hi = None
    groups: Dict[tuple, List[float]] = {}
    for rec in table.values():
        if rec["start"] is None or rec["end"] is None:
            continue
        if lo is None or rec["start"] < lo:
            lo = rec["start"]
        if hi is None or rec["end"] > hi:
            hi = rec["end"]
        if rec["kind"] == "trace":
            continue
        if plane is not None and rec["plane"] != plane:
            continue
        groups.setdefault((rec["plane"], rec["kind"]), []).append(
            rec["dur"] if rec["dur"] is not None
            else rec["end"] - rec["start"])
    wall = (hi - lo) if lo is not None else 0.0
    phases = []
    for (pl, kd), durs in groups.items():
        durs.sort()
        total = sum(durs)
        phases.append({
            "plane": pl, "kind": kd, "count": len(durs),
            "p50": _pctile(durs, 0.5), "p95": _pctile(durs, 0.95),
            "p99": _pctile(durs, 0.99), "max": durs[-1],
            "total": total,
            "fraction": (total / wall) if wall > 0 else 0.0,
        })
    phases.sort(key=lambda r: -r["total"])
    return {"wall": wall, "window": (lo, hi), "phases": phases}


def latency_breakdown(address: Optional[str] = None, *,
                      plane: Optional[str] = None,
                      trace_id: Optional[str] = None,
                      since: float = 0.0) -> Dict[str, Any]:
    """Cluster-wide per-phase latency attribution: every span kind's
    p50/p95/p99/total and fraction of wall clock, ranked.  `plane`
    narrows to one plane; `trace_id` narrows to one trace."""
    evs = events(address, since=since)
    return build_breakdown(evs, plane=plane, trace_id=trace_id)


def timeline(address: Optional[str] = None,
             include_events: bool = False) -> List[Dict[str, Any]]:
    """Chrome trace events (chrome://tracing / perfetto 'X' phases) —
    reference: `ray timeline` scripts.py:1840.  With `include_events`
    the flight-recorder stream is merged in as instant events, so one
    trace shows tasks AND the runtime decisions around them."""
    task_events = list_tasks(address)
    out = []
    for e in task_events:
        out.append({
            "name": e["name"],
            "cat": "actor_task" if e.get("actor_id") else "task",
            "ph": "X",
            "ts": e["start"] * 1e6,
            "dur": max(e["end"] - e["start"], 1e-6) * 1e6,
            "pid": f'{e.get("node_id", "")}:{e.get("pid", 0)}',
            "tid": e.get("worker_id", ""),
            "args": {"task_id": e.get("task_id"),
                     "actor_id": e.get("actor_id")},
        })
    if include_events:
        for e in events(address):
            out.append({
                "name": f'{e["plane"]}:{e["kind"]}',
                "cat": f'event:{e["plane"]}',
                "ph": "i",
                "s": "p",
                "ts": e.get("ts_adj", e["ts"]) * 1e6,
                "pid": f'{e.get("node_id", "")}:{e.get("pid", 0)}',
                "tid": e.get("source", "live"),
                "args": {"payload": e.get("payload"),
                         "trace_id": e.get("trace_id"),
                         "span_id": e.get("span_id")},
            })
    return out


def cluster_metrics(address: Optional[str] = None) -> Dict[str, Any]:
    """Per-process metric snapshots: GCS + every alive node daemon
    (reference: state aggregation over per-node metrics agents)."""
    addr = _gcs_address(address)
    gcs = _run(_gcs_call(addr, "get_metrics"))
    per_node = _run(_each_node(addr, "NodeManager", "Metrics"))
    return {"gcs": gcs.get("metrics", {}),
            "nodes": {nid: r.get("metrics", {})
                      for nid, r in per_node.items()}}


def prometheus_metrics(address: Optional[str] = None) -> str:
    """Cluster-wide Prometheus exposition text."""
    from ray_tpu.util import metrics as mt
    snap = cluster_metrics(address)
    out = [mt.prometheus_text(snap["gcs"], {"component": "gcs"})]
    for nid, m in snap["nodes"].items():
        out.append(mt.prometheus_text(
            m, {"component": "hostd", "node_id": nid[:12]}))
    return "".join(out)


def summarize_cluster(address: Optional[str] = None) -> Dict[str, Any]:
    addr = _gcs_address(address)
    nodes = list_nodes(addr)
    actors = list_actors(addr)
    pgs = list_placement_groups(addr)
    total: Dict[str, float] = {}
    avail: Dict[str, float] = {}
    for n in nodes:
        if not n["alive"]:
            continue
        for k, v in n["resources_total"].items():
            total[k] = total.get(k, 0.0) + v
        for k, v in n["resources_available"].items():
            avail[k] = avail.get(k, 0.0) + v
    by_state: Dict[str, int] = {}
    for a in actors:
        by_state[a["state"]] = by_state.get(a["state"], 0) + 1
    return {
        "nodes_alive": sum(n["alive"] for n in nodes),
        "nodes_dead": sum(not n["alive"] for n in nodes),
        "resources_total": total,
        "resources_available": avail,
        "actors": by_state,
        "placement_groups": len(pgs),
    }


def stack_traces(address: Optional[str] = None) -> Dict[str, Any]:
    """Live per-thread Python stacks for every daemon/worker process
    (reference: `ray stack`, scripts.py:1798)."""
    addr = _gcs_address(address)
    return _run(_each_node(addr, "NodeManager", "StackTraces"))
