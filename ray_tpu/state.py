"""State observability API: list live cluster entities.

Reference parity: python/ray/experimental/state/api.py (list_actors,
list_nodes, list_placement_groups, list_workers, list_objects,
summarize_*) backed by dashboard/state_aggregator.py over GCS tables.
Here the GCS tables and per-node daemons are queried directly; works both
inside a connected driver (address=None) and standalone against a GCS
address (the CLI's mode).
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, List, Optional


def _run(coro):
    from ray_tpu import api
    if api._worker is not None:
        return api._worker.io.run(coro)
    return asyncio.run(coro)


def _gcs_address(address: Optional[str]) -> str:
    if address:
        return address
    from ray_tpu import api
    if api._worker is not None:
        return api._worker.gcs_address
    raise RuntimeError(
        "not connected: pass address= or call ray_tpu.init() first")


async def _gcs_call(address: str, method: str, req: dict | None = None):
    from ray_tpu._private.rpc import RpcClient
    from ray_tpu import api
    if api._worker is not None and address == api._worker.gcs_address:
        return await api._worker.gcs.call("Gcs", method, req or {})
    client = RpcClient(address)
    try:
        return await client.call("Gcs", method, req or {}, timeout=30)
    finally:
        await client.close()


def list_nodes(address: Optional[str] = None) -> List[Dict[str, Any]]:
    addr = _gcs_address(address)
    reply = _run(_gcs_call(addr, "get_nodes"))
    return [{
        "node_id": n.node_id.hex(),
        "address": n.address,
        "alive": n.alive,
        "is_head": n.is_head,
        "resources_total": dict(n.resources_total),
        "resources_available": dict(n.resources_available),
    } for n in reply["nodes"]]


def list_actors(address: Optional[str] = None) -> List[Dict[str, Any]]:
    addr = _gcs_address(address)
    reply = _run(_gcs_call(addr, "list_actors"))
    out = []
    for a in reply["actors"]:
        out.append({
            "actor_id": a.actor_id.hex(),
            "class_name": a.class_name,
            "state": a.state,
            "name": a.name or None,
            "namespace": a.namespace or None,
            "node_id": a.node_id.hex() if a.node_id else None,
            "num_restarts": a.num_restarts,
            "death_cause": a.death_cause or None,
        })
    return out


def list_placement_groups(address: Optional[str] = None
                          ) -> List[Dict[str, Any]]:
    addr = _gcs_address(address)
    reply = _run(_gcs_call(addr, "list_placement_groups"))
    return [{
        "placement_group_id": p.pg_id.hex(),
        "state": p.state,
        "strategy": p.strategy,
        "bundles": list(p.bundles),
        "bundle_nodes": [n.hex() if n else None for n in p.bundle_nodes],
    } for p in reply["placement_groups"]]


async def _each_node(address: str, service: str, method: str,
                     req: dict | None = None) -> Dict[str, Any]:
    from ray_tpu._private.rpc import RpcClient
    nodes = (await _gcs_call(address, "get_nodes"))["nodes"]
    out = {}
    for n in nodes:
        if not n.alive:
            continue
        client = RpcClient(n.address)
        try:
            out[n.node_id.hex()] = await client.call(
                service, method, req or {}, timeout=10)
        except Exception as e:
            out[n.node_id.hex()] = {"error": repr(e)}
        finally:
            await client.close()
    return out


def list_workers(address: Optional[str] = None) -> List[Dict[str, Any]]:
    """Worker processes across every alive node."""
    addr = _gcs_address(address)
    per_node = _run(_each_node(addr, "NodeManager", "ListWorkers"))
    out = []
    for node_id, reply in per_node.items():
        for w in reply.get("workers", []):
            out.append({"node_id": node_id, **w})
    return out


def list_objects(address: Optional[str] = None) -> List[Dict[str, Any]]:
    """Object-store summary per node (per-object enumeration requires the
    owner's table; the connected driver's own objects are included)."""
    addr = _gcs_address(address)
    per_node = _run(_each_node(addr, "NodeManager", "StoreStats"))
    out = [{"node_id": nid, **stats} for nid, stats in per_node.items()]
    from ray_tpu import api
    if api._worker is not None:
        w = api._worker
        for oid, st in list(w.objects.items()):
            out.append({
                "object_id": oid.hex(), "owner": "self",
                "pending": st.pending, "pins": st.pins,
                "local_refs": st.local_refs,
                "locations": [l.hex() if hasattr(l, "hex") else str(l)
                              for l in st.locations],
            })
    return out


def list_tasks(address: Optional[str] = None,
               limit: int = 10000) -> List[Dict[str, Any]]:
    """Recently executed tasks from the GCS task-event sink (reference:
    experimental/state/api.py list_tasks over task events)."""
    addr = _gcs_address(address)
    reply = _run(_gcs_call(addr, "get_task_events", {"limit": limit}))
    return list(reply.get("events", []))


def events(address: Optional[str] = None, *, plane: Optional[str] = None,
           kind: Optional[str] = None, trace_id: Optional[str] = None,
           since: float = 0.0) -> List[Dict[str, Any]]:
    """Cluster-wide flight-recorder aggregation: every node's
    CollectEvents scrape (the hostd ring + live worker rings + crash
    dumps from dead processes) plus the connected driver's own ring,
    time-skew normalized and merged into one ordered stream.

    Skew normalization: each node reply carries its wall clock (`now`);
    the RPC midpoint approximates the same instant locally, so
    ``ts_adj = ts + (local_midpoint - remote_now)`` puts every node's
    events on the caller's clock (NTP-grade, good enough to order
    cross-node decision sequences).  Filters: plane / kind / trace_id /
    since (raw remote ts)."""
    import os
    import time as _time

    addr = _gcs_address(address)

    async def _collect():
        from ray_tpu._private.rpc import RpcClient
        nodes = (await _gcs_call(addr, "get_nodes"))["nodes"]
        out: List[Dict[str, Any]] = []
        for n in nodes:
            if not n.alive:
                continue
            client = RpcClient(n.address)
            try:
                t0 = _time.time()
                reply = await client.call(
                    "NodeManager", "CollectEvents", {"since": since},
                    timeout=10)
                t1 = _time.time()
            except Exception:
                continue
            finally:
                await client.close()
            mid = (t0 + t1) / 2.0
            offset = mid - reply.get("now", mid)
            for e in reply.get("events", []):
                e = dict(e)
                e["node_id"] = n.node_id.hex()
                e["ts_adj"] = e["ts"] + offset
                out.append(e)
        return out

    evs = _run(_collect())
    # The caller's own ring: serve routers and train drivers record from
    # the driver process, which no hostd scrapes.
    from ray_tpu import api
    from ray_tpu.util import events as ev
    if api._worker is not None and address is None:
        driver_pid = os.getpid()
        seen = {(e.get("pid"), e.get("seq")) for e in evs}
        for e in ev.snapshot(since=since):
            if (driver_pid, e.get("seq")) in seen:
                continue
            evs.append(dict(e, pid=driver_pid, source="live",
                            node_id="driver", ts_adj=e["ts"]))
    evs = [e for e in evs
           if (plane is None or e.get("plane") == plane)
           and (kind is None or e.get("kind") == kind)
           and (trace_id is None or e.get("trace_id") == trace_id)]
    evs.sort(key=lambda e: e.get("ts_adj", e["ts"]))
    return evs


def timeline(address: Optional[str] = None,
             include_events: bool = False) -> List[Dict[str, Any]]:
    """Chrome trace events (chrome://tracing / perfetto 'X' phases) —
    reference: `ray timeline` scripts.py:1840.  With `include_events`
    the flight-recorder stream is merged in as instant events, so one
    trace shows tasks AND the runtime decisions around them."""
    task_events = list_tasks(address)
    out = []
    for e in task_events:
        out.append({
            "name": e["name"],
            "cat": "actor_task" if e.get("actor_id") else "task",
            "ph": "X",
            "ts": e["start"] * 1e6,
            "dur": max(e["end"] - e["start"], 1e-6) * 1e6,
            "pid": f'{e.get("node_id", "")}:{e.get("pid", 0)}',
            "tid": e.get("worker_id", ""),
            "args": {"task_id": e.get("task_id"),
                     "actor_id": e.get("actor_id")},
        })
    if include_events:
        for e in events(address):
            out.append({
                "name": f'{e["plane"]}:{e["kind"]}',
                "cat": f'event:{e["plane"]}',
                "ph": "i",
                "s": "p",
                "ts": e.get("ts_adj", e["ts"]) * 1e6,
                "pid": f'{e.get("node_id", "")}:{e.get("pid", 0)}',
                "tid": e.get("source", "live"),
                "args": {"payload": e.get("payload"),
                         "trace_id": e.get("trace_id"),
                         "span_id": e.get("span_id")},
            })
    return out


def cluster_metrics(address: Optional[str] = None) -> Dict[str, Any]:
    """Per-process metric snapshots: GCS + every alive node daemon
    (reference: state aggregation over per-node metrics agents)."""
    addr = _gcs_address(address)
    gcs = _run(_gcs_call(addr, "get_metrics"))
    per_node = _run(_each_node(addr, "NodeManager", "Metrics"))
    return {"gcs": gcs.get("metrics", {}),
            "nodes": {nid: r.get("metrics", {})
                      for nid, r in per_node.items()}}


def prometheus_metrics(address: Optional[str] = None) -> str:
    """Cluster-wide Prometheus exposition text."""
    from ray_tpu.util import metrics as mt
    snap = cluster_metrics(address)
    out = [mt.prometheus_text(snap["gcs"], {"component": "gcs"})]
    for nid, m in snap["nodes"].items():
        out.append(mt.prometheus_text(
            m, {"component": "hostd", "node_id": nid[:12]}))
    return "".join(out)


def summarize_cluster(address: Optional[str] = None) -> Dict[str, Any]:
    addr = _gcs_address(address)
    nodes = list_nodes(addr)
    actors = list_actors(addr)
    pgs = list_placement_groups(addr)
    total: Dict[str, float] = {}
    avail: Dict[str, float] = {}
    for n in nodes:
        if not n["alive"]:
            continue
        for k, v in n["resources_total"].items():
            total[k] = total.get(k, 0.0) + v
        for k, v in n["resources_available"].items():
            avail[k] = avail.get(k, 0.0) + v
    by_state: Dict[str, int] = {}
    for a in actors:
        by_state[a["state"]] = by_state.get(a["state"], 0) + 1
    return {
        "nodes_alive": sum(n["alive"] for n in nodes),
        "nodes_dead": sum(not n["alive"] for n in nodes),
        "resources_total": total,
        "resources_available": avail,
        "actors": by_state,
        "placement_groups": len(pgs),
    }


def stack_traces(address: Optional[str] = None) -> Dict[str, Any]:
    """Live per-thread Python stacks for every daemon/worker process
    (reference: `ray stack`, scripts.py:1798)."""
    addr = _gcs_address(address)
    return _run(_each_node(addr, "NodeManager", "StackTraces"))
