"""Dataset: lazy, distributed, Arrow-blocked data.

Reference parity: python/ray/data/dataset.py:169 — creation in read_api.py,
transforms build a lazy plan (map/map_batches/filter/flat_map/repartition/
random_shuffle/sort/limit/union/split/groupby), consumption executes it
(take/count/iter_batches/iter_rows/to_pandas/write_*), streaming execution
with backpressure in executor.py.

TPU angle: `iter_batches(batch_format="numpy")` yields dicts of numpy
arrays sized for `global_batch` ingestion, and `split(n)` hands each
training worker its own shard — the sharded-ingest path for pods.
"""

from __future__ import annotations

import random as _random
from typing import Any, Callable, Iterator, List, Optional

import numpy as np
import pyarrow as pa

import ray_tpu
from ray_tpu.data import block as blk
from ray_tpu.data import ingest
from ray_tpu.data.executor import (
    ActorPoolStrategy,
    AllToAll, ExecPlan, OneToOne, execute, iter_output_refs)


# ---------------- per-block remote helpers (driver stays thin) -------------


@ray_tpu.remote
def _block_meta(block):
    return block.num_rows, block.schema


@ray_tpu.remote
def _agg_partial(block, col):
    vals = np.asarray(block.column(col).to_pylist())
    if vals.size == 0:
        return (0, 0.0, 0.0, None, None)
    v = vals.astype(np.float64)
    return (int(v.size), float(v.sum()), float((v * v).sum()),
            float(v.min()), float(v.max()))


@ray_tpu.remote
def _unique_partial(block, col):
    return sorted(set(block.column(col).to_pylist()))


@ray_tpu.remote
def _hash_partition(block, key, n):
    """Split a block into n hash partitions by key (stable hash)."""
    import zlib
    parts = [[] for _ in range(n)]
    for row in block.to_pylist():
        h = zlib.crc32(repr(row[key]).encode()) % n
        parts[h].append(row)
    out = tuple(blk.rows_to_block(p) for p in parts)
    return out if n > 1 else out[0]


@ray_tpu.remote
def _concat_remote(*blocks):
    return blk.concat_blocks(list(blocks))


@ray_tpu.remote
def _partition_random(block, n, seed):
    """Assign each row to one of n shuffle partitions (seeded)."""
    if n == 1:
        return block
    rng = np.random.default_rng(seed)
    assign = rng.integers(0, n, size=block.num_rows)
    return tuple(block.take(pa.array(np.nonzero(assign == j)[0]))
                 for j in range(n))


@ray_tpu.remote
def _partition_chunks(block, n):
    """Split a block into n even row-range chunks."""
    if n == 1:
        return block
    rows = block.num_rows
    per = -(-rows // n) if rows else 1
    return tuple(blk.slice_block(block, min(j * per, rows),
                                 min((j + 1) * per, rows))
                 for j in range(n))


@ray_tpu.remote
def _partition_range(block, key, boundaries):
    """Range-partition by sorted boundaries (len(boundaries)+1 parts)."""
    n = len(boundaries) + 1
    if n == 1:
        return block
    if block.num_rows == 0 or key not in block.schema.names:
        return tuple(blk.slice_block(block, 0, 0) for _ in range(n))
    col = block.column(key).to_numpy(zero_copy_only=False)
    assign = np.searchsorted(np.asarray(boundaries), col, side="right")
    return tuple(block.take(pa.array(np.nonzero(assign == j)[0]))
                 for j in range(n))


@ray_tpu.remote
def _merge_shuffled(seed, *parts):
    whole = blk.concat_blocks(list(parts))
    if whole.num_rows == 0:
        return whole
    rng = np.random.default_rng(seed)
    return whole.take(pa.array(rng.permutation(whole.num_rows)))


@ray_tpu.remote
def _merge_sorted(key, order, *parts):
    whole = blk.concat_blocks(list(parts))
    if whole.num_rows == 0:
        return whole
    return whole.take(pa.compute.sort_indices(whole,
                                              sort_keys=[(key, order)]))


@ray_tpu.remote
def _sample_keys(block, key, k):
    if block.num_rows == 0 or key not in block.schema.names:
        return []
    col = block.column(key).to_numpy(zero_copy_only=False)
    if len(col) <= k:
        return list(col)
    idx = np.random.default_rng(0).choice(len(col), size=k, replace=False)
    return list(col[idx])


@ray_tpu.remote
def _slice_remote(block, start, end):
    return blk.slice_block(block, start, end)


def _scatter_merge(refs, partitioner, merger, n):
    """Map-side partition + reduce-side merge, all in remote tasks — the
    driver moves only refs (reference: _internal/push_based_shuffle.py
    two-phase map/merge; ADVICE r1: all-to-all must not materialize on
    the driver)."""
    if not refs:
        return refs
    parts = [partitioner(r) for r in refs]
    if n == 1:
        cols = [parts]
    else:
        cols = [[parts[i][j] for i in range(len(refs))] for j in range(n)]
    return [merger(j, cols[j]) for j in range(n)]


@ray_tpu.remote
def _group_apply(block, key, fn):
    """Group a partition's rows by key and apply fn per group."""
    import collections
    groups = collections.defaultdict(list)
    for row in block.to_pylist():
        groups[row[key]].append(row)
    rows = []
    for k in sorted(groups):
        rows.extend(fn(groups[k]))
    return blk.rows_to_block(rows)


def _rechunk(table: pa.Table, n: int) -> List[pa.Table]:
    """Slice a table into up to n near-equal pieces (empty tail dropped)."""
    n = max(1, n)
    if table.num_rows == 0:
        return [table]
    per = -(-table.num_rows // n)
    return [blk.slice_block(table, i * per,
                            min((i + 1) * per, table.num_rows))
            for i in range(n) if i * per < table.num_rows]


class Dataset:
    def __init__(self, plan: ExecPlan):
        self._plan = plan
        self._materialized: Optional[List[Any]] = None

    # ----------------------------------------------------------------
    # transforms (lazy)
    # ----------------------------------------------------------------

    def _with_one_to_one(self, fn, name) -> "Dataset":
        return Dataset(self._plan.with_stage(OneToOne(fn, name)))

    def map(self, fn: Callable[[dict], dict]) -> "Dataset":
        def do(block):
            return blk.rows_to_block([fn(r) for r in blk.block_rows(block)])
        return Dataset(self._plan.with_stage(OneToOne(
            do, "map", row_preserving=True)))

    def flat_map(self, fn: Callable[[dict], list]) -> "Dataset":
        def do(block):
            out = []
            for r in blk.block_rows(block):
                out.extend(fn(r))
            return blk.rows_to_block(out)
        return self._with_one_to_one(do, "flat_map")

    def filter(self, fn: Callable[[dict], bool]) -> "Dataset":
        def do(block):
            return blk.rows_to_block(
                [r for r in blk.block_rows(block) if fn(r)])
        return self._with_one_to_one(do, "filter")

    def map_batches(self, fn: Callable, *, batch_format: str = "numpy",
                    batch_size: Optional[int] = None,
                    fn_kwargs: Optional[dict] = None,
                    compute: Optional["ActorPoolStrategy"] = None
                    ) -> "Dataset":
        """compute=ActorPoolStrategy(size=N) runs the stage on a pool of
        long-lived actors — fn may be a CLASS whose instances cache
        expensive state (model weights) across blocks (reference:
        actor_pool_map_operator.py)."""
        kwargs = fn_kwargs or {}
        callable_holder = [fn]

        def do(block):
            f = callable_holder[0]
            if isinstance(f, type):
                f = callable_holder[0] = f()  # construct once per worker
            if block.num_rows == 0:
                return block
            size = batch_size or block.num_rows
            outs = []
            for start in range(0, block.num_rows, size):
                piece = blk.slice_block(block, start,
                                        min(start + size, block.num_rows))
                batch = blk.block_to_batch(piece, batch_format)
                outs.append(blk.batch_to_block(f(batch, **kwargs)))
            return blk.concat_blocks(outs)

        if compute is not None:
            return Dataset(self._plan.with_stage(
                OneToOne(do, "map_batches", compute=compute)))
        return self._with_one_to_one(do, "map_batches")

    def add_column(self, name: str, fn: Callable) -> "Dataset":
        def do(batch):
            batch[name] = fn(batch)
            return batch
        return self.map_batches(do, batch_format="pandas")

    def drop_columns(self, cols: List[str]) -> "Dataset":
        def do(block):
            keep = [c for c in block.column_names if c not in cols]
            return block.select(keep)
        return self._with_one_to_one(do, "drop_columns")

    def select_columns(self, cols: List[str]) -> "Dataset":
        def do(block):
            return block.select(cols)
        return Dataset(self._plan.with_stage(OneToOne(
            do, "select_columns", row_preserving=True,
            projection=list(cols))))

    # ------------------------- all-to-all ---------------------------

    def repartition(self, num_blocks: int) -> "Dataset":
        def do(refs):
            n = max(1, num_blocks)
            return _scatter_merge(
                refs,
                lambda r: _partition_chunks.options(num_returns=n)
                .remote(r, n),
                lambda j, col: _concat_remote.remote(*col), n)
        return Dataset(self._plan.with_stage(AllToAll(do, "repartition")))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        def do(refs):
            n = max(1, len(refs))
            # seed=None must be nondeterministic per execution (reference
            # semantics) — draw fresh entropy at execution time.
            base = seed if seed is not None else int(
                np.random.SeedSequence().entropy % (2 ** 31))
            return _scatter_merge(
                refs,
                lambda r, _c=iter(range(len(refs))):
                    _partition_random.options(num_returns=n)
                    .remote(r, n, base + next(_c)),
                lambda j, col: _merge_shuffled.remote(base + 7919 * (j + 1),
                                                      *col), n)
        return Dataset(self._plan.with_stage(AllToAll(do, "random_shuffle")))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        def do(refs):
            n = max(1, len(refs))
            order = "descending" if descending else "ascending"
            if n == 1:
                return [_merge_sorted.remote(key, order, *refs)]
            # Sample-based range partitioning (reference: _internal/sort.py
            # sample -> boundaries -> partition -> per-range merge-sort).
            samples: list = []
            for chunk in ray_tpu.get(
                    [_sample_keys.remote(r, key, 64) for r in refs]):
                samples.extend(chunk)
            if not samples:
                return [_merge_sorted.remote(key, order, *refs)]
            samples.sort()
            bounds = [samples[(i + 1) * len(samples) // n]
                      for i in range(n - 1)]
            out = _scatter_merge(
                refs,
                lambda r: _partition_range.options(num_returns=n)
                .remote(r, key, bounds),
                lambda j, col: _merge_sorted.remote(key, order, *col), n)
            return out[::-1] if descending else out
        return Dataset(self._plan.with_stage(AllToAll(do, "sort")))

    def limit(self, n: int) -> "Dataset":
        def do(refs):
            out, seen = [], 0
            for r in refs:
                if seen >= n:
                    break
                b = ray_tpu.get(r)
                take = min(b.num_rows, n - seen)
                out.append(ray_tpu.put(blk.slice_block(b, 0, take)))
                seen += take
            return out
        return Dataset(self._plan.with_stage(
            AllToAll(do, "limit", limit_rows=n)))

    def union(self, *others: "Dataset") -> "Dataset":
        refs = list(self._execute())
        for o in others:
            refs.extend(o._execute())
        return Dataset(ExecPlan(refs))

    def split(self, n: int, *, equal: bool = False) -> List["Dataset"]:
        """Shard into n datasets (reference: dataset.split — per-worker
        ingest)."""
        refs = self._execute()
        if equal:
            # Remote slicing against global row offsets — the driver reads
            # only per-block row counts (ADVICE r1: split(equal) must not
            # concatenate the dataset in driver memory).
            counts = [c for c, _ in ray_tpu.get(
                [_block_meta.remote(r) for r in refs])]
            total = sum(counts)
            per = total // n
            shards: List[List[Any]] = [[] for _ in range(n)]
            offset = 0
            for r, c in zip(refs, counts):
                for i in range(n):
                    lo, hi = i * per, (i + 1) * per
                    s0, s1 = max(lo, offset), min(hi, offset + c)
                    if s1 > s0:
                        if s1 - s0 == c:
                            shards[i].append(r)
                        else:
                            shards[i].append(_slice_remote.remote(
                                r, s0 - offset, s1 - offset))
                offset += c
            return [Dataset(ExecPlan(s)) for s in shards]
        shards: List[List[Any]] = [[] for _ in range(n)]
        for i, r in enumerate(refs):
            shards[i % n].append(r)
        return [Dataset(ExecPlan(s)) for s in shards]

    def repeat(self, times: Optional[int] = None) -> "DatasetPipeline":
        """Epoch pipelining (reference: dataset_pipeline.py Dataset.repeat
        -> DatasetPipeline.iter_epochs): each epoch re-executes this
        dataset's lazy plan (fresh shuffles and transforms), blocks flow
        with the executor's backpressure."""
        return DatasetPipeline(self, times=times)

    def window(self, *, blocks_per_window: int = 4) -> "DatasetPipeline":
        """Windowed pipelining (reference: Dataset.window): the plan's
        input blocks split into windows processed independently, bounding
        in-flight materialization."""
        return DatasetPipeline(self, blocks_per_window=blocks_per_window)

    def streaming_split(self, n: int, *, equal: bool = False,
                        steal: bool = False, deterministic: bool = False,
                        lease_timeout_s: Optional[float] = None
                        ) -> List["DataIterator"]:
        """n independent streaming iterators, one per consumer (Train
        workers): each holds only ITS shard's block refs and pulls blocks
        with bounded prefetch — no driver round-trips during iteration
        (reference: dataset.streaming_split / DataIterator).  Picklable:
        pass them to actors.

        steal=True replaces the static per-worker lists with a
        SplitCoordinator actor that LEASES blocks dynamically: a worker
        drains its own shard first (local-store blocks first), then
        steals from the slowest peer's tail, and a dead worker's
        outstanding leases re-queue — a straggler host no longer strands
        its shard.  `deterministic=True` keeps the coordinator but serves
        each worker exactly its static shard in order (byte-identical to
        steal=False), for token-exact elastic-restore runs."""
        shards = self.split(n, equal=equal)
        if not steal:
            return [DataIterator(d._execute()) for d in shards]
        shard_refs = [d._execute() for d in shards]
        pool: List[Any] = []
        queues: List[List[int]] = []
        for refs in shard_refs:
            queues.append(list(range(len(pool), len(pool) + len(refs))))
            pool.extend(refs)
        coord = ingest.SplitCoordinator.remote(
            queues, deterministic, lease_timeout_s)
        return [CoordinatedDataIterator(pool, coord, i) for i in range(n)]

    def groupby(self, key: str) -> "GroupedData":
        return GroupedData(self, key)

    # ----------------------------------------------------------------
    # execution / consumption
    # ----------------------------------------------------------------

    def _execute(self) -> List[Any]:
        if self._materialized is None:
            self._materialized = execute(self._plan)
        return self._materialized

    def materialize(self) -> "Dataset":
        return Dataset(ExecPlan(self._execute()))

    def num_blocks(self) -> int:
        return len(self._execute())

    def count(self) -> int:
        # Metadata-only: per-block remote num_rows, never full payloads.
        metas = ray_tpu.get([_block_meta.remote(r) for r in self._execute()])
        return sum(n for n, _ in metas)

    def schema(self) -> Optional[pa.Schema]:
        metas = ray_tpu.get([_block_meta.remote(r) for r in self._execute()])
        for n, schema in metas:
            if n or schema.names:
                return schema
        return None

    def columns(self) -> List[str]:
        s = self.schema()
        return list(s.names) if s else []

    def take(self, n: int = 20) -> List[Any]:
        out = []
        for r in self._execute():
            for row in blk.block_rows(ray_tpu.get(r)):
                out.append(row)
                if len(out) >= n:
                    return out
        return out

    def take_all(self) -> List[Any]:
        out = []
        for r in self._execute():
            out.extend(blk.block_rows(ray_tpu.get(r)))
        return out

    def show(self, n: int = 20):
        for row in self.take(n):
            print(row)

    def iter_rows(self) -> Iterator[Any]:
        for r in iter_output_refs(self._plan):
            yield from blk.block_rows(ray_tpu.get(r))

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False,
                     prefetch_blocks: int = 4) -> Iterator[Any]:
        """Streaming batches with block prefetch (backpressure via the
        executor's in-flight window).  Assembly is incremental — a row
        cursor over the buffered blocks (ingest.BatchAssembler) — so
        each batch costs O(batch rows) regardless of the block-to-batch
        ratio."""
        blocks = (ray_tpu.get(r) for r in iter_output_refs(
            self._plan, window=max(1, prefetch_blocks)))
        return ingest.batches_from_block_iter(
            blocks, batch_size, batch_format, drop_last)

    def iter_torch_batches(self, **kwargs) -> Iterator[Any]:
        import torch
        for batch in self.iter_batches(**kwargs):
            yield {k: torch.as_tensor(np.asarray(v))
                   for k, v in batch.items()}

    def to_pandas(self):
        return blk.concat_blocks(ray_tpu.get(self._execute())).to_pandas()

    def to_arrow(self) -> pa.Table:
        return blk.concat_blocks(ray_tpu.get(self._execute()))

    # ------------------------- aggregates ---------------------------
    # Per-block remote partials, tiny driver-side combine — the driver
    # never fetches block payloads.

    def _partials(self, on: Optional[str]):
        col = on or blk.ITEM_COLUMN
        return ray_tpu.get([_agg_partial.remote(r, col)
                            for r in self._execute()])

    def sum(self, on: Optional[str] = None):
        return sum(p[1] for p in self._partials(on))

    def min(self, on: Optional[str] = None):
        mins = [p[3] for p in self._partials(on) if p[3] is not None]
        if not mins:
            raise ValueError("min() of an empty dataset")
        return min(mins)

    def max(self, on: Optional[str] = None):
        maxs = [p[4] for p in self._partials(on) if p[4] is not None]
        if not maxs:
            raise ValueError("max() of an empty dataset")
        return max(maxs)

    def mean(self, on: Optional[str] = None):
        ps = self._partials(on)
        n = sum(p[0] for p in ps)
        if n == 0:
            raise ValueError("mean() of an empty dataset")
        return sum(p[1] for p in ps) / n

    def std(self, on: Optional[str] = None):
        ps = self._partials(on)
        n = sum(p[0] for p in ps)
        if n < 2:
            raise ValueError("std() needs at least 2 rows")
        total = sum(p[1] for p in ps)
        sumsq = sum(p[2] for p in ps)
        return float(np.sqrt((sumsq - total * total / n) / (n - 1)))

    def unique(self, column: str) -> List[Any]:
        parts = ray_tpu.get([_unique_partial.remote(r, column)
                             for r in self._execute()])
        out = set()
        for p in parts:
            out.update(p)
        return sorted(out)

    # ------------------------- writes -------------------------------

    def write_parquet(self, path: str):
        import os
        import pyarrow.parquet as pq
        os.makedirs(path, exist_ok=True)
        for i, r in enumerate(self._execute()):
            b = ray_tpu.get(r)
            if b.num_rows:
                pq.write_table(b, os.path.join(path, f"part-{i:05d}.parquet"))

    def write_csv(self, path: str):
        import os
        import pyarrow.csv as pcsv
        os.makedirs(path, exist_ok=True)
        for i, r in enumerate(self._execute()):
            b = ray_tpu.get(r)
            if b.num_rows:
                pcsv.write_csv(b, os.path.join(path, f"part-{i:05d}.csv"))

    def write_json(self, path: str):
        import json
        import os
        os.makedirs(path, exist_ok=True)
        for i, r in enumerate(self._execute()):
            b = ray_tpu.get(r)
            if b.num_rows:
                with open(os.path.join(path, f"part-{i:05d}.json"), "w") as f:
                    for row in b.to_pylist():
                        f.write(json.dumps(row) + "\n")

    def explain(self) -> str:
        """The logical plan + the optimizer's pushdown decisions, without
        executing anything (reference: logical-plan inspection)."""
        from ray_tpu.data import logical
        return logical.explain(self._plan)

    def __repr__(self):
        src = self._plan.source
        head = (f"source={src.describe()}" if src is not None
                else f"num_blocks={len(self._plan.input_refs)}+")
        return (f"Dataset({head}, "
                f"stages={[getattr(s, 'name', '?') for s in self._plan.stages]})")


class GroupedData:
    """Hash-partitioned distributed groupby (reference:
    data/grouped_data.py over push_based_shuffle.py): each block hash-
    partitions by key remotely, partitions merge remotely (group keys are
    disjoint across partitions), and per-group work runs as one task per
    partition — the driver only touches refs and tiny aggregate rows."""

    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def _partitions(self) -> List[Any]:
        refs = self._ds._execute()
        n = max(1, len(refs))
        if n == 1:
            return list(refs)
        parts = [_hash_partition.options(num_returns=n).remote(
            r, self._key, n) for r in refs]
        return [_concat_remote.remote(*[row[p] for row in parts])
                for p in range(n)]

    def _apply(self, fn: Callable[[list], list]) -> Dataset:
        out = [_group_apply.remote(p, self._key, fn)
               for p in self._partitions()]
        result = Dataset(ExecPlan(out))
        return result.sort(self._key)

    def count(self) -> Dataset:
        key = self._key
        return self._apply(
            lambda rows: [{key: rows[0][key], "count()": len(rows)}])

    def sum(self, on: str) -> Dataset:
        key = self._key
        return self._apply(
            lambda rows: [{key: rows[0][key],
                           f"sum({on})": sum(r[on] for r in rows)}])

    def mean(self, on: str) -> Dataset:
        key = self._key
        return self._apply(
            lambda rows: [{key: rows[0][key],
                           f"mean({on})": sum(r[on] for r in rows)
                           / len(rows)}])

    def min(self, on: str) -> Dataset:
        key = self._key
        return self._apply(
            lambda rows: [{key: rows[0][key],
                           f"min({on})": min(r[on] for r in rows)}])

    def max(self, on: str) -> Dataset:
        key = self._key
        return self._apply(
            lambda rows: [{key: rows[0][key],
                           f"max({on})": max(r[on] for r in rows)}])

    def map_groups(self, fn: Callable[[list], list]) -> Dataset:
        out = [_group_apply.remote(p, self._key, fn)
               for p in self._partitions()]
        return Dataset(ExecPlan(out))


def _batches_from_refs(refs, batch_size, batch_format, drop_last,
                       prefetch: int = 4):
    """Yield batches from block refs with bounded touch-ahead prefetch.
    Assembly is incremental (ingest.BatchAssembler): O(batch rows) per
    batch, where the old path re-concatenated the whole buffered tail."""
    return ingest.batches_from_block_iter(
        ingest.iter_blocks_from_refs(refs, prefetch),
        batch_size, batch_format, drop_last)


class DataIterator:
    """A shard's streaming view (reference: data/dataset_iterator.py).
    Holds block refs only; safe to ship to a worker actor."""

    def __init__(self, refs: List[Any]):
        self._refs = list(refs)

    def _block_iter(self, prefetch: int = 4) -> Iterator[Any]:
        """Materialized blocks, in shard order, with bounded touch-ahead
        (subclasses may source blocks elsewhere, e.g. a lease
        coordinator)."""
        return ingest.iter_blocks_from_refs(self._refs, prefetch)

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "numpy", drop_last: bool = False,
                     prefetch_blocks: int = 4) -> Iterator[Any]:
        return ingest.batches_from_block_iter(
            self._block_iter(prefetch_blocks), batch_size, batch_format,
            drop_last)

    def iter_device_batches(self, *, sharding=None, batch_size: int = 256,
                            drop_last: bool = False,
                            prefetch_blocks: Optional[int] = None,
                            queue_depth: Optional[int] = None,
                            device_buffers: Optional[int] = None
                            ) -> "ingest.DeviceBatchIterator":
        """Overlapped device feed: a background thread fetches blocks and
        assembles numpy batches into a bounded queue, and the returned
        iterator keeps `device_buffers` (default 2) batches in flight on
        the accelerator — while the jitted step consumes batch k, batch
        k+1's jax.device_put has already been dispatched, so the device
        never waits on fetch+assemble+H2D.  Batches are numerically
        identical to iter_batches(batch_format="numpy").

        `sharding` may be None (default device), a jax.sharding.Sharding
        (every column), a Mesh (per-column ("batch", "length") layout via
        parallel.sharding.batch_shardings), or a dict column -> Sharding.
        Defaults for the knobs come from the ingest_* config flags."""
        from ray_tpu._private.config import GLOBAL_CONFIG as cfg
        prefetch = (prefetch_blocks if prefetch_blocks is not None
                    else cfg.ingest_prefetch_blocks)
        producer = ingest.BatchProducer(
            self._block_iter(prefetch), batch_size, "numpy", drop_last,
            queue_depth)
        return ingest.DeviceBatchIterator(producer, sharding, device_buffers)

    def iter_rows(self) -> Iterator[Any]:
        for b in self._block_iter():
            yield from blk.block_rows(b)

    def count(self) -> int:
        return sum(c for c, _ in ray_tpu.get(
            [_block_meta.remote(r) for r in self._refs]))

    def materialize(self) -> "Dataset":
        return Dataset(ExecPlan(list(self._refs)))

    def __reduce__(self):
        return (DataIterator, (self._refs,))


class CoordinatedDataIterator(DataIterator):
    """A work-stealing shard: holds the WHOLE split's ref pool but pulls
    block indexes from a SplitCoordinator lease loop, so which blocks
    this worker consumes is decided at iteration time (own shard first —
    local-store blocks before remote ones — then stolen stragglers).
    count()/materialize() describe the full pool, not one worker's share.
    Picklable; the coordinator handle travels with it."""

    def __init__(self, refs: List[Any], coordinator, worker: int):
        super().__init__(refs)
        self._coordinator = coordinator
        self._worker = worker

    def _block_iter(self, prefetch: int = 4) -> Iterator[Any]:
        local = [i for i, r in enumerate(self._refs)
                 if ingest.block_is_local(r)]
        for idx in ingest.coordinated_block_indexes(
                self._coordinator, self._worker, local):
            yield ray_tpu.get(self._refs[idx])

    def coordinator(self):
        return self._coordinator

    def __reduce__(self):
        return (CoordinatedDataIterator,
                (self._refs, self._coordinator, self._worker))


class DatasetPipeline:
    """Epoch/window pipelining over a lazy Dataset (reference:
    data/dataset_pipeline.py).  repeat(n): iter_epochs yields n Datasets,
    each a FRESH execution of the plan (so per-epoch random_shuffle
    reshuffles); window(k): the input blocks process k at a time."""

    def __init__(self, dataset: "Dataset", times: Optional[int] = None,
                 blocks_per_window: Optional[int] = None):
        self._dataset = dataset
        self._times = times
        self._blocks_per_window = blocks_per_window

    def iter_epochs(self) -> Iterator["Dataset"]:
        if self._blocks_per_window is not None:
            raise ValueError("windowed pipelines iterate batches/windows")
        count = 0
        while self._times is None or count < self._times:
            # Fresh plan execution per epoch: no cached materialization.
            p = self._dataset._plan
            yield Dataset(ExecPlan(list(p.input_refs), list(p.stages),
                                   p.source))
            count += 1

    def iter_windows(self) -> Iterator["Dataset"]:
        if self._blocks_per_window is None:
            raise ValueError("epoch pipelines iterate epochs")
        # window() applies at its position in the chain (reference
        # semantics): stages BEFORE it (e.g. repartition) run first, so
        # the window size is in OUTPUT blocks.  Consequence: upstream
        # stages materialize in full — for bounded memory put window()
        # directly after the source and map over the windows.
        refs = (self._dataset._execute()
                if self._dataset._plan.stages
                or self._dataset._plan.source is not None
                else list(self._dataset._plan.input_refs))
        k = max(1, self._blocks_per_window)
        for lo in range(0, len(refs), k):
            yield Dataset(ExecPlan(refs[lo:lo + k]))

    def iter_batches(self, batch_size: int = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False,
                     prefetch_blocks: int = 4, **_) -> Iterator[Any]:
        """Stream batches.  Windowed pipelines batch across window
        boundaries (steady shapes for fixed-global-batch training);
        epochs batch independently (an epoch is a semantic boundary)."""
        if self._blocks_per_window is not None:
            refs = []
            for ds in self.iter_windows():
                refs.extend(ds._plan.input_refs)
            yield from _batches_from_refs(refs, batch_size, batch_format,
                                          drop_last, prefetch_blocks)
            return
        for ds in self.iter_epochs():
            yield from ds.iter_batches(batch_size=batch_size,
                                       batch_format=batch_format,
                                       drop_last=drop_last,
                                       prefetch_blocks=prefetch_blocks)

    def iter_rows(self) -> Iterator[Any]:
        parts = (self.iter_windows() if self._blocks_per_window is not None
                 else self.iter_epochs())
        for ds in parts:
            yield from ds.iter_rows()
