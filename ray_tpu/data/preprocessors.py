"""Preprocessors: fit statistics on a Dataset, transform batches.

Reference parity: python/ray/data/preprocessor.py (Preprocessor:
fit/transform/fit_transform, transform_batch for serving) and
preprocessors/ (BatchMapper, StandardScaler, MinMaxScaler, LabelEncoder,
Concatenator, Chain).  Statistics come from the Dataset's distributed
aggregates (per-block remote partials); transforms run as normal fused
map_batches stages.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np


class Preprocessor:
    """Base: subclasses implement _fit(dataset) (stats) and
    _transform_batch(batch)."""

    _fitted = False

    def fit(self, dataset) -> "Preprocessor":
        self._fit(dataset)
        self._fitted = True
        return self

    def transform(self, dataset):
        if not self._fitted and self._needs_fit():
            raise RuntimeError(f"{type(self).__name__} must be fit first")
        return dataset.map_batches(self._transform_batch)

    def fit_transform(self, dataset):
        return self.fit(dataset).transform(dataset)

    def transform_batch(self, batch: Dict[str, np.ndarray]):
        """Single-batch form (serving path; reference:
        preprocessor.transform_batch)."""
        if not self._fitted and self._needs_fit():
            raise RuntimeError(f"{type(self).__name__} must be fit first")
        return self._transform_batch(dict(batch))

    # -- subclass hooks ----------------------------------------------------
    def _needs_fit(self) -> bool:
        return True

    def _fit(self, dataset) -> None:
        pass

    def _transform_batch(self, batch):
        raise NotImplementedError


class BatchMapper(Preprocessor):
    """Stateless batch transform (reference: preprocessors/batch_mapper)."""

    def __init__(self, fn: Callable[[Dict[str, np.ndarray]], Dict]):
        self._fn = fn

    def _needs_fit(self) -> bool:
        return False

    def _transform_batch(self, batch):
        return self._fn(batch)


class StandardScaler(Preprocessor):
    """(x - mean) / std per column (reference: preprocessors/scaler)."""

    def __init__(self, columns: List[str]):
        self.columns = list(columns)
        self.stats_: Dict[str, tuple] = {}

    def _fit(self, dataset) -> None:
        # Dataset._execute() materializes blocks once; per-column aggregate
        # calls afterwards are remote partials over the cached block refs.
        for col in self.columns:
            self.stats_[col] = (float(dataset.mean(col)),
                                float(dataset.std(col)))

    def _transform_batch(self, batch):
        for col in self.columns:
            mean, std = self.stats_[col]
            batch[col] = (np.asarray(batch[col], np.float64) - mean) \
                / (std if std > 0 else 1.0)
        return batch


class MinMaxScaler(Preprocessor):
    """(x - min) / (max - min) per column."""

    def __init__(self, columns: List[str]):
        self.columns = list(columns)
        self.stats_: Dict[str, tuple] = {}

    def _fit(self, dataset) -> None:
        for col in self.columns:
            self.stats_[col] = (float(dataset.min(col)),
                                float(dataset.max(col)))

    def _transform_batch(self, batch):
        for col in self.columns:
            lo, hi = self.stats_[col]
            span = (hi - lo) or 1.0
            batch[col] = (np.asarray(batch[col], np.float64) - lo) / span
        return batch


class LabelEncoder(Preprocessor):
    """Categorical column -> contiguous int codes (reference:
    preprocessors/encoder.LabelEncoder)."""

    def __init__(self, label_column: str):
        self.label_column = label_column
        self.classes_: Optional[List[Any]] = None

    def _fit(self, dataset) -> None:
        self.classes_ = sorted(dataset.unique(self.label_column))

    def _transform_batch(self, batch):
        index = {v: i for i, v in enumerate(self.classes_)}
        col = batch[self.label_column]
        batch[self.label_column] = np.array(
            [index[v] for v in np.asarray(col).tolist()], np.int64)
        return batch


class Concatenator(Preprocessor):
    """Merge numeric columns into one vector column (reference:
    preprocessors/concatenator — the standard last step before ML
    ingest)."""

    def __init__(self, columns: List[str], output_column: str = "features",
                 dtype=np.float32):
        self.columns = list(columns)
        self.output_column = output_column
        self.dtype = dtype

    def _needs_fit(self) -> bool:
        return False

    def _transform_batch(self, batch):
        stacked = np.stack(
            [np.asarray(batch.pop(c), self.dtype) for c in self.columns],
            axis=1)
        batch[self.output_column] = stacked
        return batch


class Chain(Preprocessor):
    """Sequential composition; fit runs left to right with intermediate
    transforms (reference: preprocessors/chain)."""

    def __init__(self, *stages: Preprocessor):
        self.stages = list(stages)

    def _needs_fit(self) -> bool:
        return any(s._needs_fit() for s in self.stages)

    def fit(self, dataset) -> "Chain":
        for stage in self.stages:
            dataset = stage.fit_transform(dataset)
        self._fitted = True
        return self

    def _transform_batch(self, batch):
        for stage in self.stages:
            batch = stage._transform_batch(batch)
        return batch
