"""Execution: fused per-block task chains with bounded in-flight windows.

Reference parity: python/ray/data/_internal/execution/ —
StreamingExecutor:41 (operator pipeline with backpressure) +
the plan optimizer's stage fusion (_internal/logical/).  Design here:

  * one-to-one stages (map/filter/flat_map/map_batches) FUSE into a single
    remote task per block — one task launch + one object-store hop per
    block regardless of chain length;
  * all-to-all stages (repartition/shuffle/sort) are barriers that
    materialize their input block list;
  * streaming consumption (iter over blocks) keeps at most `window`
    block-tasks in flight — backpressure without a separate control plane.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Iterator, List, Optional, Tuple

import ray_tpu
from ray_tpu.data import block as blk


@dataclass
class ActorPoolStrategy:
    """compute= for stateful/model-loading transforms: the stage runs on a
    pool of long-lived actors instead of stateless tasks (reference:
    execution/operators/actor_pool_map_operator.py + ActorPoolStrategy)."""

    size: int = 2
    num_cpus: float = 1.0
    num_tpus: Optional[float] = None


@ray_tpu.remote
class _PoolWorker:
    """One actor of a map stage's pool; caches the (possibly expensive to
    construct) transform across blocks."""

    def __init__(self, fn):
        self._fn = fn

    def run(self, block):
        return self._fn(block)


@dataclass
class OneToOne:
    """A fusable per-block transform."""

    fn: Callable  # block -> block
    name: str
    compute: Optional[ActorPoolStrategy] = None


@dataclass
class AllToAll:
    """A barrier transform over the whole block list."""

    fn: Callable  # (list[ref], ctx) -> list[ref]
    name: str


@dataclass
class ExecPlan:
    """Input block refs + stage list (logical plan)."""

    input_refs: List[Any]
    stages: List[Any] = field(default_factory=list)

    def with_stage(self, stage) -> "ExecPlan":
        return ExecPlan(list(self.input_refs), self.stages + [stage])


def _fuse(chain: List[OneToOne]) -> Callable:
    fns = [s.fn for s in chain]

    def fused(block):
        for f in fns:
            block = f(block)
        return block

    return fused


@ray_tpu.remote
def _run_block(block, fused_fn):
    return fused_fn(block)


def _segments(stages: List[Any]) -> List[Tuple[str, Any]]:
    """Group consecutive stateless OneToOne stages into fused segments;
    actor-pool stages stand alone (their state lives in the pool)."""
    segs: List[Tuple[str, Any]] = []
    chain: List[OneToOne] = []

    def flush():
        nonlocal chain
        if chain:
            segs.append(("fused", _fuse(chain)))
            chain = []

    for s in stages:
        if isinstance(s, OneToOne) and s.compute is None:
            chain.append(s)
        elif isinstance(s, OneToOne):
            flush()
            segs.append(("actor_pool", s))
        else:
            flush()
            segs.append(("barrier", s))
    flush()
    return segs


def _run_actor_pool(refs: List[Any], stage: OneToOne) -> List[Any]:
    strat = stage.compute
    pool = [_PoolWorker.options(num_cpus=strat.num_cpus,
                                num_tpus=strat.num_tpus).remote(stage.fn)
            for _ in range(max(1, strat.size))]
    out = [pool[i % len(pool)].run.remote(r) for i, r in enumerate(refs)]
    # Returns live in the node object store / owner memory, not in the
    # actors — once every result is sealed the pool can be released.
    if out:
        ray_tpu.wait(out, num_returns=len(out), timeout=None,
                     fetch_local=False)
    for a in pool:
        try:
            ray_tpu.kill(a)
        except Exception:
            pass
    return out


def execute(plan: ExecPlan, window: int = 16) -> List[Any]:
    """Materialize: returns the final block refs."""
    refs = list(plan.input_refs)
    for kind, seg in _segments(plan.stages):
        if kind == "fused":
            out = []
            pending = {}
            for r in refs:
                while len(pending) >= window:
                    done, _ = ray_tpu.wait(list(pending), num_returns=1,
                                           timeout=None)
                    for d in done:
                        pending.pop(d, None)
                task = _run_block.remote(r, seg)
                pending[task] = True
                out.append(task)
            refs = out
            # Let stragglers finish before a subsequent barrier counts rows.
        elif kind == "actor_pool":
            refs = _run_actor_pool(refs, seg)
        else:
            refs = seg.fn(refs)
    return refs


def iter_output_refs(plan: ExecPlan, window: int = 8) -> Iterator[Any]:
    """Streaming: yield final block refs one at a time, launching at most
    `window` fused tasks ahead of the consumer (backpressure)."""
    segs = _segments(plan.stages)
    # Barriers force materialization of everything before them; stream only
    # the trailing fused segment.
    refs = list(plan.input_refs)
    trailing: Optional[Callable] = None
    for i, (kind, seg) in enumerate(segs):
        is_last = i == len(segs) - 1
        if kind == "fused" and is_last:
            trailing = seg
            break
        if kind == "fused":
            refs = [_run_block.remote(r, seg) for r in refs]
        elif kind == "actor_pool":
            refs = _run_actor_pool(refs, seg)
        else:
            refs = seg.fn(refs)
    if trailing is None:
        yield from refs
        return
    in_flight: List[Any] = []
    src = iter(refs)
    try:
        while True:
            while len(in_flight) < window:
                try:
                    r = next(src)
                except StopIteration:
                    break
                in_flight.append(_run_block.remote(r, trailing))
            if not in_flight:
                return
            yield in_flight.pop(0)
    finally:
        pass
