"""Execution: fused per-block task chains with bounded in-flight windows.

Reference parity: python/ray/data/_internal/execution/ —
StreamingExecutor:41 (operator pipeline with backpressure) +
the plan optimizer's stage fusion (_internal/logical/).  Design here:

  * one-to-one stages (map/filter/flat_map/map_batches) FUSE into a single
    remote task per block — one task launch + one object-store hop per
    block regardless of chain length;
  * all-to-all stages (repartition/shuffle/sort) are barriers that
    materialize their input block list;
  * streaming consumption (iter over blocks) keeps at most `window`
    block-tasks in flight — backpressure without a separate control plane.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Iterator, List, Optional, Tuple

import ray_tpu
from ray_tpu.data import block as blk


@dataclass
class ActorPoolStrategy:
    """compute= for stateful/model-loading transforms: the stage runs on a
    pool of long-lived actors instead of stateless tasks (reference:
    execution/operators/actor_pool_map_operator.py + ActorPoolStrategy)."""

    size: int = 2
    num_cpus: float = 1.0
    num_tpus: Optional[float] = None


@ray_tpu.remote
class _PoolWorker:
    """One actor of a map stage's pool; caches the (possibly expensive to
    construct) transform across blocks."""

    def __init__(self, fn):
        self._fn = fn

    def run(self, block):
        return self._fn(block)


@dataclass
class OneToOne:
    """A fusable per-block transform.  The logical tags feed the plan
    optimizer (logical.py): `row_preserving` stages let a downstream
    limit push into the read; `projection` marks a pure column-select
    that can move into a columnar file reader."""

    fn: Callable  # block -> block
    name: str
    compute: Optional[ActorPoolStrategy] = None
    row_preserving: bool = False
    projection: Optional[List[str]] = None


@dataclass
class AllToAll:
    """A barrier transform over the whole block list."""

    fn: Callable  # (list[ref], ctx) -> list[ref]
    name: str
    limit_rows: Optional[int] = None   # set on limit stages (optimizer)


@dataclass
class ExecPlan:
    """Input block refs (or a LAZY source, see logical.py) + stages."""

    input_refs: List[Any]
    stages: List[Any] = field(default_factory=list)
    source: Optional[Any] = None       # logical.LazyRead | None

    def with_stage(self, stage) -> "ExecPlan":
        return ExecPlan(list(self.input_refs), self.stages + [stage],
                        self.source)

    def resolve(self):
        """(input_refs, stages) after the read-side optimizer rules;
        launches the lazy source."""
        from ray_tpu.data import logical
        return logical.resolve(self)


def _fuse(chain: List[OneToOne]) -> Callable:
    fns = [s.fn for s in chain]

    def fused(block):
        for f in fns:
            block = f(block)
        return block

    return fused


@ray_tpu.remote
def _run_block(block, fused_fn):
    return fused_fn(block)


def _segments(stages: List[Any]) -> List[Tuple[str, Any]]:
    """Group consecutive stateless OneToOne stages into fused segments;
    actor-pool stages stand alone (their state lives in the pool)."""
    segs: List[Tuple[str, Any]] = []
    chain: List[OneToOne] = []

    def flush():
        nonlocal chain
        if chain:
            segs.append(("fused", _fuse(chain)))
            chain = []

    for s in stages:
        if isinstance(s, OneToOne) and s.compute is None:
            chain.append(s)
        elif isinstance(s, OneToOne):
            flush()
            segs.append(("actor_pool", s))
        else:
            flush()
            segs.append(("barrier", s))
    flush()
    return segs


def _run_actor_pool(refs: List[Any], stage: OneToOne) -> List[Any]:
    strat = stage.compute
    pool = [_PoolWorker.options(num_cpus=strat.num_cpus,
                                num_tpus=strat.num_tpus).remote(stage.fn)
            for _ in range(max(1, strat.size))]
    out = [pool[i % len(pool)].run.remote(r) for i, r in enumerate(refs)]
    # Returns live in the node object store / owner memory, not in the
    # actors — once every result is sealed the pool can be released.
    if out:
        ray_tpu.wait(out, num_returns=len(out), timeout=None,
                     fetch_local=False)
    for a in pool:
        try:
            ray_tpu.kill(a)
        except Exception:
            pass
    return out


def execute(plan: ExecPlan, window: int = 16) -> List[Any]:
    """Materialize: returns the final block refs."""
    refs, stages = plan.resolve()
    for kind, seg in _segments(stages):
        if kind == "fused":
            out = []
            pending = {}
            for r in refs:
                while len(pending) >= window:
                    # These refs only ever pass BY REFERENCE to the next
                    # stage's tasks — never pull their payloads here.
                    done, _ = ray_tpu.wait(list(pending), num_returns=1,
                                           timeout=None, fetch_local=False)
                    for d in done:
                        pending.pop(d, None)
                task = _run_block.remote(r, seg)
                pending[task] = True
                out.append(task)
            refs = out
            # Let stragglers finish before a subsequent barrier counts rows.
        elif kind == "actor_pool":
            refs = _run_actor_pool(refs, seg)
        else:
            refs = seg.fn(refs)
    return refs


def _sizeof_block(block) -> int:
    nb = getattr(block, "nbytes", None)
    if nb is not None:
        return int(nb)
    try:
        import sys
        return sys.getsizeof(block)
    except Exception:
        return 1 << 20


@ray_tpu.remote(num_cpus=0)
def _probe_nbytes(block) -> int:
    return _sizeof_block(block)


def _local_nbytes(ref) -> Optional[int]:
    """Serialized size of a SEALED object resident on this node, read
    straight from the store / owner state — no task, no deserialization.
    None when the object is inline-less and not in the local store."""
    from ray_tpu import api
    w = api._worker
    if w is None:
        return None
    try:
        st = w.objects.get(ref.id)
        if st is not None and st.inline is not None:
            return len(st.inline[0])
        store = getattr(w, "store", None)
        if store is None or not store.contains(ref.id):
            return None
        buf = store.get(ref.id, timeout_ms=0)
        if buf is None:
            return None
        try:
            return len(buf.data)
        finally:
            buf.release()
    except Exception:
        return None


class _ByteWindow:
    """Adaptive in-flight bound: counts until the segment's first output
    block has been sized, then bytes/size blocks — resource-aware
    backpressure without a separate control plane (reference:
    StreamingExecutor's per-operator resource budgets,
    streaming_executor.py:41).  Sizing is free when the sealed block is
    local (store metadata via _local_nbytes); the remote _probe_nbytes
    task is a fallback for blocks sealed on another node only."""

    def __init__(self, window: int, window_bytes: int):
        self.window = max(1, window)
        self.window_bytes = window_bytes
        self._first = None
        self._probe = None
        self._est: Optional[int] = None

    def _resolve(self) -> None:
        if self._first is not None:
            ready, _ = ray_tpu.wait([self._first], num_returns=1, timeout=0,
                                    fetch_local=False)
            if not ready:
                return
            n = _local_nbytes(self._first)
            if n is not None:
                self._est = max(1, n)
                self._first = None
                return
            self._probe = _probe_nbytes.remote(self._first)
            self._first = None
        if self._probe is not None:
            done, _ = ray_tpu.wait([self._probe], num_returns=1, timeout=0)
            if done:
                try:
                    self._est = max(1, int(ray_tpu.get(done[0])))
                except Exception:
                    self._est = None
                self._probe = None

    def limit(self) -> int:
        if self._est is None:
            self._resolve()
        if self._est is None:
            return self.window
        return max(1, min(self.window, self.window_bytes // self._est))

    def observe(self, out_ref) -> None:
        if self._est is None and self._first is None and self._probe is None:
            self._first = out_ref


def _stream_fused(src: Iterator[Any], fused_fn: Callable, window: int,
                  window_bytes: int) -> Iterator[Any]:
    """Bounded-window transform stage: at most `limit()` tasks launched
    ahead of what downstream has taken, yielding refs in order."""
    bw = _ByteWindow(window, window_bytes)
    in_flight: List[Any] = []
    src = iter(src)
    exhausted = False
    while True:
        while not exhausted and len(in_flight) < bw.limit():
            try:
                r = next(src)
            except StopIteration:
                exhausted = True
                break
            task = _run_block.remote(r, fused_fn)
            bw.observe(task)
            in_flight.append(task)
        if not in_flight:
            return
        yield in_flight.pop(0)


def _stream_actor_pool(src: Iterator[Any], stage: OneToOne,
                       window: int) -> Iterator[Any]:
    """Actor-pool stage as a streaming operator: the pool lives for the
    stage's lifetime, a bounded submission window rides on it."""
    strat = stage.compute
    pool = [_PoolWorker.options(num_cpus=strat.num_cpus,
                                num_tpus=strat.num_tpus).remote(stage.fn)
            for _ in range(max(1, strat.size))]
    in_flight: List[Any] = []
    i = 0
    src = iter(src)
    exhausted = False
    try:
        while True:
            while not exhausted and len(in_flight) < window:
                try:
                    r = next(src)
                except StopIteration:
                    exhausted = True
                    break
                in_flight.append(pool[i % len(pool)].run.remote(r))
                i += 1
            if not in_flight:
                return
            head = in_flight.pop(0)
            # The result must be sealed before its producing actor can
            # die at stage teardown.
            ray_tpu.wait([head], num_returns=1, timeout=None,
                         fetch_local=False)
            yield head
    finally:
        for a in pool:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass


def iter_output_refs(plan: ExecPlan, window: int = 8,
                     window_bytes: int = 128 << 20) -> Iterator[Any]:
    """Streaming execution across ALL operators: every fused/actor-pool
    segment is a bounded-window generator stage pulling from the previous
    one, so block 0 can be in the last stage while block N is still in
    the first — no stage launches its whole input up front.  Barriers
    (shuffle/sort) are inherent pipeline breakers and materialize the
    stream reaching them; everything between barriers streams.  Windows
    are byte-aware: each stage probes its first output block's size and
    bounds in-flight work by `window_bytes` (reference:
    streaming_executor.py:41 resource-aware backpressure)."""
    refs, stages = plan.resolve()
    stream: Iterator[Any] = iter(refs)
    for kind, seg in _segments(stages):
        if kind == "fused":
            stream = _stream_fused(stream, seg, window, window_bytes)
        elif kind == "actor_pool":
            stream = _stream_actor_pool(stream, seg, window)
        else:
            stream = iter(seg.fn(list(stream)))  # barrier: materialize
    yield from stream
