"""Execution: fused per-block task chains with bounded in-flight windows.

Reference parity: python/ray/data/_internal/execution/ —
StreamingExecutor:41 (operator pipeline with backpressure) +
the plan optimizer's stage fusion (_internal/logical/).  Design here:

  * one-to-one stages (map/filter/flat_map/map_batches) FUSE into a single
    remote task per block — one task launch + one object-store hop per
    block regardless of chain length;
  * all-to-all stages (repartition/shuffle/sort) are barriers that
    materialize their input block list;
  * streaming consumption (iter over blocks) keeps at most `window`
    block-tasks in flight — backpressure without a separate control plane.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Iterator, List, Optional, Tuple

import ray_tpu
from ray_tpu.data import block as blk


@dataclass
class OneToOne:
    """A fusable per-block transform."""

    fn: Callable  # block -> block
    name: str


@dataclass
class AllToAll:
    """A barrier transform over the whole block list."""

    fn: Callable  # (list[ref], ctx) -> list[ref]
    name: str


@dataclass
class ExecPlan:
    """Input block refs + stage list (logical plan)."""

    input_refs: List[Any]
    stages: List[Any] = field(default_factory=list)

    def with_stage(self, stage) -> "ExecPlan":
        return ExecPlan(list(self.input_refs), self.stages + [stage])


def _fuse(chain: List[OneToOne]) -> Callable:
    fns = [s.fn for s in chain]

    def fused(block):
        for f in fns:
            block = f(block)
        return block

    return fused


@ray_tpu.remote
def _run_block(block, fused_fn):
    return fused_fn(block)


def _segments(stages: List[Any]) -> List[Tuple[str, Any]]:
    """Group consecutive OneToOne stages into fused segments."""
    segs: List[Tuple[str, Any]] = []
    chain: List[OneToOne] = []
    for s in stages:
        if isinstance(s, OneToOne):
            chain.append(s)
        else:
            if chain:
                segs.append(("fused", _fuse(chain)))
                chain = []
            segs.append(("barrier", s))
    if chain:
        segs.append(("fused", _fuse(chain)))
    return segs


def execute(plan: ExecPlan, window: int = 16) -> List[Any]:
    """Materialize: returns the final block refs."""
    refs = list(plan.input_refs)
    for kind, seg in _segments(plan.stages):
        if kind == "fused":
            out = []
            pending = {}
            for r in refs:
                while len(pending) >= window:
                    done, _ = ray_tpu.wait(list(pending), num_returns=1,
                                           timeout=None)
                    for d in done:
                        pending.pop(d, None)
                task = _run_block.remote(r, seg)
                pending[task] = True
                out.append(task)
            refs = out
            # Let stragglers finish before a subsequent barrier counts rows.
        else:
            refs = seg.fn(refs)
    return refs


def iter_output_refs(plan: ExecPlan, window: int = 8) -> Iterator[Any]:
    """Streaming: yield final block refs one at a time, launching at most
    `window` fused tasks ahead of the consumer (backpressure)."""
    segs = _segments(plan.stages)
    # Barriers force materialization of everything before them; stream only
    # the trailing fused segment.
    refs = list(plan.input_refs)
    trailing: Optional[Callable] = None
    for i, (kind, seg) in enumerate(segs):
        is_last = i == len(segs) - 1
        if kind == "fused" and is_last:
            trailing = seg
            break
        if kind == "fused":
            refs = [_run_block.remote(r, seg) for r in refs]
        else:
            refs = seg.fn(refs)
    if trailing is None:
        yield from refs
        return
    in_flight: List[Any] = []
    src = iter(refs)
    try:
        while True:
            while len(in_flight) < window:
                try:
                    r = next(src)
                except StopIteration:
                    break
                in_flight.append(_run_block.remote(r, trailing))
            if not in_flight:
                return
            yield in_flight.pop(0)
    finally:
        pass
