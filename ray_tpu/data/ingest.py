"""Device-feed input pipeline: overlapped ingest for the train plane.

Three stages that the naive path serializes on the training thread —
fetch block refs, assemble fixed-size batches, host-to-device transfer —
overlap here so the accelerator never idles on the host
("Exploring the limits of Concurrency in ML Training on Google TPUs";
Podracer/Sebulba: pipeline data preparation against compute):

  * `BatchAssembler` — incremental batch assembly with a row cursor:
    blocks are consumed exactly once and each emitted batch costs
    O(batch rows), regardless of the block-to-batch ratio (the old path
    re-concatenated the whole buffer per batch: O(n^2)).
  * `BatchProducer` — a background thread per iteration that pulls
    blocks with bounded lookahead, assembles batches OFF the training
    thread, and hands them over through a small bounded queue
    (`ingest_queue_depth`).  Producer-starved vs consumer-starved time
    is metered so users can tell which side is the bottleneck.
  * `DeviceBatchIterator` — double-buffered H2D staging: while the
    jitted step consumes batch k, batch k+1 is already being
    `jax.device_put` to its sharding.  The host batch is built over the
    object store's zero-copy np.frombuffer views (_private/
    serialization.py), so the only copy is host -> device.
  * `SplitCoordinator` — a work-stealing alternative to static
    per-worker block lists: blocks are leased to workers dynamically
    (locality-preferring: a worker's local-store blocks first, via
    ObjectStore.contains), a straggler no longer strands its shard, and
    leases re-queue on worker death.  A deterministic round-robin mode
    serves each worker exactly its static shard, in order, for
    token-exact elastic-restore runs.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from typing import Any, Iterable, Iterator, List, Optional

import ray_tpu
from ray_tpu.data import block as blk
from ray_tpu.util import events, spans


def _cfg():
    from ray_tpu._private.config import GLOBAL_CONFIG
    return GLOBAL_CONFIG


_M = None


def _metrics():
    global _M
    if _M is None:
        from ray_tpu.util import metrics as mt
        _M = {
            "batches": mt.Counter(
                "ingest_batches", "batches produced by the ingest pipeline"),
            "producer_wait": mt.Counter(
                "ingest_producer_wait_seconds",
                "seconds the batch producer blocked on a full handoff queue "
                "(consumer/step side is the bottleneck)"),
            "consumer_wait": mt.Counter(
                "ingest_consumer_wait_seconds",
                "seconds the consumer blocked on an empty handoff queue "
                "(producer/fetch side is the bottleneck)"),
            "steals": mt.Counter(
                "ingest_steals",
                "blocks a work-stealing split served from another worker's "
                "queue"),
            "requeues": mt.Counter(
                "ingest_lease_requeues",
                "block leases re-queued after their worker died"),
            "served": mt.Counter(
                "ingest_blocks_served",
                "blocks handed out by the split coordinator"),
            "queue_depth": mt.Gauge(
                "ingest_queue_depth",
                "current depth of the producer->consumer handoff queue"),
            "fetch_s": mt.Histogram(
                "ingest_fetch_s",
                "per-block fetch latency (ref resolution + transfer)",
                buckets=(1e-5, 1e-4, 0.001, 0.0025, 0.005, 0.01, 0.025,
                         0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)),
            "assemble_s": mt.Histogram(
                "ingest_assemble_s",
                "per-block batch-assembly latency (row copy + format)",
                buckets=(1e-6, 1e-5, 1e-4, 0.001, 0.0025, 0.005, 0.01,
                         0.025, 0.05, 0.1, 0.25, 0.5, 1.0)),
        }
    return _M


# ---------------------------------------------------------------------------
# Incremental batch assembly (row cursor, O(batch) per batch)
# ---------------------------------------------------------------------------


class BatchAssembler:
    """Assemble fixed-size batches from a stream of Arrow blocks.

    Blocks enter once via `add_block`; a row cursor walks them so each
    emitted batch slices only the rows it contains — no re-concatenation
    of the buffered tail.  Zero-copy friendly: slices are Arrow views
    over the original (store-mapped) tables until the final per-batch
    concat/convert.
    """

    def __init__(self, batch_size: int, batch_format: str = "numpy"):
        self._batch_size = max(1, int(batch_size))
        self._format = batch_format
        self._blocks: deque = deque()
        self._cursor = 0          # row offset into _blocks[0]
        self._rows = 0            # buffered rows at/after the cursor

    @property
    def buffered_rows(self) -> int:
        return self._rows

    def add_block(self, block) -> None:
        if block.num_rows:
            self._blocks.append(block)
            self._rows += block.num_rows

    def _take(self, n: int):
        pieces = []
        need = n
        while need:
            head = self._blocks[0]
            take = min(head.num_rows - self._cursor, need)
            pieces.append(head.slice(self._cursor, take))
            self._cursor += take
            need -= take
            self._rows -= take
            if self._cursor == head.num_rows:
                self._blocks.popleft()
                self._cursor = 0
        piece = pieces[0] if len(pieces) == 1 else blk.concat_blocks(pieces)
        return blk.block_to_batch(piece, self._format)

    def next_batch(self):
        """One full batch, or None until enough rows are buffered."""
        if self._rows < self._batch_size:
            return None
        return self._take(self._batch_size)

    def flush(self):
        """The final partial batch (or None if nothing is buffered)."""
        if not self._rows:
            return None
        return self._take(self._rows)


def batches_from_block_iter(blocks: Iterable, batch_size: int,
                            batch_format: str = "numpy",
                            drop_last: bool = False) -> Iterator[Any]:
    """Synchronous assembly over an (already materialized) block stream.
    Per-block fetch (pulling the next block out of the iterator, which
    for ref streams includes the object-store get) and assemble (row
    copies into batches) latencies feed the two ingest histograms."""
    asm = BatchAssembler(batch_size, batch_format)
    met = _metrics()
    it = iter(blocks)
    while True:
        t0 = time.perf_counter()
        try:
            b = next(it)
        except StopIteration:
            break
        met["fetch_s"].observe(time.perf_counter() - t0)
        t1 = time.perf_counter()
        ready = []
        asm.add_block(b)
        while True:
            batch = asm.next_batch()
            if batch is None:
                break
            ready.append(batch)
        met["assemble_s"].observe(time.perf_counter() - t1)
        yield from ready
    if not drop_last:
        tail = asm.flush()
        if tail is not None:
            yield tail


def iter_blocks_from_refs(refs, prefetch: int = 4) -> Iterator[Any]:
    """Resolve a ref stream to blocks with bounded touch-ahead: up to
    `prefetch` upcoming refs are warmed via ray_tpu.wait before the
    blocking get."""
    window: deque = deque()
    src = iter(refs)
    exhausted = False
    while True:
        while not exhausted and len(window) < max(1, prefetch):
            try:
                window.append(next(src))
            except StopIteration:
                exhausted = True
        if not window:
            return
        if len(window) > 1:
            ray_tpu.wait(list(window), num_returns=len(window), timeout=0,
                         fetch_local=False)
        yield ray_tpu.get(window.popleft())


# ---------------------------------------------------------------------------
# Background batch producer (bounded handoff queue)
# ---------------------------------------------------------------------------

_DONE = object()


class BatchProducer:
    """Pulls blocks and assembles batches on a background thread.

    The training thread only drains a bounded queue, so fetch + assemble
    cost overlaps the jitted step.  `stats()` exposes the two wait-side
    accumulators: `producer_wait_s` (blocked on a full queue — the
    consumer is the bottleneck) and `consumer_wait_s` (blocked on an
    empty queue — the producer is)."""

    def __init__(self, block_iter: Iterable, batch_size: int,
                 batch_format: str = "numpy", drop_last: bool = False,
                 queue_depth: Optional[int] = None):
        depth = (queue_depth if queue_depth is not None
                 else _cfg().ingest_queue_depth)
        self._q: queue.Queue = queue.Queue(maxsize=max(1, int(depth)))
        self._depth = max(1, int(depth))
        self._blocks = block_iter
        self._batch_size = batch_size
        self._format = batch_format
        self._drop_last = drop_last
        self._stop = threading.Event()
        self._error: Optional[BaseException] = None
        self._stats = {"batches": 0, "producer_wait_s": 0.0,
                       "consumer_wait_s": 0.0, "max_queue_depth": 0}
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="raytpu-ingest-producer")
        self._thread.start()

    # -- producer side ----------------------------------------------------

    def _put(self, item) -> bool:
        # Starvation counters flush LIVE (not at end-of-run): a scrape of
        # /metrics or `cli top` mid-epoch must see the bottleneck while
        # it is happening, not after the iterator is exhausted.
        met = _metrics()
        while not self._stop.is_set():
            t0 = time.perf_counter()
            try:
                self._q.put(item, timeout=0.1)
            except queue.Full:
                waited = time.perf_counter() - t0
                self._stats["producer_wait_s"] += waited
                met["producer_wait"].inc(waited)
                continue
            waited = time.perf_counter() - t0
            if waited > 0.005:
                self._stats["producer_wait_s"] += waited
                met["producer_wait"].inc(waited)
            depth = self._q.qsize()
            self._stats["max_queue_depth"] = max(
                self._stats["max_queue_depth"], depth)
            met["queue_depth"].set(depth)
            return True
        return False

    def _run(self):
        try:
            for batch in batches_from_block_iter(
                    self._blocks, self._batch_size, self._format,
                    self._drop_last):
                self._stats["batches"] += 1
                _metrics()["batches"].inc()
                if not self._put(batch):
                    return
        except BaseException as e:  # noqa: BLE001 — crosses to the consumer
            self._error = e
        finally:
            try:
                self._q.put(_DONE, timeout=60)
            except queue.Full:
                pass

    # -- consumer side ----------------------------------------------------

    def __iter__(self) -> Iterator[Any]:
        from ray_tpu.util.metrics import timer
        met = _metrics()
        wait = met["consumer_wait"]
        try:
            while True:
                # Durational ingest_wait span: the gap the training
                # thread spends blocked on the handoff queue (always on —
                # batch cadence is far below the ring's budget).
                tok = spans.begin("ingest", "ingest_wait")
                with timer(wait) as t:
                    item = self._q.get()
                spans.end(tok, depth=self._q.qsize())
                met["queue_depth"].set(self._q.qsize())
                self._stats["consumer_wait_s"] += t.elapsed
                if t.elapsed > 0.01:
                    # The training thread sat idle on an empty handoff
                    # queue: the producer (fetch/assemble) is starving it.
                    events.record("ingest", "producer_starved",
                                  wait_s=round(t.elapsed, 6))
                if item is _DONE:
                    if self._error is not None:
                        raise self._error
                    return
                yield item
        finally:
            self.close()

    def stats(self) -> dict:
        return dict(self._stats)

    def close(self):
        self._stop.set()
        # Drain so a producer blocked on put() wakes and exits.
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=5.0)

    def __del__(self):
        try:
            self._stop.set()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# Double-buffered host-to-device staging
# ---------------------------------------------------------------------------


def _resolve_sharding(sharding, batch):
    """sharding may be None (default device), a jax Sharding (applied to
    every leaf), a Mesh (per-leaf ("batch","length") logical layout via
    parallel.sharding.batch_shardings), or a dict col -> Sharding."""
    if sharding is None:
        return None
    import jax
    if isinstance(sharding, jax.sharding.Mesh):
        from ray_tpu.parallel.sharding import batch_shardings
        return batch_shardings(sharding, batch)
    if isinstance(sharding, dict) and isinstance(batch, dict):
        return {k: sharding.get(k) for k in batch}
    return sharding


class DeviceBatchIterator:
    """Keeps N batches in flight on the device: while the step consumes
    batch k, batch k+1's jax.device_put has already been dispatched.
    Never holds more than `buffers` device batches (queue-depth gate)."""

    def __init__(self, producer: BatchProducer, sharding=None,
                 buffers: Optional[int] = None):
        self._producer = producer
        self._sharding = sharding
        self._buffers = max(1, int(buffers if buffers is not None
                                   else _cfg().ingest_device_buffers))
        self._resolved = None
        self._have_resolved = False
        self._max_inflight = 0

    def _to_device(self, batch):
        import jax
        if not self._have_resolved:
            self._resolved = _resolve_sharding(self._sharding, batch)
            self._have_resolved = True
        # h2d span covers the device_put DISPATCH (the copy itself is
        # async; a long span here means the staging queue is full).
        tok = spans.begin("ingest", "h2d")
        try:
            if self._resolved is None:
                return jax.device_put(batch)
            if isinstance(self._resolved, dict):
                return {k: (jax.device_put(v, self._resolved[k])
                            if self._resolved[k] is not None
                            else jax.device_put(v))
                        for k, v in batch.items()}
            return jax.device_put(batch, self._resolved)
        finally:
            spans.end(tok)

    def __iter__(self) -> Iterator[Any]:
        inflight: deque = deque()
        try:
            for batch in self._producer:
                inflight.append(self._to_device(batch))
                self._max_inflight = max(self._max_inflight, len(inflight))
                if len(inflight) >= self._buffers:
                    yield inflight.popleft()
            while inflight:
                yield inflight.popleft()
        finally:
            self.close()

    def stats(self) -> dict:
        out = self._producer.stats()
        out["max_device_inflight"] = self._max_inflight
        out["device_buffers"] = self._buffers
        return out

    def close(self):
        self._producer.close()


# ---------------------------------------------------------------------------
# Work-stealing dataset splits
# ---------------------------------------------------------------------------


def block_is_local(ref) -> bool:
    """True when the ref's payload is resident in THIS process (inline
    owned value or sealed in the node's shm store: ObjectStore.contains)."""
    from ray_tpu import api
    w = api._worker
    if w is None:
        return False
    try:
        if ref.owner_address in ("", getattr(w, "address", "")):
            st = w.objects.get(ref.id)
            if st is not None and not st.pending and st.inline is not None:
                return True
        store = getattr(w, "store", None)
        return store is not None and store.contains(ref.id)
    except Exception:
        return False


@ray_tpu.remote
class SplitCoordinator:
    """Leases block INDEXES (into a shared ref pool) to workers.

    Each worker seeds with its static shard's queue.  In stealing mode an
    empty worker takes from the victim with the most remaining blocks
    (tail-first, so the victim's own locality-ordered head survives);
    locality preference serves a worker the blocks already sealed in its
    local store first.  Deterministic mode serves each worker exactly its
    own queue, in order — byte-identical to the static split.

    A lease completes when the worker reports it with its next request
    (or `done`).  `mark_dead` re-queues a dead worker's outstanding
    leases; exhausted stealers also reap leases of workers silent past
    `lease_timeout_s` so a crashed consumer never strands its blocks.
    """

    def __init__(self, queues: List[List[int]], deterministic: bool = False,
                 lease_timeout_s: Optional[float] = None):
        self._queues = [deque(q) for q in queues]
        self._det = bool(deterministic)
        self._timeout = (lease_timeout_s if lease_timeout_s is not None
                         else _cfg().ingest_lease_timeout_s)
        self._orphans: deque = deque()       # re-queued leases, served first
        self._leases: dict = {}              # lease_id -> (worker, idx, t)
        self._next_lease = 0
        self._last_seen: dict = {}           # worker -> monotonic
        self._local: dict = {}               # worker -> set of local idxs
        self._dead: set = set()
        self._stats = {"served": 0, "stolen": 0, "requeued": 0}

    def register(self, worker: int, local_idxs: List[int]) -> None:
        """Record the worker's locality preferences (indexes whose blocks
        its node store already holds)."""
        self._local[worker] = set(local_idxs)

    def _complete(self, lease_id) -> None:
        if lease_id is not None:
            self._leases.pop(lease_id, None)

    def _reap(self, now: float) -> None:
        """Re-queue leases of dead or long-silent workers (only consulted
        once the fresh pool is empty, so a merely slow worker keeps its
        lease)."""
        expired = [lid for lid, (w, _, t) in self._leases.items()
                   if w in self._dead
                   or now - self._last_seen.get(w, t) > self._timeout]
        for lid in expired:
            w, idx, _ = self._leases.pop(lid)
            self._orphans.append(idx)
            self._stats["requeued"] += 1
            _metrics()["requeues"].inc()
            events.record("ingest", "requeue", worker=w, block=idx,
                          reason="lease_timeout")

    def _pick(self, worker: int) -> Optional[int]:
        own = self._queues[worker] if worker < len(self._queues) else deque()
        if self._det:
            return own.popleft() if own else None
        if self._orphans:
            return self._orphans.popleft()
        local = self._local.get(worker)
        if own:
            if local:
                for i, idx in enumerate(own):
                    if idx in local:
                        del own[i]
                        return idx
            return own.popleft()
        # Steal from the victim with the most remaining blocks, tail-first.
        victim = None
        for q in self._queues:
            if q and (victim is None or len(q) > len(victim)):
                victim = q
        if victim is not None:
            self._stats["stolen"] += 1
            _metrics()["steals"].inc()
            events.record("ingest", "steal", worker=worker)
            return victim.pop()
        return None

    def next(self, worker: int, completed=None):
        """Complete `completed` and lease the next block: (lease_id, idx);
        "wait" when the pool is drained but another worker still holds a
        lease that may re-queue (caller backs off and retries); None when
        this worker's stream is exhausted."""
        now = time.monotonic()
        self._last_seen[worker] = now
        self._complete(completed)
        idx = self._pick(worker)
        if idx is None and not self._det:
            self._reap(now)
            if self._orphans:
                idx = self._orphans.popleft()
            elif any(w != worker for w, _, _ in self._leases.values()):
                return "wait"
        if idx is None:
            return None
        lease_id = self._next_lease
        self._next_lease += 1
        self._leases[lease_id] = (worker, idx, now)
        self._stats["served"] += 1
        _metrics()["served"].inc()
        return (lease_id, idx)

    def done(self, worker: int, lease_id) -> None:
        self._last_seen[worker] = time.monotonic()
        self._complete(lease_id)

    def mark_dead(self, worker: int) -> int:
        """Re-queue every outstanding lease of a dead worker; returns how
        many blocks went back to the pool."""
        self._dead.add(worker)
        stale = [lid for lid, (w, _, _) in self._leases.items()
                 if w == worker]
        for lid in stale:
            _, idx, _ = self._leases.pop(lid)
            self._orphans.append(idx)
            self._stats["requeued"] += 1
            _metrics()["requeues"].inc()
            events.record("ingest", "requeue", worker=worker, block=idx,
                          reason="worker_dead")
        return len(stale)

    def stats(self) -> dict:
        out = dict(self._stats)
        out["outstanding_leases"] = len(self._leases)
        out["remaining"] = (len(self._orphans)
                            + sum(len(q) for q in self._queues))
        return out


def coordinated_block_indexes(coordinator, worker: int,
                              local_idxs: Optional[List[int]] = None
                              ) -> Iterator[int]:
    """Worker-side lease loop: yields block indexes from the coordinator,
    acking the previous lease with each request."""
    ray_tpu.get(coordinator.register.remote(worker, list(local_idxs or ())))
    lease = None
    while True:
        nxt = ray_tpu.get(coordinator.next.remote(worker, lease))
        lease = None
        if nxt is None:
            return
        if nxt == "wait":
            # Pool drained but a peer still holds a lease: it may re-queue
            # (death/timeout), so back off instead of ending the stream.
            time.sleep(0.05)
            continue
        lease, idx = nxt
        yield idx
        # The lease completes with the NEXT request (including the final
        # one that returns None), so a worker that dies mid-block leaves
        # its lease outstanding for mark_dead / timeout re-queue.
