"""ray_tpu.data — distributed Arrow-blocked datasets.

Reference parity: python/ray/data/ (SURVEY.md §2.3): lazy plans with stage
fusion, streaming execution with backpressure, map/map_batches/shuffle/
sort/groupby, parquet/csv/json/numpy/text IO, split() for per-worker
ingest.
"""

from ray_tpu.data.dataset import (  # noqa: F401
    CoordinatedDataIterator,
    DataIterator,
    Dataset,
    DatasetPipeline,
    GroupedData,
)
from ray_tpu.data.executor import ActorPoolStrategy  # noqa: F401
from ray_tpu.data.ingest import (  # noqa: F401
    BatchAssembler,
    BatchProducer,
    DeviceBatchIterator,
    SplitCoordinator,
)
from ray_tpu.data.preprocessors import (  # noqa: F401
    BatchMapper,
    Chain,
    Concatenator,
    LabelEncoder,
    MinMaxScaler,
    Preprocessor,
    StandardScaler,
)
from ray_tpu.data.read_api import (  # noqa: F401
    from_arrow,
    from_items,
    from_numpy,
    from_pandas,
    range,
    read_binary_files,
    read_csv,
    read_images,
    read_json,
    read_numpy,
    read_parquet,
    read_sql,
    read_text,
    read_webdataset,
)
