"""Blocks: the unit of distributed data (one Arrow table per block).

Reference parity: python/ray/data/block.py + _internal/arrow_block.py —
blocks live in the object store as Arrow tables; batch views convert to
numpy / pandas / pyarrow on demand.  TPU angle: the "numpy" batch format is
the default (feeds jax.device_put / global_batch directly).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Iterable, List, Optional

import numpy as np
import pyarrow as pa

ITEM_COLUMN = "item"  # reference: from_items wraps scalars in {"item": v}


@dataclass
class BlockMetadata:
    num_rows: int
    size_bytes: int
    schema: Optional[pa.Schema] = None


def rows_to_block(rows: List[Any]) -> pa.Table:
    """Normalize a list of rows (dicts or scalars) into an Arrow table."""
    if not rows:
        return pa.table({})
    if isinstance(rows[0], dict):
        cols: Dict[str, list] = {}
        for r in rows:
            for k in r:
                cols.setdefault(k, [])
        for r in rows:
            for k in cols:
                cols[k].append(r.get(k))
        return pa.table({k: _to_array(v) for k, v in cols.items()})
    return pa.table({ITEM_COLUMN: _to_array(list(rows))})


def _to_array(values: list) -> pa.Array:
    if values and isinstance(values[0], np.ndarray):
        # Tensor column: fixed-shape tensor extension type preserves both
        # dtype and per-row shape through the store and back to numpy.
        arr = np.stack(values)
        return pa.FixedShapeTensorArray.from_numpy_ndarray(arr)
    return pa.array(values)


def _ndarray_to_column(arr: np.ndarray) -> pa.Array:
    """A batch column from an ndarray: rows along dim 0; ndim>1 becomes a
    fixed-shape tensor column."""
    if arr.ndim > 1:
        return pa.FixedShapeTensorArray.from_numpy_ndarray(arr)
    return pa.array(arr)


def block_metadata(block: pa.Table) -> BlockMetadata:
    return BlockMetadata(num_rows=block.num_rows,
                         size_bytes=block.nbytes,
                         schema=block.schema)


def block_to_batch(block: pa.Table, batch_format: str):
    """Convert a block to the requested batch format."""
    if batch_format in ("default", "numpy"):
        return {name: _column_to_numpy(block.column(name))
                for name in block.column_names}
    if batch_format == "pandas":
        return block.to_pandas()
    if batch_format == "pyarrow":
        return block
    raise ValueError(f"unknown batch_format {batch_format!r} "
                     f"(use numpy/pandas/pyarrow)")


def _column_to_numpy(col: pa.ChunkedArray) -> np.ndarray:
    if isinstance(col.type, pa.FixedShapeTensorType):
        merged = col.combine_chunks() if isinstance(
            col, pa.ChunkedArray) else col
        return merged.to_numpy_ndarray()
    try:
        return col.to_numpy(zero_copy_only=False)
    except pa.ArrowInvalid:
        return np.array(col.to_pylist(), dtype=object)


def batch_to_block(batch: Any) -> pa.Table:
    """Convert a user-returned batch back into an Arrow block."""
    if isinstance(batch, pa.Table):
        return batch
    if isinstance(batch, dict):
        cols = {}
        for k, v in batch.items():
            if isinstance(v, np.ndarray):
                cols[k] = _ndarray_to_column(v)
            elif isinstance(v, (pa.Array, pa.ChunkedArray)):
                cols[k] = v
            else:
                cols[k] = _to_array(list(v))
        return pa.table(cols)
    try:
        import pandas as pd
        if isinstance(batch, pd.DataFrame):
            return pa.Table.from_pandas(batch, preserve_index=False)
    except ImportError:
        pass
    if isinstance(batch, list):
        return rows_to_block(batch)
    raise TypeError(f"cannot convert batch of type {type(batch)} to a block")


def block_rows(block: pa.Table) -> Iterable[dict]:
    cols = block.column_names
    if cols == [ITEM_COLUMN]:
        for v in block.column(ITEM_COLUMN).to_pylist():
            yield v
    else:
        for row in block.to_pylist():
            yield row


def concat_blocks(blocks: List[pa.Table]) -> pa.Table:
    blocks = [b for b in blocks if b.num_rows > 0]
    if not blocks:
        return pa.table({})
    return pa.concat_tables(blocks, promote_options="default")


def slice_block(block: pa.Table, start: int, end: int) -> pa.Table:
    return block.slice(start, end - start)
