"""Logical plan layer: lazy sources + a rule-based optimizer.

Reference parity: python/ray/data/_internal/logical/ — operators are
recorded declaratively and `optimizers.py` rewrites the plan before
execution (projection/limit pushdown into reads, operator fusion, read
parallelism).  Here the physical fusion already lives in executor.py;
this layer adds the READ-side rules, which need a source that has not
launched yet:

  * **Projection pushdown**: `read_parquet(...).select_columns(cols)`
    reads only `cols` from disk (Parquet is columnar — the projection
    happens in the file reader, not after materialization).
  * **Limit pushdown**: `read_parquet(...).limit(n)` consults per-file
    row-count METADATA (no data IO) and launches read tasks for only
    the file prefix covering n rows.  Row-preserving stages (map,
    select_columns) between the read and the limit keep the rule valid;
    a filter/flat_map/map_batches blocks it.
  * **Read parallelism hints**: `read_parquet(paths, parallelism=k)`
    groups files into k read tasks instead of one per file.

Eager plans (from_items, non-parquet readers) resolve trivially.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Tuple


@dataclass
class LazyRead:
    """A not-yet-launched read: the optimizer may narrow `paths` (limit
    pushdown), set `columns` (projection pushdown) and group paths
    (parallelism) before `loader` fires."""

    paths: List[str]
    # loader(path_group, columns) -> block ref
    loader: Callable[[List[str], Optional[List[str]]], Any]
    columns: Optional[List[str]] = None
    parallelism: Optional[int] = None
    # count_rows(path) -> row count from file METADATA (None = unknown,
    # which disables limit pushdown for safety).
    count_rows: Optional[Callable[[str], Optional[int]]] = None
    name: str = "read"

    def __post_init__(self):
        # Launch cache keyed by (paths, columns): re-iterating the same
        # Dataset (or a derived plan resolving to the same read) reuses
        # the object-store blocks instead of re-reading files — matching
        # the eager readers' semantics.  Bounded: one entry per distinct
        # pushdown outcome.
        self._launched: dict = {}

    def describe(self) -> str:
        bits = [f"{self.name}[{len(self.paths)} files"]
        if self.columns is not None:
            bits.append(f", columns={self.columns}")
        if self.parallelism:
            bits.append(f", parallelism={self.parallelism}")
        return "".join(bits) + "]"


def _chunk(items: List[Any], k: int) -> List[List[Any]]:
    k = max(1, min(k, len(items)))
    size = (len(items) + k - 1) // k
    return [items[i:i + size] for i in range(0, len(items), size)]


def _analyze(src, stages: List[Any]) -> Tuple[Optional[List[str]],
                                              Optional[int], List[Any]]:
    """The shared rule analysis behind resolve() and explain():
    returns (columns, limit_rows, remaining_stages) WITHOUT launching
    anything — one implementation so the executed plan and the explained
    plan cannot drift."""
    stages = list(stages)
    columns = src.columns

    # Projection pushdown: a select_columns DIRECTLY after the read
    # moves into the file reader (only there is column use knowable —
    # an arbitrary map could touch any column).
    if stages and getattr(stages[0], "projection", None) is not None \
            and columns is None:
        columns = stages[0].projection
        stages = stages[1:]

    # Limit pushdown: scan past row-preserving stages for a limit.
    limit_rows = None
    for s in stages:
        lr = getattr(s, "limit_rows", None)
        if lr is not None:
            limit_rows = lr
            break        # the limit stage stays: it trims the tail block
        if not getattr(s, "row_preserving", False):
            break
    return columns, limit_rows, stages


def resolve(plan) -> Tuple[List[Any], List[Any]]:
    """Apply the read-side rules and launch the source; returns
    (input_refs, remaining_stages).  Called once per execution by the
    executor's entry points."""
    src = getattr(plan, "source", None)
    if src is None:
        return list(plan.input_refs), list(plan.stages)
    columns, limit_rows, stages = _analyze(src, plan.stages)
    paths = list(src.paths)
    if limit_rows is not None and src.count_rows is not None:
        picked: List[str] = []
        acc = 0
        for p in paths:
            picked.append(p)
            n = src.count_rows(p)
            if n is None:      # unknown metadata: read everything
                picked = paths
                break
            acc += n
            if acc >= limit_rows:
                break
        paths = picked

    key = (tuple(paths), tuple(columns) if columns is not None else None)
    refs = src._launched.get(key)
    if refs is None:
        groups = (_chunk(paths, src.parallelism) if src.parallelism
                  else [[p] for p in paths])
        refs = [src.loader(g, columns) for g in groups]
        src._launched[key] = refs
    return list(refs), stages


def explain(plan) -> str:
    """Human-readable logical plan + the optimizer's decisions (the
    plan-inspection surface; reference: Dataset.explain())."""
    src = getattr(plan, "source", None)
    lines = []
    stages_shown = list(plan.stages)
    if src is None:
        lines.append(f"EagerInput[{len(plan.input_refs)} blocks]")
    else:
        # stages_shown = what will actually run after pushdown — a
        # pushed-down select_columns must not ALSO appear as a stage.
        columns, limit_rows, stages_shown = _analyze(src, plan.stages)
        d = src.describe()
        if columns is not None and src.columns is None:
            d += f" <- pushed projection {columns}"
        if limit_rows is not None and src.count_rows is not None:
            d += f" <- pushed limit {limit_rows}"
        lines.append(d)
    for s in stages_shown:
        tags = []
        if getattr(s, "row_preserving", False):
            tags.append("row-preserving")
        if getattr(s, "projection", None) is not None:
            tags.append(f"projection={s.projection}")
        if getattr(s, "limit_rows", None) is not None:
            tags.append(f"limit={s.limit_rows}")
        suffix = f"  [{', '.join(tags)}]" if tags else ""
        lines.append(f"  -> {getattr(s, 'name', '?')}{suffix}")
    return "\n".join(lines)
