"""Dataset creation (reference: python/ray/data/read_api.py — range,
from_items, from_pandas/from_arrow/from_numpy, read_parquet/csv/json/
numpy/text/binary over datasource/ file readers)."""

from __future__ import annotations

import builtins
import glob as _glob
import os
from typing import Any, List, Optional

import numpy as np
import pyarrow as pa

import ray_tpu
from ray_tpu.data import block as blk
from ray_tpu.data.dataset import Dataset
from ray_tpu.data.executor import ExecPlan

DEFAULT_BLOCK_ROWS = 1000


def _from_blocks(blocks: List[pa.Table]) -> Dataset:
    return Dataset(ExecPlan([ray_tpu.put(b) for b in blocks]))


def _chunk(rows: list, parallelism: int) -> List[list]:
    n = max(1, min(parallelism, len(rows)) if rows else 1)
    per = -(-len(rows) // n) if rows else 1
    return [rows[i * per:(i + 1) * per] for i in builtins.range(n)
            if i * per < len(rows)] or [[]]


def from_items(items: List[Any], *, parallelism: int = 8) -> Dataset:
    return _from_blocks([blk.rows_to_block(c)
                         for c in _chunk(list(items), parallelism)])


def range(n: int, *, parallelism: int = 8) -> Dataset:  # noqa: A001
    per = -(-n // max(1, parallelism)) if n else 1
    blocks = []
    for start in builtins.range(0, n, per):
        stop = min(start + per, n)
        blocks.append(pa.table({"id": pa.array(np.arange(start, stop))}))
    return _from_blocks(blocks or [pa.table({"id": pa.array([], pa.int64())})])


def from_pandas(dfs) -> Dataset:
    if not isinstance(dfs, list):
        dfs = [dfs]
    return _from_blocks([pa.Table.from_pandas(df, preserve_index=False)
                         for df in dfs])


def from_arrow(tables) -> Dataset:
    if not isinstance(tables, list):
        tables = [tables]
    return _from_blocks(list(tables))


def from_numpy(arrays, column: str = "data") -> Dataset:
    if not isinstance(arrays, list):
        arrays = [arrays]
    blocks = []
    for arr in arrays:
        blocks.append(blk.rows_to_block([{column: row} for row in arr]))
    return _from_blocks(blocks)


def _expand_paths(paths, suffix: Optional[str] = None) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            pattern = os.path.join(p, f"*{suffix}" if suffix else "*")
            out.extend(sorted(_glob.glob(pattern)))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files matched {paths}")
    return out


def read_parquet(paths, *, columns: Optional[List[str]] = None,
                 parallelism: Optional[int] = None) -> Dataset:
    """LAZY columnar read: the plan optimizer (data/logical.py) may push
    a downstream select_columns into the file reader and a downstream
    limit into the file list (per-file row counts come from Parquet
    metadata, no data IO); `parallelism` groups files into that many
    read tasks."""
    import pyarrow.parquet as pq

    from ray_tpu.data.logical import LazyRead
    files = _expand_paths(paths, ".parquet")

    @ray_tpu.remote
    def load(group, cols):
        import pyarrow as pa
        tables = [pq.read_table(p, columns=cols) for p in group]
        return tables[0] if len(tables) == 1 else pa.concat_tables(tables)

    def count_rows(path):
        try:
            return pq.ParquetFile(path).metadata.num_rows
        except Exception:
            return None

    return Dataset(ExecPlan([], source=LazyRead(
        paths=files,
        loader=lambda group, cols: load.remote(group, cols),
        columns=list(columns) if columns else None,
        parallelism=parallelism,
        count_rows=count_rows,
        name="read_parquet")))


def read_csv(paths) -> Dataset:
    import pyarrow.csv as pcsv
    files = _expand_paths(paths, ".csv")

    @ray_tpu.remote
    def load(path):
        return pcsv.read_csv(path)

    return Dataset(ExecPlan([load.remote(p) for p in files]))


def read_json(paths) -> Dataset:
    import json

    files = _expand_paths(paths, ".json")

    @ray_tpu.remote
    def load(path):
        with open(path) as f:
            rows = [json.loads(line) for line in f if line.strip()]
        return blk.rows_to_block(rows)

    return Dataset(ExecPlan([load.remote(p) for p in files]))


def read_numpy(paths, column: str = "data") -> Dataset:
    files = _expand_paths(paths, ".npy")

    @ray_tpu.remote
    def load(path):
        arr = np.load(path)
        return blk.rows_to_block([{column: row} for row in arr])

    return Dataset(ExecPlan([load.remote(p) for p in files]))


def read_text(paths) -> Dataset:
    files = _expand_paths(paths)

    @ray_tpu.remote
    def load(path):
        with open(path) as f:
            return blk.rows_to_block(
                [{"text": line.rstrip("\n")} for line in f])

    return Dataset(ExecPlan([load.remote(p) for p in files]))


def read_binary_files(paths) -> Dataset:
    files = _expand_paths(paths)

    @ray_tpu.remote
    def load(path):
        with open(path, "rb") as f:
            return blk.rows_to_block([{"path": path, "bytes": f.read()}])

    return Dataset(ExecPlan([load.remote(p) for p in files]))


def read_sql(sql: str, connection_factory, *,
             parallelism: int = 8) -> Dataset:
    """Load the result rows of a SQL query (reference:
    data/datasource/sql_datasource.py — connection_factory() -> DBAPI2
    connection; sqlite3 from the stdlib qualifies).  The query executes
    EXACTLY ONCE, in one worker task, which streams the cursor into
    `parallelism` blocks (offset-splitting across re-executions would
    corrupt results on backends with non-deterministic scan order)."""
    p = max(1, parallelism)

    @ray_tpu.remote(num_returns=p)
    def load():
        conn = connection_factory()
        try:
            cur = conn.cursor()
            cur.execute(sql)
            cols = [d[0] for d in cur.description]
            rows = [dict(zip(cols, r)) for r in cur.fetchall()]
        finally:
            conn.close()
        blocks = [blk.rows_to_block(c) for c in _chunk(rows, p)]
        blocks += [blk.rows_to_block([])] * (p - len(blocks))
        return tuple(blocks) if p > 1 else blocks[0]

    refs = load.remote()
    return Dataset(ExecPlan(list(refs) if p > 1 else [refs]))


def read_images(paths, *, size: Optional[tuple] = None,
                mode: str = "RGB") -> Dataset:
    """Decode image files into {"image": HWC uint8 array, "path"} rows
    (reference: data/datasource/image_datasource.py).  One task per file;
    `size` resizes, `mode` converts (RGB/L/...)."""
    files = _expand_paths(paths)

    @ray_tpu.remote
    def load(path):
        from PIL import Image
        img = Image.open(path)
        if mode:
            img = img.convert(mode)
        if size is not None:
            img = img.resize(size)
        return blk.rows_to_block(
            [{"image": np.asarray(img), "path": path}])

    return Dataset(ExecPlan([load.remote(p) for p in files]))


def read_webdataset(paths) -> Dataset:
    """Read webdataset-style tar shards: files grouped by key (basename
    before the first dot), one row per key with a column per extension
    (reference: data/datasource/webdataset_datasource.py).  One task per
    shard; payloads stay bytes — decode with map()."""
    files = _expand_paths(paths)

    @ray_tpu.remote
    def load(path):
        import tarfile
        samples: dict = {}
        with tarfile.open(path) as tar:
            for member in tar:
                if not member.isfile():
                    continue
                base = os.path.basename(member.name)
                key, _, ext = base.partition(".")
                payload = tar.extractfile(member).read()
                samples.setdefault(key, {"__key__": key})[ext] = payload
        return blk.rows_to_block(
            [samples[k] for k in sorted(samples)])

    return Dataset(ExecPlan([load.remote(p) for p in files]))
