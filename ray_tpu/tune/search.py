"""Search spaces and suggestion: samplers, grid expansion, concurrency cap.

Reference parity: python/ray/tune/search/ — basic_variant.py
(BasicVariantGenerator: grid_search x num_samples expansion),
sample.py (uniform/loguniform/choice/randint/...), concurrency_limiter.py.
Plugin searchers (optuna/hyperopt/...) slot in behind the same Searcher
interface.
"""

from __future__ import annotations

import random
from typing import Any, Dict, List, Optional


class Domain:
    def sample(self, rng: random.Random):
        raise NotImplementedError


class Float(Domain):
    def __init__(self, low: float, high: float, log: bool = False):
        self.low, self.high, self.log = low, high, log

    def sample(self, rng):
        if self.log:
            import math
            return math.exp(rng.uniform(math.log(self.low),
                                        math.log(self.high)))
        return rng.uniform(self.low, self.high)


class Integer(Domain):
    def __init__(self, low: int, high: int):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class Categorical(Domain):
    def __init__(self, categories: list):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


def uniform(low: float, high: float) -> Float:
    return Float(low, high)


def loguniform(low: float, high: float) -> Float:
    return Float(low, high, log=True)


def randint(low: int, high: int) -> Integer:
    return Integer(low, high)


def choice(categories: list) -> Categorical:
    return Categorical(categories)


def grid_search(values: list) -> dict:
    return {"grid_search": list(values)}


def _expand_grid(space: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Cartesian product over every grid_search entry (reference:
    basic_variant.py variant generation)."""
    grids = [(k, v["grid_search"]) for k, v in space.items()
             if isinstance(v, dict) and "grid_search" in v]
    variants = [{}]
    for key, values in grids:
        variants = [dict(v, **{key: val}) for v in variants for val in values]
    return variants


class Searcher:
    """Interface for suggestion algorithms (reference: search/searcher.py)."""

    def suggest(self, trial_id: str) -> Optional[dict]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str, result: Optional[dict],
                          error: bool = False):
        pass


class BasicVariantGenerator(Searcher):
    def __init__(self, space: Dict[str, Any], num_samples: int = 1,
                 seed: Optional[int] = None):
        self._space = space
        self._rng = random.Random(seed)
        self._queue: List[dict] = []
        for _ in range(num_samples):
            for variant in _expand_grid(space):
                cfg = {}
                for k, v in space.items():
                    if k in variant:
                        cfg[k] = variant[k]
                    elif isinstance(v, Domain):
                        cfg[k] = v.sample(self._rng)
                    elif isinstance(v, dict) and "grid_search" in v:
                        pass  # covered by variant
                    else:
                        cfg[k] = v
                self._queue.append(cfg)

    @property
    def total(self) -> int:
        return len(self._queue)

    def suggest(self, trial_id: str) -> Optional[dict]:
        return self._queue.pop(0) if self._queue else None


class ConcurrencyLimiter(Searcher):
    """Cap in-flight suggestions (reference: concurrency_limiter.py)."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set = set()

    def suggest(self, trial_id: str) -> Optional[dict]:
        if len(self._live) >= self.max_concurrent:
            return None
        cfg = self.searcher.suggest(trial_id)
        if cfg is not None:
            self._live.add(trial_id)
        return cfg

    def on_trial_complete(self, trial_id, result=None, error=False):
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result, error)
