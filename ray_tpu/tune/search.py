"""Search spaces and suggestion: samplers, grid expansion, concurrency cap.

Reference parity: python/ray/tune/search/ — basic_variant.py
(BasicVariantGenerator: grid_search x num_samples expansion),
sample.py (uniform/loguniform/choice/randint/...), concurrency_limiter.py.
Plugin searchers (optuna/hyperopt/...) slot in behind the same Searcher
interface.
"""

from __future__ import annotations

import random
from typing import Any, Dict, List, Optional


class Domain:
    def sample(self, rng: random.Random):
        raise NotImplementedError


class Float(Domain):
    def __init__(self, low: float, high: float, log: bool = False):
        self.low, self.high, self.log = low, high, log

    def sample(self, rng):
        if self.log:
            import math
            return math.exp(rng.uniform(math.log(self.low),
                                        math.log(self.high)))
        return rng.uniform(self.low, self.high)


class Integer(Domain):
    def __init__(self, low: int, high: int):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class Categorical(Domain):
    def __init__(self, categories: list):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


def uniform(low: float, high: float) -> Float:
    return Float(low, high)


def loguniform(low: float, high: float) -> Float:
    return Float(low, high, log=True)


def randint(low: int, high: int) -> Integer:
    return Integer(low, high)


def choice(categories: list) -> Categorical:
    return Categorical(categories)


def grid_search(values: list) -> dict:
    return {"grid_search": list(values)}


def _expand_grid(space: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Cartesian product over every grid_search entry (reference:
    basic_variant.py variant generation)."""
    grids = [(k, v["grid_search"]) for k, v in space.items()
             if isinstance(v, dict) and "grid_search" in v]
    variants = [{}]
    for key, values in grids:
        variants = [dict(v, **{key: val}) for v in variants for val in values]
    return variants


class Searcher:
    """Interface for suggestion algorithms (reference: search/searcher.py)."""

    def suggest(self, trial_id: str) -> Optional[dict]:
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: dict):
        """Intermediate observation (multi-fidelity searchers — BOHB —
        model per training budget; most searchers ignore these)."""

    def on_trial_complete(self, trial_id: str, result: Optional[dict],
                          error: bool = False):
        pass


class BasicVariantGenerator(Searcher):
    def __init__(self, space: Dict[str, Any], num_samples: int = 1,
                 seed: Optional[int] = None):
        self._space = space
        self._rng = random.Random(seed)
        self._queue: List[dict] = []
        for _ in range(num_samples):
            for variant in _expand_grid(space):
                cfg = {}
                for k, v in space.items():
                    if k in variant:
                        cfg[k] = variant[k]
                    elif isinstance(v, Domain):
                        cfg[k] = v.sample(self._rng)
                    elif isinstance(v, dict) and "grid_search" in v:
                        pass  # covered by variant
                    else:
                        cfg[k] = v
                self._queue.append(cfg)

    @property
    def total(self) -> int:
        return len(self._queue)

    def suggest(self, trial_id: str) -> Optional[dict]:
        return self._queue.pop(0) if self._queue else None


class TPESearcher(Searcher):
    """Native Tree-structured Parzen Estimator searcher.

    Reference role: python/ray/tune/search/optuna/optuna_search.py (the
    reference delegates model-based suggestion to plugin libraries; this
    is a from-scratch TPE behind the same Searcher interface, so plugin
    searchers and this one are interchangeable).

    Classic TPE: past observations split at the gamma-quantile of the
    objective into good/bad sets; per-dimension Parzen (KDE) densities
    l(x) (good) and g(x) (bad); candidates are drawn from l and ranked by
    the acquisition log l(x) - log g(x).  Dimensions are treated
    independently; Float dims with log=True are modeled in log space;
    Categorical dims use smoothed count ratios.
    """

    def __init__(self, space: Dict[str, Any], metric: str,
                 mode: str = "min", n_startup: int = 10,
                 n_candidates: int = 24, gamma: float = 0.25,
                 seed: Optional[int] = None):
        assert mode in ("min", "max")
        self._space = {k: v for k, v in space.items()}
        self._metric = metric
        self._mode = mode
        self._n_startup = n_startup
        self._n_candidates = n_candidates
        self._gamma = gamma
        self._rng = random.Random(seed)
        self._configs: Dict[str, dict] = {}     # trial_id -> config
        self._obs: List[tuple] = []             # (config, objective)

    # -- helpers -----------------------------------------------------------

    def _to_unit(self, dom, value: float) -> float:
        import math
        if isinstance(dom, Float) and dom.log:
            lo, hi = math.log(dom.low), math.log(dom.high)
            return (math.log(value) - lo) / (hi - lo)
        lo, hi = float(dom.low), float(dom.high)
        return (float(value) - lo) / (hi - lo)

    def _from_unit(self, dom, u: float):
        import math
        u = min(max(u, 0.0), 1.0)
        if isinstance(dom, Float):
            if dom.log:
                lo, hi = math.log(dom.low), math.log(dom.high)
                return math.exp(lo + u * (hi - lo))
            return dom.low + u * (dom.high - dom.low)
        return min(int(dom.low + u * (dom.high - dom.low)), dom.high - 1)

    @staticmethod
    def _kde_logpdf(x: float, centers: List[float], bw: float) -> float:
        import math
        if not centers:
            return 0.0
        acc = 0.0
        for c in centers:
            z = (x - c) / bw
            acc += math.exp(-0.5 * z * z)
        return math.log(max(acc / (len(centers) * bw), 1e-12))

    def _split(self):
        vals = sorted(o for _, o in self._obs)
        n_good = max(1, int(self._gamma * len(vals)))
        cut = vals[n_good - 1]
        good = [c for c, o in self._obs if o <= cut][:n_good * 2]
        bad = [c for c, o in self._obs if o > cut]
        return good, bad or [c for c, _ in self._obs]

    # -- Searcher ----------------------------------------------------------

    def suggest(self, trial_id: str) -> Optional[dict]:
        import math
        cfg: dict = {}
        model = len(self._obs) >= self._n_startup
        good = bad = None
        if model:
            good, bad = self._split()
        for key, dom in self._space.items():
            if not isinstance(dom, Domain):
                cfg[key] = dom
                continue
            if isinstance(dom, Categorical):
                if not model:
                    cfg[key] = dom.sample(self._rng)
                    continue
                cats = dom.categories

                def smoothed(obs_set):
                    counts = {c: 1.0 for c in cats}  # +1 prior
                    for c_cfg in obs_set:
                        counts[c_cfg[key]] = counts.get(c_cfg[key], 1.) + 1
                    total = sum(counts.values())
                    return {c: counts[c] / total for c in cats}

                pl, pg = smoothed(good), smoothed(bad)
                cfg[key] = max(
                    cats, key=lambda c: math.log(pl[c]) - math.log(pg[c])
                    + self._rng.random() * 1e-6)
                continue
            if not model:
                cfg[key] = dom.sample(self._rng)
                continue
            gu = [self._to_unit(dom, c[key]) for c in good]
            bu = [self._to_unit(dom, c[key]) for c in bad]
            bw_g = max(1.0 / math.sqrt(len(gu) + 1), 0.05)
            bw_b = max(1.0 / math.sqrt(len(bu) + 1), 0.05)
            best_u, best_score = None, -1e18
            for _ in range(self._n_candidates):
                center = self._rng.choice(gu)
                u = center + self._rng.gauss(0.0, bw_g)
                u = min(max(u, 0.0), 1.0)
                score = (self._kde_logpdf(u, gu, bw_g)
                         - self._kde_logpdf(u, bu, bw_b))
                if score > best_score:
                    best_u, best_score = u, score
            cfg[key] = self._from_unit(dom, best_u)
        self._configs[trial_id] = cfg
        return cfg

    def on_trial_complete(self, trial_id: str, result: Optional[dict],
                          error: bool = False):
        cfg = self._configs.pop(trial_id, None)
        if cfg is None or error or not result \
                or self._metric not in result:
            return
        value = float(result[self._metric])
        if self._mode == "max":
            value = -value
        self._obs.append((cfg, value))


class BOHBSearcher(TPESearcher):
    """BOHB's model half: TPE conditioned on training budget.

    Reference role: python/ray/tune/search/bohb/ (TuneBOHB) paired with
    schedulers/hb_bohb.py — HyperBand decides budgets/stopping, the
    model proposes configs from observations AT A BUDGET.  Observations
    pool per `time_attr` value (every intermediate result is one
    observation at its budget); suggestion models on the LARGEST budget
    that has accumulated >= n_startup observations, falling back to
    random until any budget qualifies.  Pair with HyperBandScheduler.
    """

    def __init__(self, space: Dict[str, Any], metric: str,
                 mode: str = "min", n_startup: int = 8,
                 n_candidates: int = 24, gamma: float = 0.25,
                 seed: Optional[int] = None,
                 time_attr: str = "training_iteration"):
        super().__init__(space, metric, mode, n_startup=n_startup,
                         n_candidates=n_candidates, gamma=gamma, seed=seed)
        self._time_attr = time_attr
        self._by_budget: Dict[int, List[tuple]] = {}

    def on_trial_result(self, trial_id: str, result: dict):
        cfg = self._configs.get(trial_id)
        value = result.get(self._metric)
        budget = result.get(self._time_attr)
        if cfg is None or value is None or budget is None:
            return
        v = float(value)
        if self._mode == "max":
            v = -v
        self._by_budget.setdefault(int(budget), []).append((cfg, v))

    def suggest(self, trial_id: str) -> Optional[dict]:
        # Select the highest budget with a modelable pool; TPESearcher's
        # machinery then runs on that pool via self._obs.
        pool: List[tuple] = []
        for budget in sorted(self._by_budget, reverse=True):
            if len(self._by_budget[budget]) >= self._n_startup:
                pool = self._by_budget[budget]
                break
        self._obs = pool
        return super().suggest(trial_id)

    def on_trial_complete(self, trial_id: str, result: Optional[dict],
                          error: bool = False):
        # The final result already arrived via on_trial_result (the
        # controller feeds every report); recording it again here would
        # double-weight completed trials in the TPE pool.
        self._configs.pop(trial_id, None)


class ConcurrencyLimiter(Searcher):
    """Cap in-flight suggestions (reference: concurrency_limiter.py)."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set = set()

    def suggest(self, trial_id: str) -> Optional[dict]:
        if len(self._live) >= self.max_concurrent:
            return None
        cfg = self.searcher.suggest(trial_id)
        if cfg is not None:
            self._live.add(trial_id)
        return cfg

    def on_trial_result(self, trial_id, result):
        self.searcher.on_trial_result(trial_id, result)

    def on_trial_complete(self, trial_id, result=None, error=False):
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result, error)
