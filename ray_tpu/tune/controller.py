"""Trial lifecycle: each trial is a session-running actor; one controller
event loop multiplexes reports, applies scheduler decisions, and handles
failures.

Reference parity: python/ray/tune/execution/tune_controller.py:47 (step:228,
actor-event driven) + ray_trial_executor.py:185 (trial = remote actor under
the trial's resources); trials reuse the train session actor machinery the
same way the reference's function trainables reuse _TrainSession.
"""

from __future__ import annotations

import logging
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import RunConfig
from ray_tpu.train.session import TrainContext
from ray_tpu.train.worker_group import RayTrainWorker
from ray_tpu.tune import schedulers as sched_mod
from ray_tpu.tune.search import Searcher

logger = logging.getLogger("ray_tpu.tune")


@dataclass
class Trial:
    trial_id: str
    config: dict
    state: str = "PENDING"   # PENDING/RUNNING/TERMINATED/ERROR
    actor: Any = None
    pending_ref: Any = None  # in-flight get_next ref
    last_result: Optional[dict] = None
    results: List[dict] = field(default_factory=list)
    checkpoint: Optional[Checkpoint] = None
    error: Optional[BaseException] = None
    iteration: int = 0
    reached_rungs: set = field(default_factory=set)
    # PBT exploit/explore decision recorded by the scheduler:
    exploit_from: Any = None
    explored_config: Optional[dict] = None
    restarts: int = 0
    # Per-trial resource override + pending reallocation
    # (ResourceChangingScheduler):
    resources: Optional[dict] = None
    new_resources: Optional[dict] = None


class TuneController:
    def __init__(self, trainable: Callable[[dict], Any], *,
                 searcher: Searcher,
                 scheduler: Optional[sched_mod.TrialScheduler] = None,
                 max_concurrent: int = 8,
                 resources_per_trial: Optional[dict] = None,
                 run_config: Optional[RunConfig] = None,
                 max_failures_per_trial: int = 0,
                 experiment_path: Optional[str] = None):
        self._trainable = trainable
        self._searcher = searcher
        self._scheduler = scheduler or sched_mod.FIFOScheduler()
        self._max_concurrent = max_concurrent
        self._resources = dict(resources_per_trial or {"CPU": 1})
        self._capacity_cap: Optional[int] = None  # from cluster totals
        self._capacity_cap_at = 0.0
        self._run_config = run_config or RunConfig()
        self._max_failures = max_failures_per_trial
        if hasattr(self._scheduler, "base_resources"):
            self._scheduler.base_resources = dict(self._resources)
            self._scheduler.controller = self
        self.trials: List[Trial] = []
        self._next_index = 0
        self._experiment_path = experiment_path
        if experiment_path:
            import os
            os.makedirs(experiment_path, exist_ok=True)

    # ------------------------------------------------------------------

    def _make_trial(self) -> Optional[Trial]:
        trial_id = f"trial_{self._next_index:05d}_{uuid.uuid4().hex[:6]}"
        config = self._searcher.suggest(trial_id)
        if config is None:
            return None
        self._next_index += 1
        trial = Trial(trial_id=trial_id, config=config)
        self.trials.append(trial)
        return trial

    def _start_trial(self, trial: Trial):
        res = dict(trial.resources or self._resources)
        cpu = res.pop("CPU", 1)
        tpu = res.pop("TPU", None)
        trial.actor = RayTrainWorker.options(
            num_cpus=cpu, num_tpus=tpu, resources=res or None).remote()
        fn = self._trainable
        config = dict(trial.config)

        def run_fn():
            fn(config)

        ctx = TrainContext(world_rank=0, world_size=1, local_rank=0,
                           local_world_size=1, node_rank=0,
                           trial_name=trial.trial_id)
        ray_tpu.get(trial.actor.init_session.remote(
            run_fn, ctx, trial.checkpoint), timeout=120)
        trial.state = "RUNNING"
        trial.pending_ref = trial.actor.get_next.remote(None)

    def _stop_trial(self, trial: Trial, state: str = "TERMINATED",
                    error: Optional[BaseException] = None):
        trial.state = state
        trial.error = error
        self._teardown_actor(trial)
        self._searcher.on_trial_complete(
            trial.trial_id, trial.last_result, error is not None)
        self._scheduler.on_trial_complete(trial, trial.last_result)

    # ------------------------------------------------------------------

    def _running(self) -> List[Trial]:
        return [t for t in self.trials if t.state == "RUNNING"]

    def _resource_cap(self) -> int:
        """How many trials the CLUSTER can run at once (reference: the
        trial executor only starts trials whose resources fit).  The
        controller must never block on a trial whose actor is queued for
        resources — `_start_trial`'s init get would starve the RUNNING
        trials that will free them (livelock until timeout).  Refreshed
        every ~2s so an autoscaling cluster raises the cap."""
        now = time.monotonic()
        if self._capacity_cap is not None \
                and now - self._capacity_cap_at < 2.0:
            return self._capacity_cap
        try:
            total = ray_tpu.cluster_resources()
        except Exception:
            total = None
        cap = None
        if total is not None:
            # Resources already pledged to RUNNING trials (per-trial
            # overrides included — a ResourceChangingScheduler may have
            # grown them past the base request).  Ignoring the overrides
            # would overcount free capacity and block _start_trial on an
            # unplaceable actor — the livelock this cap exists to prevent.
            held: Dict[str, float] = {}
            n_running = 0
            for t in self.trials:
                if t.state != "RUNNING":
                    continue
                n_running += 1
                for k, v in (t.resources or self._resources).items():
                    held[k] = held.get(k, 0) + (v or 0)
            for k, need in self._resources.items():
                if not need:
                    continue
                # A demanded resource ABSENT from the cluster caps at 1:
                # one launch surfaces the pend/failure instead of a
                # thundering start that livelocks on init.
                free = total.get(k, 0) - held.get(k, 0)
                fit = n_running + max(0, int(free / need))
                cap = fit if cap is None else min(cap, fit)
        self._capacity_cap = max(1, cap) if cap is not None \
            else self._max_concurrent
        self._capacity_cap_at = now
        return self._capacity_cap

    def _fits(self, trial: Trial) -> bool:
        """Does THIS trial's demand (its per-trial override, not the base
        request) fit in what the cluster has left after the RUNNING
        trials' holdings?  Restored experiments can hold grown
        allocations on PENDING trials — launching one the cluster can't
        place blocks _start_trial's init get and starves everyone."""
        try:
            total = ray_tpu.cluster_resources()
        except Exception:
            return True
        held: Dict[str, float] = {}
        for t in self._running():
            for k, v in (t.resources or self._resources).items():
                held[k] = held.get(k, 0) + (v or 0)
        for k, need in (trial.resources or self._resources).items():
            if need and total.get(k, 0) - held.get(k, 0) < need:
                return False
        return True

    def step(self) -> bool:
        """One controller iteration; False when everything is done."""
        # 1. Launch new/pending trials up to the concurrency AND
        # cluster-capacity caps.
        launch_cap = min(self._max_concurrent, self._resource_cap())
        while len(self._running()) < launch_cap:
            pending = next((t for t in self.trials if t.state == "PENDING"),
                           None)
            if pending is None:
                pending = self._make_trial()
            if pending is None:
                break
            # A demanded resource the cluster can NEVER satisfy still
            # launches when nothing is running (one launch surfaces the
            # pend/failure); otherwise wait for capacity to free up.
            if not self._fits(pending) and self._running():
                break
            try:
                self._start_trial(pending)
            except Exception as e:
                self._stop_trial(pending, "ERROR", e)

        running = self._running()
        if not running:
            return False

        # 2. Wait for any trial to produce a report (or finish).
        refs = [t.pending_ref for t in running]
        ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=1.0)
        for t in running:
            if t.pending_ref not in ready:
                continue
            try:
                item = ray_tpu.get(t.pending_ref)
            except Exception as e:
                self._on_trial_error(t, e)
                continue
            if item is None:  # finished cleanly
                self._stop_trial(t, "TERMINATED")
                continue
            metrics, checkpoint = item
            t.iteration += 1
            metrics.setdefault("training_iteration", t.iteration)
            metrics["trial_id"] = t.trial_id
            t.last_result = metrics
            t.results.append(metrics)
            if checkpoint is not None:
                t.checkpoint = checkpoint
            self._searcher.on_trial_result(t.trial_id, metrics)
            decision = self._scheduler.on_trial_result(t, metrics)
            if decision == sched_mod.STOP:
                if t.explored_config is not None:
                    self._exploit_explore(t)
                elif t.new_resources is not None:
                    self._change_resources(t)
                else:
                    self._stop_trial(t, "TERMINATED")
            else:
                t.pending_ref = t.actor.get_next.remote(None)
        return True

    def _on_trial_error(self, trial: Trial, error: BaseException):
        if trial.restarts < self._max_failures or self._max_failures == -1:
            trial.restarts += 1
            logger.warning("trial %s failed (%s); restarting (%d/%s)",
                           trial.trial_id, error, trial.restarts,
                           self._max_failures)
            self._teardown_actor(trial)
            try:
                self._start_trial(trial)
            except Exception as e:
                self._stop_trial(trial, "ERROR", e)
        else:
            self._stop_trial(trial, "ERROR", error)

    def _exploit_explore(self, trial: Trial):
        """PBT restart: adopt donor checkpoint + explored config."""
        donor = trial.exploit_from
        logger.info("PBT: %s exploits %s", trial.trial_id, donor.trial_id)
        trial.config = trial.explored_config
        trial.checkpoint = donor.checkpoint
        trial.exploit_from = None
        trial.explored_config = None
        self._teardown_actor(trial)
        try:
            self._start_trial(trial)
        except Exception as e:
            self._stop_trial(trial, "ERROR", e)

    def _change_resources(self, trial: Trial):
        """ResourceChangingScheduler restart: same config, latest
        checkpoint, new resource allocation."""
        logger.info("resources: %s -> %s for %s",
                    trial.resources or self._resources, trial.new_resources,
                    trial.trial_id)
        trial.resources = trial.new_resources
        trial.new_resources = None
        self._capacity_cap_at = 0.0  # held-resources changed: recompute
        self._teardown_actor(trial)
        try:
            self._start_trial(trial)
        except Exception as e:
            self._stop_trial(trial, "ERROR", e)

    def _teardown_actor(self, trial: Trial):
        if trial.actor is not None:
            try:
                ray_tpu.kill(trial.actor)
            except Exception:
                pass
            trial.actor = None
        trial.pending_ref = None

    # ---------------- experiment state (reference:
    # tune/execution/experiment_state.py + Tuner.restore) ----------------

    def save_experiment_state(self) -> None:
        """Snapshot trials + searcher/scheduler so a killed experiment
        resumes where it stopped (finished trials keep results; in-flight
        trials restart from their latest checkpoint)."""
        if not self._experiment_path:
            return
        import os

        import cloudpickle
        state = {
            "next_index": self._next_index,
            "searcher": self._searcher,
            "scheduler": self._scheduler,
            "trials": [{
                "trial_id": t.trial_id,
                "config": t.config,
                "state": t.state,
                "last_result": t.last_result,
                "results": t.results,
                "checkpoint": t.checkpoint,
                "iteration": t.iteration,
                "restarts": t.restarts,
                "resources": t.resources,
                "error": repr(t.error) if t.error is not None else None,
            } for t in self.trials],
        }
        path = os.path.join(self._experiment_path, "experiment_state.pkl")
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            cloudpickle.dump(state, f)
        os.replace(tmp, path)

    def restore_experiment_state(self, path: str,
                                 resume_errored: bool = True) -> None:
        import os

        import cloudpickle
        with open(os.path.join(path, "experiment_state.pkl"), "rb") as f:
            state = cloudpickle.load(f)
        self._next_index = state["next_index"]
        self._searcher = state["searcher"]
        self._scheduler = state["scheduler"]
        if hasattr(self._scheduler, "base_resources"):
            self._scheduler.controller = self
        self.trials = []
        for ts in state["trials"]:
            trial = Trial(trial_id=ts["trial_id"], config=ts["config"])
            trial.last_result = ts["last_result"]
            trial.results = ts["results"]
            trial.checkpoint = ts["checkpoint"]
            trial.iteration = ts["iteration"]
            trial.restarts = ts["restarts"]
            trial.resources = ts.get("resources")
            # In-flight trials resume from their latest checkpoint;
            # errored ones too when resume_errored (reference:
            # Tuner.restore resume_errored/restart_errored flags).
            resumable = ("RUNNING", "PENDING") + (
                ("ERROR",) if resume_errored else ())
            if ts["state"] in resumable:
                trial.state = "PENDING"
            else:
                trial.state = ts["state"]
                if ts["state"] == "ERROR" and ts.get("error"):
                    trial.error = RuntimeError(ts["error"])
            self.trials.append(trial)
        self._experiment_path = path

    def run(self, deadline_s: Optional[float] = None):
        start = time.monotonic()
        last_save = 0.0
        while self.step():
            # Snapshot cost grows with history — throttle mid-run saves
            # (a crash loses at most save_interval of progress; resume
            # replays from the last checkpointed state).
            if time.monotonic() - last_save >= 5.0:
                self.save_experiment_state()
                last_save = time.monotonic()
            if deadline_s and time.monotonic() - start > deadline_s:
                for t in self._running():
                    self._stop_trial(t, "TERMINATED")
                break
        self.save_experiment_state()
