"""ray_tpu.tune — hyperparameter search over trial actors.

Reference parity: python/ray/tune/ (SURVEY.md §2.3): Tuner/tune.run event
loop over trial actors, ASHA/median/PBT schedulers, grid/random search with
pluggable Searcher interface, Train integration (a Trainer is a trainable).
"""

from ray_tpu.tune.controller import Trial, TuneController  # noqa: F401
from ray_tpu.tune.schedulers import (  # noqa: F401
    ASHAScheduler,
    DistributeResources,
    FIFOScheduler,
    HyperBandScheduler,
    MedianStoppingRule,
    PB2,
    PopulationBasedTraining,
    ResourceChangingScheduler,
    TrialScheduler,
)
from ray_tpu.tune.search import (  # noqa: F401
    BasicVariantGenerator,
    Categorical,
    ConcurrencyLimiter,
    Searcher,
    BOHBSearcher,
    TPESearcher,
    choice,
    grid_search,
    loguniform,
    randint,
    uniform,
)
from ray_tpu.tune.tuner import (  # noqa: F401
    ResultGrid,
    TuneConfig,
    Tuner,
    run,
)

# Worker-side reporting inside trainables (reference: ray.tune.report /
# ray.air.session inside function trainables).
from ray_tpu.train.session import get_checkpoint, report  # noqa: F401
