"""Trial schedulers: early stopping and population-based training.

Reference parity: python/ray/tune/schedulers/ — FIFOScheduler,
ASHAScheduler (async_hyperband.py), MedianStoppingRule
(median_stopping_rule.py), PopulationBasedTraining (pbt.py).
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class TrialScheduler:
    metric: Optional[str] = None
    mode: Optional[str] = None

    def set_search_properties(self, metric: Optional[str],
                              mode: Optional[str]):
        """Adopt TuneConfig's metric/mode unless this scheduler was
        constructed with explicit ones (reference: schedulers propagate
        metric/mode from tune.run)."""
        if self.metric is None:
            self.metric = metric
        if self.mode is None:
            self.mode = mode or "min"

    def _require_metric(self):
        if self.metric is None:
            raise ValueError(
                f"{type(self).__name__} needs a metric — pass metric= to "
                f"the scheduler or set TuneConfig.metric")

    def on_trial_result(self, trial, result: dict) -> str:
        return CONTINUE

    def on_trial_complete(self, trial, result: Optional[dict]):
        pass


class FIFOScheduler(TrialScheduler):
    pass


class ASHAScheduler(TrialScheduler):
    """Asynchronous successive halving (reference: async_hyperband.py).

    Rung milestones r, r*eta, r*eta^2, ... up to max_t; at each rung a
    trial continues only if its metric is in the top 1/eta of results
    recorded at that rung so far (async: no waiting for full brackets).
    """

    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None,
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 4,
                 time_attr: str = "training_iteration"):
        assert mode in (None, "min", "max")
        self.metric, self.mode = metric, mode
        self.time_attr = time_attr
        self.max_t = max_t
        self.grace = grace_period
        self.eta = reduction_factor
        self.rungs: List[int] = []
        t = grace_period
        while t < max_t:
            self.rungs.append(t)
            t *= reduction_factor
        self._recorded: Dict[int, list] = {r: [] for r in self.rungs}

    def on_trial_result(self, trial, result: dict) -> str:
        self._require_metric()
        t = result.get(self.time_attr)
        value = result.get(self.metric)
        if t is None or value is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        for rung in reversed(self.rungs):
            if t >= rung and rung not in trial.reached_rungs:
                trial.reached_rungs.add(rung)
                recorded = self._recorded[rung]
                recorded.append(value)
                if len(recorded) < self.eta:
                    return CONTINUE  # too few peers to judge
                ordered = sorted(recorded, reverse=(self.mode == "max"))
                cutoff = ordered[max(0, len(ordered) // self.eta - 1)]
                good = (value >= cutoff if self.mode == "max"
                        else value <= cutoff)
                return CONTINUE if good else STOP
        return CONTINUE


class MedianStoppingRule(TrialScheduler):
    """Stop trials whose best result is worse than the median of running
    averages (reference: median_stopping_rule.py)."""

    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None,
                 min_samples_required: int = 3, grace_period: int = 1,
                 time_attr: str = "training_iteration"):
        assert mode in (None, "min", "max")
        self.metric, self.mode = metric, mode
        self.min_samples = min_samples_required
        self.grace = grace_period
        self.time_attr = time_attr
        self._avgs: Dict[str, list] = {}

    def on_trial_result(self, trial, result: dict) -> str:
        self._require_metric()
        t = result.get(self.time_attr, 0)
        value = result.get(self.metric)
        if value is None:
            return CONTINUE
        self._avgs.setdefault(trial.trial_id, []).append(value)
        if t < self.grace or len(self._avgs) < self.min_samples:
            return CONTINUE
        import statistics
        running = [statistics.fmean(v) for v in self._avgs.values()]
        median = statistics.median(running)
        mine = statistics.fmean(self._avgs[trial.trial_id])
        ok = mine >= median if self.mode == "max" else mine <= median
        return CONTINUE if ok else STOP


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference: pbt.py): at each perturbation interval, bottom-
    quantile trials exploit (clone checkpoint + config of a top-quantile
    trial) and explore (perturb hyperparams).  The controller performs the
    actual restart; this scheduler records the decision on the trial."""

    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None,
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Optional[dict] = None,
                 quantile_fraction: float = 0.25,
                 perturbation_factors=(0.8, 1.2), seed: Optional[int] = None,
                 time_attr: str = "training_iteration"):
        self.metric, self.mode = metric, mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.factors = perturbation_factors
        self.time_attr = time_attr
        self._rng = random.Random(seed)
        self._latest: Dict[str, dict] = {}

    def on_trial_result(self, trial, result: dict) -> str:
        self._require_metric()
        t = result.get(self.time_attr, 0)
        value = result.get(self.metric)
        if value is None:
            return CONTINUE
        self._latest[trial.trial_id] = {"value": value, "trial": trial}
        if t == 0 or t % self.interval:
            return CONTINUE
        peers = sorted(self._latest.values(), key=lambda e: e["value"],
                       reverse=(self.mode == "max"))
        n = len(peers)
        k = max(1, int(n * self.quantile))
        if n < 2 * k:
            return CONTINUE
        bottom = {e["trial"].trial_id for e in peers[-k:]}
        if trial.trial_id not in bottom:
            return CONTINUE
        donor = self._rng.choice(peers[:k])["trial"]
        if donor.checkpoint is None:
            return CONTINUE
        trial.exploit_from = donor
        trial.explored_config = self._explore(dict(donor.config))
        return STOP  # controller restarts it with the new config+checkpoint

    def on_trial_complete(self, trial, result=None):
        # Dead trials must not occupy quantile slots or act as donors.
        self._latest.pop(trial.trial_id, None)

    def _explore(self, config: dict) -> dict:
        for key, spec in self.mutations.items():
            if key not in config:
                continue
            if isinstance(spec, list):
                config[key] = self._rng.choice(spec)
            elif callable(spec):
                config[key] = spec()
            else:  # numeric: scale by a perturbation factor
                config[key] = config[key] * self._rng.choice(self.factors)
        return config


class HyperBandScheduler(TrialScheduler):
    """HyperBand as a family of successive-halving brackets.

    Reference: tune/schedulers/hyperband.py.  Trials are assigned
    round-robin to brackets b = 0..s_max; bracket b starts trials at
    budget max_t / eta^(s_max - b) and halves asynchronously at each rung
    (the pause-free asynchronous formulation — each bracket behaves like
    an ASHA instance with its own grace period, which preserves
    HyperBand's exploration/exploitation spread without requiring trial
    pause/resume support in the executor).
    """

    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None,
                 max_t: int = 81, reduction_factor: int = 3,
                 time_attr: str = "training_iteration"):
        assert mode in (None, "min", "max")
        self.metric, self.mode = metric, mode
        self.time_attr = time_attr
        self.max_t = max_t
        self.eta = reduction_factor
        import math
        self.s_max = int(math.log(max_t) / math.log(reduction_factor))
        # bracket index -> list of rung budgets (ascending)
        self.brackets: List[List[int]] = []
        for s in range(self.s_max, -1, -1):
            start = max(1, max_t // (reduction_factor ** s))
            rungs = []
            t = start
            while t < max_t:
                rungs.append(t)
                t *= reduction_factor
            self.brackets.append(rungs)
        self._recorded: Dict[tuple, list] = {}   # (bracket, rung) -> values
        self._assigned: Dict[str, int] = {}      # trial_id -> bracket
        self._next_bracket = 0

    def _bracket_of(self, trial) -> int:
        b = self._assigned.get(trial.trial_id)
        if b is None:
            b = self._next_bracket % len(self.brackets)
            self._next_bracket += 1
            self._assigned[trial.trial_id] = b
        return b

    def on_trial_result(self, trial, result: dict) -> str:
        self._require_metric()
        t = result.get(self.time_attr)
        value = result.get(self.metric)
        if t is None or value is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        b = self._bracket_of(trial)
        for rung in reversed(self.brackets[b]):
            if t >= rung and (b, rung) not in trial.reached_rungs:
                trial.reached_rungs.add((b, rung))
                recorded = self._recorded.setdefault((b, rung), [])
                recorded.append(value)
                if len(recorded) < self.eta:
                    return CONTINUE
                ordered = sorted(recorded, reverse=(self.mode == "max"))
                cutoff = ordered[max(0, len(ordered) // self.eta - 1)]
                good = (value >= cutoff if self.mode == "max"
                        else value <= cutoff)
                return CONTINUE if good else STOP
        return CONTINUE
