"""Trial schedulers: early stopping and population-based training.

Reference parity: python/ray/tune/schedulers/ — FIFOScheduler,
ASHAScheduler (async_hyperband.py), MedianStoppingRule
(median_stopping_rule.py), PopulationBasedTraining (pbt.py).
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class TrialScheduler:
    metric: Optional[str] = None
    mode: Optional[str] = None

    def set_search_properties(self, metric: Optional[str],
                              mode: Optional[str]):
        """Adopt TuneConfig's metric/mode unless this scheduler was
        constructed with explicit ones (reference: schedulers propagate
        metric/mode from tune.run)."""
        if self.metric is None:
            self.metric = metric
        if self.mode is None:
            self.mode = mode or "min"

    def _require_metric(self):
        if self.metric is None:
            raise ValueError(
                f"{type(self).__name__} needs a metric — pass metric= to "
                f"the scheduler or set TuneConfig.metric")

    def on_trial_result(self, trial, result: dict) -> str:
        return CONTINUE

    def on_trial_complete(self, trial, result: Optional[dict]):
        pass


class FIFOScheduler(TrialScheduler):
    pass


class ASHAScheduler(TrialScheduler):
    """Asynchronous successive halving (reference: async_hyperband.py).

    Rung milestones r, r*eta, r*eta^2, ... up to max_t; at each rung a
    trial continues only if its metric is in the top 1/eta of results
    recorded at that rung so far (async: no waiting for full brackets).
    """

    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None,
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 4,
                 time_attr: str = "training_iteration"):
        assert mode in (None, "min", "max")
        self.metric, self.mode = metric, mode
        self.time_attr = time_attr
        self.max_t = max_t
        self.grace = grace_period
        self.eta = reduction_factor
        self.rungs: List[int] = []
        t = grace_period
        while t < max_t:
            self.rungs.append(t)
            t *= reduction_factor
        self._recorded: Dict[int, list] = {r: [] for r in self.rungs}

    def on_trial_result(self, trial, result: dict) -> str:
        self._require_metric()
        t = result.get(self.time_attr)
        value = result.get(self.metric)
        if t is None or value is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        for rung in reversed(self.rungs):
            if t >= rung and rung not in trial.reached_rungs:
                trial.reached_rungs.add(rung)
                recorded = self._recorded[rung]
                recorded.append(value)
                if len(recorded) < self.eta:
                    return CONTINUE  # too few peers to judge
                ordered = sorted(recorded, reverse=(self.mode == "max"))
                cutoff = ordered[max(0, len(ordered) // self.eta - 1)]
                good = (value >= cutoff if self.mode == "max"
                        else value <= cutoff)
                return CONTINUE if good else STOP
        return CONTINUE


class MedianStoppingRule(TrialScheduler):
    """Stop trials whose best result is worse than the median of running
    averages (reference: median_stopping_rule.py)."""

    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None,
                 min_samples_required: int = 3, grace_period: int = 1,
                 time_attr: str = "training_iteration"):
        assert mode in (None, "min", "max")
        self.metric, self.mode = metric, mode
        self.min_samples = min_samples_required
        self.grace = grace_period
        self.time_attr = time_attr
        self._avgs: Dict[str, list] = {}

    def on_trial_result(self, trial, result: dict) -> str:
        self._require_metric()
        t = result.get(self.time_attr, 0)
        value = result.get(self.metric)
        if value is None:
            return CONTINUE
        self._avgs.setdefault(trial.trial_id, []).append(value)
        if t < self.grace or len(self._avgs) < self.min_samples:
            return CONTINUE
        import statistics
        running = [statistics.fmean(v) for v in self._avgs.values()]
        median = statistics.median(running)
        mine = statistics.fmean(self._avgs[trial.trial_id])
        ok = mine >= median if self.mode == "max" else mine <= median
        return CONTINUE if ok else STOP


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference: pbt.py): at each perturbation interval, bottom-
    quantile trials exploit (clone checkpoint + config of a top-quantile
    trial) and explore (perturb hyperparams).  The controller performs the
    actual restart; this scheduler records the decision on the trial."""

    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None,
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Optional[dict] = None,
                 quantile_fraction: float = 0.25,
                 perturbation_factors=(0.8, 1.2), seed: Optional[int] = None,
                 time_attr: str = "training_iteration"):
        self.metric, self.mode = metric, mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.factors = perturbation_factors
        self.time_attr = time_attr
        self._rng = random.Random(seed)
        self._latest: Dict[str, dict] = {}

    def on_trial_result(self, trial, result: dict) -> str:
        self._require_metric()
        t = result.get(self.time_attr, 0)
        value = result.get(self.metric)
        if value is None:
            return CONTINUE
        self._latest[trial.trial_id] = {"value": value, "trial": trial}
        if t == 0 or t % self.interval:
            return CONTINUE
        peers = sorted(self._latest.values(), key=lambda e: e["value"],
                       reverse=(self.mode == "max"))
        n = len(peers)
        k = max(1, int(n * self.quantile))
        if n < 2 * k:
            return CONTINUE
        bottom = {e["trial"].trial_id for e in peers[-k:]}
        if trial.trial_id not in bottom:
            return CONTINUE
        donor = self._rng.choice(peers[:k])["trial"]
        if donor.checkpoint is None:
            return CONTINUE
        trial.exploit_from = donor
        trial.explored_config = self._explore(dict(donor.config))
        return STOP  # controller restarts it with the new config+checkpoint

    def on_trial_complete(self, trial, result=None):
        # Dead trials must not occupy quantile slots or act as donors.
        self._latest.pop(trial.trial_id, None)

    def _explore(self, config: dict) -> dict:
        for key, spec in self.mutations.items():
            if key not in config:
                continue
            if isinstance(spec, list):
                config[key] = self._rng.choice(spec)
            elif callable(spec):
                config[key] = spec()
            else:  # numeric: scale by a perturbation factor
                config[key] = config[key] * self._rng.choice(self.factors)
        return config


class PB2(PopulationBasedTraining):
    """Population Based Bandits (reference: tune/schedulers/pb2.py,
    Parker-Holder et al. 2020).

    PBT's random explore step is replaced by a GP-UCB suggestion: a
    Gaussian process models the per-window reward CHANGE as a function of
    (normalized time, hyperparameters); the exploited trial's new config
    maximizes UCB = mu + kappa*sigma over candidates sampled inside
    `hyperparam_bounds`.  Unlike the reference we fit a small numpy GP
    (RBF kernel over [t, hparams]) rather than depending on GPy — the
    time dimension gives the paper's time-varying behavior (stale windows
    decorrelate from current candidates as t grows).
    """

    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None,
                 perturbation_interval: int = 4,
                 hyperparam_bounds: Optional[Dict[str, list]] = None,
                 quantile_fraction: float = 0.25,
                 seed: Optional[int] = None,
                 time_attr: str = "training_iteration",
                 ucb_kappa: float = 2.0, n_candidates: int = 256):
        super().__init__(metric=metric, mode=mode,
                         perturbation_interval=perturbation_interval,
                         hyperparam_mutations={},
                         quantile_fraction=quantile_fraction, seed=seed,
                         time_attr=time_attr)
        if not hyperparam_bounds:
            raise ValueError("PB2 needs hyperparam_bounds={key: [lo, hi]}")
        self.bounds = {k: (float(lo), float(hi))
                       for k, (lo, hi) in hyperparam_bounds.items()}
        self.kappa = ucb_kappa
        self.n_candidates = n_candidates
        self._data: List[tuple] = []      # (t, config_vec, reward_delta)
        self._window_start: Dict[str, float] = {}  # trial_id -> metric value

    def on_trial_result(self, trial, result: dict) -> str:
        value = result.get(self.metric)
        t = result.get(self.time_attr, 0)
        if value is not None and t and t % self.interval == 0:
            prev = self._window_start.get(trial.trial_id)
            if prev is not None:
                delta = value - prev
                if self.mode != "max":
                    delta = -delta
                vec = [self._norm(k, trial.config.get(k)) for k in self.bounds]
                if None not in vec:
                    self._data.append((float(t), vec, delta))
            self._window_start[trial.trial_id] = value
        decision = super().on_trial_result(trial, result)
        if trial.explored_config is not None:
            # Exploit/explore restart: the next window starts from the
            # DONOR's score, so the pre-clone window must not attribute
            # the checkpoint jump to the newly explored config.
            self._window_start.pop(trial.trial_id, None)
        return decision

    def _norm(self, key, v):
        if v is None:
            return None
        lo, hi = self.bounds[key]
        return (float(v) - lo) / (hi - lo) if hi > lo else 0.0

    def _explore(self, config: dict) -> dict:
        import numpy as np
        keys = list(self.bounds)
        cand = np.array([[self._rng.random() for _ in keys]
                         for _ in range(self.n_candidates)])
        if len(self._data) >= 4:
            tmax = max(d[0] for d in self._data) or 1.0
            X = np.array([[d[0] / tmax] + d[1] for d in self._data])
            y = np.array([d[2] for d in self._data], dtype=float)
            # Candidates are evaluated at "now" (t = tmax -> normalized 1).
            C = np.hstack([np.ones((len(cand), 1)), cand])
            best = cand[int(np.argmax(self._gp_ucb(X, y, C)))]
        else:  # cold start: uniform random inside the bounds
            best = cand[0]
        out = dict(config)
        for i, k in enumerate(keys):
            lo, hi = self.bounds[k]
            out[k] = lo + float(best[i]) * (hi - lo)
        return out

    def _gp_ucb(self, X, y, C):
        """UCB scores for candidate rows C under an RBF-kernel GP fit to
        (X, y).  Normalized y; fixed length scale 0.3 on unit-box inputs;
        jitter for conditioning."""
        import numpy as np
        ystd = y.std()
        yn = (y - y.mean()) / (ystd if ystd > 0 else 1.0)
        ls2 = 2 * 0.3 * 0.3
        d2 = ((X[:, None, :] - X[None, :, :]) ** 2).sum(-1)
        K = np.exp(-d2 / ls2) + 1e-4 * np.eye(len(X))
        d2c = ((C[:, None, :] - X[None, :, :]) ** 2).sum(-1)
        Kc = np.exp(-d2c / ls2)
        Kinv_y = np.linalg.solve(K, yn)
        mu = Kc @ Kinv_y
        var = 1.0 - (Kc * np.linalg.solve(K, Kc.T).T).sum(1)
        return mu + self.kappa * np.sqrt(np.maximum(var, 1e-9))


class DistributeResources:
    """Default allocation policy for ResourceChangingScheduler (reference:
    resource_changing_scheduler.py DistributeResources): split the
    cluster's total CPUs evenly among live trials, never below the trial's
    base request.  Returns None when the allocation is unchanged."""

    def __init__(self, resource: str = "CPU"):
        self.resource = resource

    def __call__(self, trial, result, base_resources: dict,
                 total_resources: dict, n_live: int) -> Optional[dict]:
        total = total_resources.get(self.resource, 0)
        base = base_resources.get(self.resource, 1)
        share = max(base, int(total // max(1, n_live)))
        current = dict(trial.resources or base_resources)
        if current.get(self.resource, base) == share:
            return None
        current[self.resource] = share
        return current


class ResourceChangingScheduler(TrialScheduler):
    """Wrap a base scheduler and periodically reallocate trial resources
    (reference: tune/schedulers/resource_changing_scheduler.py).

    Every `resource_interval` iterations the allocation function proposes
    new resources for the trial; when they differ from the current ones
    the scheduler records them on `trial.new_resources` and returns STOP —
    the controller restarts the trial from its latest checkpoint under the
    new allocation (the reference updates the placement group the same
    restart-driven way for function trainables).
    """

    def __init__(self, base_scheduler: Optional[TrialScheduler] = None,
                 resources_allocation_function=None,
                 resource_interval: int = 4,
                 time_attr: str = "training_iteration"):
        self.base = base_scheduler or FIFOScheduler()
        self.alloc = resources_allocation_function or DistributeResources()
        self.interval = resource_interval
        self.time_attr = time_attr
        self._live: Dict[str, Any] = {}
        self.base_resources: dict = {"CPU": 1}  # controller injects
        self.controller = None                  # controller injects

    def set_search_properties(self, metric, mode):
        super().set_search_properties(metric, mode)
        self.base.set_search_properties(metric, mode)

    def on_trial_result(self, trial, result: dict) -> str:
        self._live[trial.trial_id] = trial
        decision = self.base.on_trial_result(trial, result)
        if decision == STOP:
            return STOP
        t = result.get(self.time_attr, 0)
        if t and t % self.interval == 0:
            try:
                import ray_tpu
                total = ray_tpu.cluster_resources()
            except Exception:
                total = {}
            # Live = the controller's RUNNING/PENDING trials, not trials
            # seen so far: dividing by an early partial count hands the
            # first reporter the whole cluster and livelocks the rest.
            if self.controller is not None:
                n_live = sum(t.state in ("RUNNING", "PENDING")
                             for t in self.controller.trials)
            else:
                n_live = len(self._live)
            new = self.alloc(trial, result, self.base_resources, total,
                             n_live)
            if new is not None:
                # Reallocation works by stop-and-restart: without a
                # checkpoint to restore from, the restart would silently
                # rerun the trial from scratch.  Defer until one exists
                # (the next interval hit re-evaluates).
                if getattr(trial, "checkpoint", None) is None:
                    return CONTINUE
                trial.new_resources = new
                return STOP  # controller restarts under the new resources
        return CONTINUE

    def on_trial_complete(self, trial, result=None):
        self._live.pop(trial.trial_id, None)
        self.base.on_trial_complete(trial, result)

    def __getstate__(self):
        # The controller back-ref (actor handles, live trials) must not
        # ride experiment-state snapshots; it is re-injected on restore.
        state = dict(self.__dict__)
        state["controller"] = None
        state["_live"] = {}
        return state


class HyperBandScheduler(TrialScheduler):
    """HyperBand as a family of successive-halving brackets.

    Reference: tune/schedulers/hyperband.py.  Trials are assigned
    round-robin to brackets b = 0..s_max; bracket b starts trials at
    budget max_t / eta^(s_max - b) and halves asynchronously at each rung
    (the pause-free asynchronous formulation — each bracket behaves like
    an ASHA instance with its own grace period, which preserves
    HyperBand's exploration/exploitation spread without requiring trial
    pause/resume support in the executor).
    """

    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None,
                 max_t: int = 81, reduction_factor: int = 3,
                 time_attr: str = "training_iteration"):
        assert mode in (None, "min", "max")
        self.metric, self.mode = metric, mode
        self.time_attr = time_attr
        self.max_t = max_t
        self.eta = reduction_factor
        import math
        self.s_max = int(math.log(max_t) / math.log(reduction_factor))
        # bracket index -> list of rung budgets (ascending)
        self.brackets: List[List[int]] = []
        for s in range(self.s_max, -1, -1):
            start = max(1, max_t // (reduction_factor ** s))
            rungs = []
            t = start
            while t < max_t:
                rungs.append(t)
                t *= reduction_factor
            self.brackets.append(rungs)
        self._recorded: Dict[tuple, list] = {}   # (bracket, rung) -> values
        self._assigned: Dict[str, int] = {}      # trial_id -> bracket
        self._next_bracket = 0

    def _bracket_of(self, trial) -> int:
        b = self._assigned.get(trial.trial_id)
        if b is None:
            b = self._next_bracket % len(self.brackets)
            self._next_bracket += 1
            self._assigned[trial.trial_id] = b
        return b

    def on_trial_result(self, trial, result: dict) -> str:
        self._require_metric()
        t = result.get(self.time_attr)
        value = result.get(self.metric)
        if t is None or value is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        b = self._bracket_of(trial)
        for rung in reversed(self.brackets[b]):
            if t >= rung and (b, rung) not in trial.reached_rungs:
                trial.reached_rungs.add((b, rung))
                recorded = self._recorded.setdefault((b, rung), [])
                recorded.append(value)
                if len(recorded) < self.eta:
                    return CONTINUE
                ordered = sorted(recorded, reverse=(self.mode == "max"))
                cutoff = ordered[max(0, len(ordered) // self.eta - 1)]
                good = (value >= cutoff if self.mode == "max"
                        else value <= cutoff)
                return CONTINUE if good else STOP
        return CONTINUE
