"""Tuner / tune.run / ResultGrid — the public Tune surface.

Reference parity: python/ray/tune/tuner.py (Tuner.fit), tune.py:232
(tune.run), result_grid.py (ResultGrid), tune_config.py (TuneConfig).
Train integration as in the reference: a Trainer is just a trainable
(base_trainer.py:557 wraps fit into a single-trial tune run).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Union

from ray_tpu.air.config import RunConfig
from ray_tpu.train.data_parallel_trainer import BaseTrainer, Result
from ray_tpu.tune.controller import TuneController
from ray_tpu.tune.schedulers import TrialScheduler
from ray_tpu.tune.search import BasicVariantGenerator, Searcher


class _SampleCap:
    """Bounds a never-exhausting searcher at num_samples suggestions
    (delegating everything else)."""

    def __init__(self, searcher, limit: int):
        self._s = searcher
        self._left = limit

    def suggest(self, trial_id):
        if self._left <= 0:
            return None
        cfg = self._s.suggest(trial_id)
        if cfg is not None:
            self._left -= 1
        return cfg

    def on_trial_result(self, trial_id, result):
        if hasattr(self._s, "on_trial_result"):
            self._s.on_trial_result(trial_id, result)

    def on_trial_complete(self, trial_id, result=None, error=False):
        self._s.on_trial_complete(trial_id, result, error)

    def __getattr__(self, name):
        return getattr(self._s, name)


@dataclass
class TuneConfig:
    """Reference: tune/tune_config.py."""

    metric: Optional[str] = None
    mode: str = "min"
    num_samples: int = 1
    max_concurrent_trials: int = 8
    search_alg: Optional[Searcher] = None
    scheduler: Optional[TrialScheduler] = None
    seed: Optional[int] = None
    time_budget_s: Optional[float] = None


class ResultGrid:
    """Reference: tune/result_grid.py."""

    def __init__(self, results: list, metric: Optional[str], mode: str):
        self._results = results
        self._metric = metric
        self._mode = mode

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i) -> Result:
        return self._results[i]

    def __iter__(self):
        return iter(self._results)

    @property
    def errors(self) -> list:
        return [r.error for r in self._results if r.error is not None]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("metric required (set TuneConfig.metric)")
        scored = [r for r in self._results
                  if r.metrics and metric in r.metrics]
        if not scored:
            raise ValueError(f"no trial reported metric {metric!r}")
        return (max if mode == "max" else min)(
            scored, key=lambda r: r.metrics[metric])

    def get_dataframe(self):
        import pandas as pd
        return pd.DataFrame([r.metrics for r in self._results if r.metrics])


class Tuner:
    """Reference: tune/tuner.py."""

    def __init__(self, trainable: Union[Callable, BaseTrainer], *,
                 param_space: Optional[Dict[str, Any]] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 resources_per_trial: Optional[dict] = None):
        if isinstance(trainable, BaseTrainer):
            # Trial actor only orchestrates; the trainer's own WorkerGroup
            # holds the real resources.  Callers can still override.
            self._resources = dict(resources_per_trial or {"CPU": 0.5})
            trainable = trainable.as_trainable()
        else:
            self._resources = dict(resources_per_trial or {"CPU": 1})
        self._trainable = trainable
        self._param_space = dict(param_space or {})
        self._tune_config = tune_config or TuneConfig()
        self._run_config = run_config or RunConfig()
        self._restore_path: Optional[str] = None

    def _experiment_path(self) -> Optional[str]:
        """storage_path/name (reference: air.RunConfig storage layout);
        experiment state persists here for Tuner.restore."""
        import os
        import time as _time
        rc = self._run_config
        if self._restore_path:
            return self._restore_path
        if rc.storage_path is None and rc.name is None:
            return None
        root = rc.storage_path or os.path.join(
            os.path.expanduser("~"), "ray_tpu_results")
        name = rc.name or f"tune_{int(_time.time())}"
        return os.path.join(root, name)

    @classmethod
    def restore(cls, path: str, trainable=None, *,
                resume_errored: bool = True,
                resources_per_trial: Optional[dict] = None) -> "Tuner":
        """Resume an interrupted experiment from its storage directory
        (reference: Tuner.restore / tune/execution/experiment_state.py).
        Finished trials keep their results; in-flight trials restart from
        their latest checkpoint."""
        import os

        import cloudpickle
        with open(os.path.join(path, "tuner.pkl"), "rb") as f:
            meta = cloudpickle.load(f)
        tuner = cls(trainable if trainable is not None
                    else meta["trainable"],
                    param_space=meta["param_space"],
                    tune_config=meta["tune_config"],
                    run_config=meta["run_config"],
                    resources_per_trial=(resources_per_trial
                                         or meta["resources"]))
        tuner._restore_path = path
        tuner._resume_errored = resume_errored
        return tuner

    def fit(self) -> ResultGrid:
        tc = self._tune_config
        searcher = tc.search_alg or BasicVariantGenerator(
            self._param_space, num_samples=tc.num_samples, seed=tc.seed)
        if tc.search_alg is not None and tc.num_samples:
            # Model-based searchers (TPE/BOHB) propose forever;
            # num_samples is the trial budget for them too (reference:
            # tune.run's num_samples caps any search_alg).
            searcher = _SampleCap(searcher, tc.num_samples)
        if tc.scheduler is not None:
            tc.scheduler.set_search_properties(tc.metric, tc.mode)
        exp_path = self._experiment_path()
        controller = TuneController(
            self._trainable,
            searcher=searcher,
            scheduler=tc.scheduler,
            max_concurrent=tc.max_concurrent_trials,
            resources_per_trial=self._resources,
            run_config=self._run_config,
            max_failures_per_trial=(
                self._run_config.failure_config.max_failures),
            experiment_path=exp_path)
        if self._restore_path:
            controller.restore_experiment_state(
                self._restore_path,
                resume_errored=getattr(self, "_resume_errored", True))
        elif exp_path:
            import os

            import cloudpickle
            os.makedirs(exp_path, exist_ok=True)
            with open(os.path.join(exp_path, "tuner.pkl"), "wb") as f:
                cloudpickle.dump({
                    "trainable": self._trainable,
                    "param_space": self._param_space,
                    "tune_config": tc,
                    "run_config": self._run_config,
                    "resources": self._resources,
                }, f)
        controller.run(deadline_s=tc.time_budget_s)
        results = []
        for trial in controller.trials:
            results.append(Result(
                metrics=(dict(trial.last_result, config=trial.config)
                         if trial.last_result else None),
                checkpoint=trial.checkpoint,
                error=trial.error,
                metrics_history=trial.results))
        return ResultGrid(results, tc.metric, tc.mode)


def run(trainable: Callable, *, config: Optional[dict] = None,
        num_samples: int = 1, metric: Optional[str] = None,
        mode: str = "min", scheduler: Optional[TrialScheduler] = None,
        search_alg: Optional[Searcher] = None,
        max_concurrent_trials: int = 8,
        resources_per_trial: Optional[dict] = None,
        time_budget_s: Optional[float] = None,
        run_config: Optional[RunConfig] = None) -> ResultGrid:
    """Reference: tune/tune.py:232 tune.run."""
    return Tuner(
        trainable,
        param_space=config,
        tune_config=TuneConfig(
            metric=metric, mode=mode, num_samples=num_samples,
            scheduler=scheduler, search_alg=search_alg,
            max_concurrent_trials=max_concurrent_trials,
            time_budget_s=time_budget_s),
        run_config=run_config,
        resources_per_trial=resources_per_trial,
    ).fit()
