"""Pull-based telemetry endpoints: /metrics, /events, /healthz.

A stdlib ``http.server`` thread that external scrapers (Prometheus, a
dashboard, plain ``curl``) hit without going through the CLI or the RPC
plane.  Both hostd and the driver run one:

  * ``/metrics``  — Prometheus exposition text (``util.metrics``
    ``prometheus_text``; on hostd this is the node-level merge of the
    daemon's registry plus every live worker's).
  * ``/events``   — the flight-recorder ring as JSON, filterable with
    ``?plane=&kind=&trace_id=&since=&limit=`` (on hostd: the node-level
    CollectEvents merge, crash dumps included).
  * ``/healthz``  — liveness + identity, for load balancers and the
    impatient.

The server rides the flight-recorder switch: with ``RAY_TPU_EVENTS=0``
``start_server`` returns None and nothing is bound.  Ports default to
ephemeral (several hostds share a laptop in tests); the bound port is
announced as a ``proc``/``telemetry_listen`` ring event, so
``state.events(kind="telemetry_listen")`` discovers every endpoint in
the cluster.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional
from urllib.parse import parse_qs, urlparse

logger = logging.getLogger(__name__)

# metrics_fn() -> prometheus exposition text
# events_fn(plane, kind, trace_id, since) -> list of event dicts
MetricsFn = Callable[[], str]
EventsFn = Callable[[Optional[str], Optional[str], Optional[str], float],
                    List[Dict[str, Any]]]


class TelemetryServer:
    """One daemon thread serving the three endpoints.  All handler work
    runs on short-lived per-request threads (ThreadingHTTPServer), so a
    slow scrape never blocks the process's event loop."""

    def __init__(self, *, metrics_fn: MetricsFn, events_fn: EventsFn,
                 component: str = "", host: str = "127.0.0.1",
                 port: int = 0,
                 healthz_fn: Optional[Callable[[], Dict[str, Any]]] = None):
        self.metrics_fn = metrics_fn
        self.events_fn = events_fn
        self.healthz_fn = healthz_fn
        self.component = component
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # no stderr chatter per scrape
                pass

            def _send(self, code: int, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                try:
                    url = urlparse(self.path)
                    if url.path == "/metrics":
                        body = outer.metrics_fn().encode()
                        self._send(200, body, "text/plain; version=0.0.4")
                    elif url.path == "/events":
                        q = parse_qs(url.query)

                        def one(name):
                            v = q.get(name)
                            return v[0] if v else None

                        since = float(one("since") or 0.0)
                        evs = outer.events_fn(one("plane"), one("kind"),
                                              one("trace_id"), since)
                        limit = one("limit")
                        if limit:
                            evs = evs[-int(limit):]
                        body = json.dumps(
                            {"events": evs, "count": len(evs)},
                            default=repr).encode()
                        self._send(200, body, "application/json")
                    elif url.path == "/healthz":
                        import os
                        import time
                        h = {"ok": True, "component": outer.component,
                             "pid": os.getpid(), "ts": time.time()}
                        if outer.healthz_fn is not None:
                            h.update(outer.healthz_fn())
                        self._send(200, json.dumps(h, default=repr).encode(),
                                   "application/json")
                    else:
                        self._send(404, b"not found\n", "text/plain")
                except BrokenPipeError:
                    pass
                except Exception as e:  # scrape bugs must not kill threads
                    try:
                        self._send(500, f"{e!r}\n".encode(), "text/plain")
                    except Exception:
                        pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host = self._httpd.server_address[0]
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "TelemetryServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.5},
            daemon=True, name="raytpu-telemetry")
        self._thread.start()
        return self

    def stop(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None


def start_server(*, metrics_fn: MetricsFn, events_fn: EventsFn,
                 component: str,
                 healthz_fn: Optional[Callable[[], Dict[str, Any]]] = None
                 ) -> Optional[TelemetryServer]:
    """Bind + start the endpoints per config, or return None when the
    flight recorder is off (``RAY_TPU_EVENTS=0`` disables telemetry
    cleanly), telemetry_port is -1, or the bind fails."""
    from ray_tpu._private.config import GLOBAL_CONFIG
    from ray_tpu.util import events
    if not GLOBAL_CONFIG.events:
        return None
    port = GLOBAL_CONFIG.telemetry_port
    if port < 0:
        return None
    try:
        srv = TelemetryServer(metrics_fn=metrics_fn, events_fn=events_fn,
                              component=component,
                              host=GLOBAL_CONFIG.telemetry_host, port=port,
                              healthz_fn=healthz_fn).start()
    except OSError as e:
        logger.warning("telemetry endpoints disabled: bind failed: %s", e)
        return None
    events.record("proc", "telemetry_listen", component=component,
                  host=srv.host, port=srv.port)
    logger.info("telemetry endpoints on http://%s:%d (%s)",
                srv.host, srv.port, component)
    return srv
