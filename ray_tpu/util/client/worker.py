"""ClientWorker: the client-side half of thin-client mode.

Reference parity: python/ray/util/client/worker.py — implements the same
surface the public API layer drives (put/get/wait/submit_task/
create_actor/submit_actor_task/kill/cancel + a GCS passthrough), every
call one RPC to the client server.
"""

from __future__ import annotations

import hashlib
import uuid
from typing import Any

import cloudpickle

from ray_tpu.object_ref import ObjectRef
from ray_tpu._private.ids import ActorID, ObjectID
from ray_tpu._private.rpc import EventLoopThread, RpcClient


class _GcsShim:
    """Looks like the driver's GCS client; proxies through the server."""

    def __init__(self, client: "ClientWorker"):
        self._client = client

    async def call(self, service: str, method: str, request=None,
                   timeout=None):
        reply = await self._client._rpc.call(
            "RayClient", "GcsCall",
            {"session": self._client._session,
             "service": service, "method": method,
             "timeout": timeout,
             "request": cloudpickle.dumps(request or {})},
            timeout=(timeout or 60) + 30)
        return cloudpickle.loads(reply["reply"])


class ClientWorker:
    """Drop-in for CoreWorker behind the public API, speaking RPC."""

    mode = "client"

    def __init__(self, address: str):
        self.address = address
        self.gcs_address = address  # state API etc. route via the shim
        self._session = uuid.uuid4().hex
        self.io = EventLoopThread("raytpu-client-io")
        self._rpc = RpcClient(address)
        self.gcs = _GcsShim(self)
        self.objects: dict = {}  # api-compat (observability introspection)
        self._release_buffer: list = []
        self.io.run(self._rpc.call(
            "RayClient", "Init", {"session": self._session}, timeout=30))
        # Keepalive: idle-but-connected clients must not hit the server's
        # session TTL (reference: client heartbeat); a cheap Init refresh
        # every 60s keeps last_seen current.
        import threading
        self._stop_keepalive = threading.Event()

        def _keepalive():
            while not self._stop_keepalive.wait(60.0):
                try:
                    self.io.run(self._rpc.call(
                        "RayClient", "Init",
                        {"session": self._session}, timeout=30))
                except Exception:
                    pass

        threading.Thread(target=_keepalive, daemon=True,
                         name="raytpu-client-keepalive").start()

    # ---------------- helpers ----------------

    def _call(self, method: str, req: dict, timeout=None):
        req["session"] = self._session
        # Piggyback pending ref releases (cheap, amortized).
        if self._release_buffer and method not in ("Release", "Disconnect"):
            ids, self._release_buffer = self._release_buffer, []
            self.io.run(self._rpc.call(
                "RayClient", "Release",
                {"session": self._session, "ids": ids}, timeout=30))
        return self.io.run(
            self._rpc.call("RayClient", method, req, timeout=timeout))

    @staticmethod
    def _encode_args(args, kwargs) -> bytes:
        """Deep serializer (reference: client ARCHITECTURE.md): refs and
        actor handles convert at ANY nesting depth — inside user objects,
        dataclasses, closures — via pickle persistent ids (codec.py)."""
        from ray_tpu.util.client import codec
        return codec.dumps((tuple(args), dict(kwargs)))

    def _decode_values(self, blob: bytes):
        """Results may CONTAIN refs/handles (e.g. a task returning a dict
        of refs): rebuild them as client-side objects that route through
        this server connection."""
        from ray_tpu.api import ActorHandle
        from ray_tpu.util.client import codec
        return codec.loads(
            blob,
            make_ref=lambda id_b, owner: self._mkref(id_b, owner),
            make_actor=lambda id_b: ActorHandle(
                ActorID(id_b), "remote", None))

    @staticmethod
    def _fn_blob(fn) -> tuple:
        blob = cloudpickle.dumps(fn)
        return blob, hashlib.sha1(blob).hexdigest().encode()

    def _mkref(self, id_binary: bytes, owner: str = "") -> ObjectRef:
        import weakref
        ref = ObjectRef(ObjectID(id_binary), owner or self.address,
                        _register=False)
        # Server-side pins release when the CLIENT ref is GC'd: ids batch
        # into the next RPC (reference: client refs release server state).
        weakref.finalize(ref, self._queue_release, id_binary)
        return ref

    def _queue_release(self, id_binary: bytes) -> None:
        self._release_buffer.append(id_binary)

    # ---------------- API surface ----------------

    def put(self, value) -> ObjectRef:
        reply = self._call("Put", {"value": cloudpickle.dumps(value)})
        return self._mkref(reply["id"])

    def get(self, refs, timeout=None):
        single = isinstance(refs, ObjectRef)
        rlist = [refs] if single else refs
        reply = self._call("Get", {
            "ids": [r.id.binary() for r in rlist],
            "owners": [r.owner_address or "" for r in rlist],
            "timeout": timeout},
            timeout=(timeout + 30) if timeout else None)
        if "error" in reply:
            raise cloudpickle.loads(reply["error"])
        values = self._decode_values(reply["values"])
        return values[0] if single else values

    def wait(self, refs, num_returns=1, timeout=None, fetch_local=True):
        by_id = {r.id.binary(): r for r in refs}
        reply = self._call("Wait", {
            "ids": [r.id.binary() for r in refs],
            "owners": [r.owner_address or "" for r in refs],
            "num_returns": num_returns, "timeout": timeout,
            "fetch_local": fetch_local},
            timeout=(timeout + 30) if timeout else None)
        return ([by_id[i] for i in reply["ready"]],
                [by_id[i] for i in reply["not_ready"]])

    def submit_task(self, fn, args, kwargs, opts) -> list:
        blob, fn_hash = self._fn_blob(fn)
        clean = {k: v for k, v in (opts or {}).items() if v is not None
                 and not (k == "placement_group_bundle_index" and v == -1)}
        reply = self._call("Task", {
            "fn": blob, "fn_hash": fn_hash,
            "args": self._encode_args(args, kwargs),
            "opts": cloudpickle.dumps(clean)})
        return [self._mkref(i) for i in reply["ids"]]

    def create_actor(self, cls, args, kwargs, opts) -> ActorID:
        blob, fn_hash = self._fn_blob(cls)
        clean = {k: v for k, v in (opts or {}).items() if v is not None
                 and not (k == "placement_group_bundle_index" and v == -1)
                 and not (k == "get_if_exists" and v is False)}
        reply = self._call("CreateActor", {
            "fn": blob, "fn_hash": fn_hash,
            "args": self._encode_args(args, kwargs),
            "opts": cloudpickle.dumps(clean)}, timeout=120)
        return ActorID(reply["actor_id"])

    def submit_actor_task(self, actor_id: ActorID, method: str, args,
                          kwargs, opts) -> list:
        reply = self._call("ActorCall", {
            "actor_id": actor_id.binary(), "method": method,
            "num_returns": (opts or {}).get("num_returns", 1),
            "args": self._encode_args(args, kwargs)})
        return [self._mkref(i) for i in reply["ids"]]

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        self._call("Kill", {"actor_id": actor_id.binary(),
                            "no_restart": no_restart})

    def cancel_task(self, ref: ObjectRef, force=False, recursive=True):
        self._call("Cancel", {"id": ref.id.binary(), "force": force})

    def get_named_actor(self, name: str, namespace: str = "default"):
        reply = self.io.run(self.gcs.call(
            "Gcs", "get_named_actor", {"name": name,
                                       "namespace": namespace}))
        return reply.get("info")

    def _job_int(self):
        return None  # client sessions span jobs; log echo shows all lines

    def _worker_call(self, method: str, *args, **kwargs):
        reply = self._call("WorkerCall", {
            "method": method,
            "args": cloudpickle.dumps((args, kwargs))}, timeout=120)
        return cloudpickle.loads(reply["result"])

    # Placement groups proxy to the server driver (whitelisted there).
    def create_placement_group(self, *a, **kw):
        return self._worker_call("create_placement_group", *a, **kw)

    def wait_placement_group_ready(self, *a, **kw):
        return self._worker_call("wait_placement_group_ready", *a, **kw)

    def get_placement_group_info(self, *a, **kw):
        return self._worker_call("get_placement_group_info", *a, **kw)

    def remove_placement_group(self, *a, **kw):
        return self._worker_call("remove_placement_group", *a, **kw)

    def list_placement_groups(self, *a, **kw):
        return self._worker_call("list_placement_groups", *a, **kw)

    def shutdown(self):
        self._stop_keepalive.set()
        try:
            self._call("Disconnect", {}, timeout=5)
        except Exception:
            pass
        try:
            self.io.run(self._rpc.close())
        except Exception:
            pass
        self.io.stop()
