"""Deep ref/handle translation for client mode.

Reference parity: python/ray/util/client/ARCHITECTURE.md — the client's
serializer walks the WHOLE object graph, converting ObjectRefs and actor
handles wherever they appear (inside user dataclasses, closures, numpy
object arrays...), not just in top-level containers.  Implemented with
pickle's persistent-id machinery: a custom CloudPickler emits a tagged
persistent id for every ref/handle it meets at any depth; the peer's
Unpickler rebuilds the native object via a callback.  This replaces the
r3 limitation where only plain list/dict/tuple nesting translated.
"""

from __future__ import annotations

import io
import pickle
from typing import Callable, Optional, Tuple

import cloudpickle

REF = "__ray_tpu_ref__"
ACTOR = "__ray_tpu_actor__"


def dumps(obj, on_ref: Optional[Callable] = None,
          on_actor: Optional[Callable] = None) -> bytes:
    """Serialize, converting refs/handles at any nesting depth into
    tagged persistent ids: (REF, id_bytes, owner) / (ACTOR, id_bytes).
    `on_ref(ref)` / `on_actor(handle)` observe each converted object —
    the server pins them into the session so the peer's ids stay live."""
    from ray_tpu.api import ActorHandle
    from ray_tpu.object_ref import ObjectRef

    buf = io.BytesIO()

    class _P(cloudpickle.CloudPickler):
        def persistent_id(self, o):
            if isinstance(o, ObjectRef):
                if on_ref is not None:
                    on_ref(o)
                return (REF, o.id.binary(), o.owner_address or "")
            if isinstance(o, ActorHandle):
                if on_actor is not None:
                    on_actor(o)
                return (ACTOR, o._actor_id.binary())
            return None

    _P(buf, protocol=5).dump(obj)
    return buf.getvalue()


def loads(blob: bytes, *,
          make_ref: Callable[[bytes, str], object],
          make_actor: Callable[[bytes], object]):
    """Deserialize, rebuilding refs/handles through the callbacks."""

    class _U(pickle.Unpickler):
        def persistent_load(self, pid: Tuple):
            tag = pid[0]
            if tag == REF:
                return make_ref(pid[1], pid[2])
            if tag == ACTOR:
                return make_actor(pid[1])
            raise pickle.UnpicklingError(f"unknown persistent id {tag!r}")

    return _U(io.BytesIO(blob)).load()
