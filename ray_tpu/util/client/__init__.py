"""ray_tpu.util.client — thin-client mode (`ray_tpu://host:port`).

Reference parity: python/ray/util/client/ (ARCHITECTURE.md,
ray_client.proto): a lightweight client proxies every API call over RPC
to a client server colocated with the cluster, which executes them
through an embedded driver.  Nothing cluster-side (shm store, daemons)
is required on the client machine.
"""

from ray_tpu.util.client.server import ClientServer  # noqa: F401
from ray_tpu.util.client.worker import ClientWorker  # noqa: F401

__all__ = ["ClientServer", "ClientWorker"]
