"""Client server: the cluster-side half of thin-client mode.

Reference parity: python/ray/util/client/server/ — a server-side driver
executes proxied put/get/task/actor calls; per-client sessions pin the
ObjectRefs and actor handles they created and release them on
disconnect.
"""

from __future__ import annotations

import logging
from typing import Any, Dict

import cloudpickle

logger = logging.getLogger("ray_tpu.client_server")


class _Session:
    def __init__(self):
        import time
        self.refs: Dict[bytes, Any] = {}       # object id -> ObjectRef pin
        self.actors: Dict[bytes, Any] = {}     # actor id -> ActorHandle
        self.fns: Dict[bytes, Any] = {}        # fn hash -> deserialized
        self.last_seen = time.time()


class ClientServer:
    """Hosts the RayClient RPC service over an embedded driver.

    Handlers execute the (BLOCKING) public API in a thread executor —
    running them inline would deadlock/stall whichever event loop hosts
    this server."""

    def __init__(self, host: str = "127.0.0.1"):
        from concurrent.futures import ThreadPoolExecutor

        from ray_tpu._private.rpc import RpcServer
        self.server = RpcServer(host)
        self.sessions: Dict[str, _Session] = {}
        import os
        self._pool = ThreadPoolExecutor(max_workers=64,
                                        thread_name_prefix="client-srv")
        # Crashed clients never send Disconnect; stale sessions (and the
        # object pins they hold) expire after this idle window.
        self._session_ttl = float(
            os.environ.get("RAY_TPU_CLIENT_SESSION_TTL_S", "600"))
        for name in ("Init", "Put", "Get", "Wait", "Task", "CreateActor",
                     "ActorCall", "Kill", "Cancel", "GcsCall", "Release",
                     "Disconnect", "WorkerCall"):
            self.server.register(
                "RayClient", name,
                self._wrap(getattr(self, f"_do_{name.lower()}")))

    def _wrap(self, fn):
        import asyncio

        async def handler(req):
            loop = asyncio.get_running_loop()
            return await loop.run_in_executor(self._pool, fn, req)
        return handler

    async def start(self, port: int = 0) -> int:
        return await self.server.start(port)

    def _session(self, req) -> _Session:
        import time
        now = time.time()
        for stale_id, sess in list(self.sessions.items()):
            if now - sess.last_seen > self._session_ttl:
                logger.info("expiring idle client session %s", stale_id[:8])
                self.sessions.pop(stale_id, None)
        sid = req.get("session", "default")
        if sid not in self.sessions:
            self.sessions[sid] = _Session()
        sess = self.sessions[sid]
        sess.last_seen = now
        return sess

    def _decode_args(self, session: _Session, blob: bytes):
        """Client args arrive with refs/handles as pickle persistent ids
        at ANY depth (codec.py); rebuild the server-side objects."""
        from ray_tpu.util.client import codec

        def make_actor(actor_id: bytes):
            handle = session.actors.get(actor_id)
            if handle is None:
                handle = session.actors[actor_id] = \
                    self._foreign_handle(actor_id)
            return handle

        return codec.loads(
            blob,
            make_ref=lambda i, o: self._ref_fallback(session, i, o),
            make_actor=make_actor)

    def _encode_values(self, session: _Session, values) -> bytes:
        """Results can CONTAIN refs/handles; they convert to persistent
        ids AND pin into the session so the ids the client holds stay
        resolvable until released."""
        from ray_tpu.util.client import codec

        def on_ref(ref):
            session.refs.setdefault(ref.id.binary(), ref)

        def on_actor(handle):
            session.actors.setdefault(handle._actor_id.binary(), handle)

        return codec.dumps(values, on_ref=on_ref, on_actor=on_actor)

    def _track(self, session: _Session, refs) -> list:
        out = []
        for ref in refs if isinstance(refs, list) else [refs]:
            session.refs[ref.id.binary()] = ref
            out.append(ref.id.binary())
        return out

    # ---------------- RPC handlers ----------------

    def _do_init(self, req):
        self._session(req)
        return {"ok": True}

    def _do_put(self, req):
        import ray_tpu
        session = self._session(req)
        value = cloudpickle.loads(req["value"])
        ref = ray_tpu.put(value)
        return {"id": self._track(session, ref)[0]}

    def _do_get(self, req):
        import ray_tpu
        session = self._session(req)
        owners = req.get("owners") or [""] * len(req["ids"])
        refs = [self._ref_fallback(session, i, o)
                for i, o in zip(req["ids"], owners)]
        try:
            values = ray_tpu.get(refs, timeout=req.get("timeout"))
            return {"values": self._encode_values(session, values)}
        except BaseException as e:  # noqa: BLE001 - ship to client
            return {"error": cloudpickle.dumps(e)}

    def _do_wait(self, req):
        import ray_tpu
        session = self._session(req)
        owners = req.get("owners") or [""] * len(req["ids"])
        refs = [self._ref_fallback(session, i, o)
                for i, o in zip(req["ids"], owners)]
        ready, rest = ray_tpu.wait(refs, num_returns=req["num_returns"],
                                   timeout=req.get("timeout"),
                                   fetch_local=req.get("fetch_local", True))
        return {"ready": [r.id.binary() for r in ready],
                "not_ready": [r.id.binary() for r in rest]}

    def _do_task(self, req):
        import ray_tpu
        session = self._session(req)
        fn_hash = req["fn_hash"]
        if fn_hash not in session.fns:
            session.fns[fn_hash] = cloudpickle.loads(req["fn"])
        fn = session.fns[fn_hash]
        args, kwargs = self._decode_args(session, req["args"])
        opts = cloudpickle.loads(req["opts"])
        remote_fn = ray_tpu.remote(**opts)(fn) if opts else \
            ray_tpu.remote(fn)
        refs = remote_fn.remote(*args, **kwargs)
        single = not isinstance(refs, list)
        ids = self._track(session, refs)
        return {"ids": ids, "single": single}

    def _do_createactor(self, req):
        import ray_tpu
        session = self._session(req)
        fn_hash = req["fn_hash"]
        if fn_hash not in session.fns:
            session.fns[fn_hash] = cloudpickle.loads(req["fn"])
        cls = session.fns[fn_hash]
        args, kwargs = self._decode_args(session, req["args"])
        opts = cloudpickle.loads(req["opts"])
        handle = (ray_tpu.remote(**opts)(cls) if opts
                  else ray_tpu.remote(cls)).remote(*args, **kwargs)
        session.actors[handle._actor_id.binary()] = handle
        return {"actor_id": handle._actor_id.binary(),
                "class_name": handle._class_name}

    @staticmethod
    def _ref_fallback(session: _Session, id_binary: bytes,
                      owner: str = ""):
        """Refs the session didn't create (returned as VALUES from tasks,
        then echoed back by the client) rebuild from the true owner
        address the client received."""
        from ray_tpu.object_ref import ObjectRef
        from ray_tpu._private.ids import ObjectID
        ref = session.refs.get(id_binary)
        if ref is None:
            ref = session.refs[id_binary] = ObjectRef(
                ObjectID(id_binary), owner, _register=False)
        return ref

    @staticmethod
    def _foreign_handle(actor_id: bytes):
        """Handle for an actor this session didn't create (named/detached
        actors fetched via get_actor on the client)."""
        from ray_tpu.api import ActorHandle
        from ray_tpu._private.ids import ActorID
        return ActorHandle(ActorID(actor_id), "remote", None)

    def _do_actorcall(self, req):
        session = self._session(req)
        handle = session.actors.get(req["actor_id"])
        if handle is None:
            handle = session.actors[req["actor_id"]] = \
                self._foreign_handle(req["actor_id"])
        args, kwargs = self._decode_args(session, req["args"])
        method = getattr(handle, req["method"])
        num_returns = req.get("num_returns", 1)
        if num_returns != 1:
            method = method.options(num_returns=num_returns)
        refs = method.remote(*args, **kwargs)
        single = not isinstance(refs, list)
        ids = self._track(session, refs)
        return {"ids": ids, "single": single}

    def _do_kill(self, req):
        import ray_tpu
        session = self._session(req)
        handle = session.actors.get(req["actor_id"]) \
            or self._foreign_handle(req["actor_id"])
        ray_tpu.kill(handle, no_restart=req.get("no_restart", True))
        return {"ok": True}

    def _do_cancel(self, req):
        import ray_tpu
        session = self._session(req)
        ref = session.refs.get(req["id"])
        if ref is not None:
            ray_tpu.cancel(ref, force=req.get("force", False))
        return {"ok": True}

    def _do_gcscall(self, req):
        from ray_tpu import api
        w = api._worker
        timeout = req.get("timeout") or 60
        reply = w.io.run(w.gcs.call(req["service"], req["method"],
                                    cloudpickle.loads(req["request"])),
                         timeout=timeout)
        return {"reply": cloudpickle.dumps(reply)}

    _WORKER_PASSTHROUGH = {
        "create_placement_group", "wait_placement_group_ready",
        "get_placement_group_info", "remove_placement_group",
        "list_placement_groups",
    }

    def _do_workercall(self, req):
        """Whitelisted driver-worker method passthrough (placement groups
        etc.)."""
        from ray_tpu import api
        method = req["method"]
        if method not in self._WORKER_PASSTHROUGH:
            raise ValueError(f"method {method!r} not proxied")
        args, kwargs = cloudpickle.loads(req["args"])
        result = getattr(api._worker, method)(*args, **kwargs)
        return {"result": cloudpickle.dumps(result)}

    def _do_release(self, req):
        session = self._session(req)
        for i in req.get("ids", []):
            session.refs.pop(i, None)
        return {"ok": True}

    def _do_disconnect(self, req):
        self.sessions.pop(req.get("session", "default"), None)
        return {"ok": True}


def serve_forever(gcs_address: str, host: str = "0.0.0.0",
                  port: int = 10001) -> None:
    """Run a client server attached to `gcs_address` until interrupted.
    The single entry point used by both the CLI and `python -m`."""
    import asyncio

    import ray_tpu
    ray_tpu.init(address=gcs_address)

    async def run():
        server = ClientServer(host)
        bound = await server.start(port)
        print(f"client server listening on {host}:{bound} — connect "
              f"with ray_tpu.init('ray_tpu://<host>:{bound}')", flush=True)
        await asyncio.Event().wait()

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass


def main():
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--address", required=True, help="GCS address")
    parser.add_argument("--port", type=int, default=10001)
    parser.add_argument("--host", default="0.0.0.0")
    args = parser.parse_args()
    logging.basicConfig(level="INFO")
    serve_forever(args.address, args.host, args.port)


if __name__ == "__main__":
    main()
