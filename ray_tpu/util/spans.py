"""Durational spans layered on the flight recorder.

PR 10 gave every plane an *instant* event ring (`events.record`); this
module turns pairs of those events into **spans** — named intervals with
parent links — without changing the ring's cost model: a span is exactly
two ring slots (a ``ph="B"`` begin and a ``ph="E"`` end carrying the
duration), appended through the same signal-safe fast path.  With
``RAY_TPU_EVENTS=0`` the whole module collapses to one global read per
``begin()`` and ``end()`` returns immediately on the ``None`` token.

Wire format (what ``state.spans()`` reconstructs from):

  B:  (ts, plane, kind, (trace_id, sid), {"ph": "B", "parent": psid, ...})
  E:  (ts, plane, kind, (trace_id, sid), {"ph": "E", "dur": seconds, ...})

``sid`` is cluster-unique (a per-process random prefix plus a local
counter), so begin/end pair by span id alone even after crash dumps from
several processes are merged into one stream.  ``trace_id`` may be None:
such spans never join a trace tree but still feed
``state.latency_breakdown()`` aggregates.

Pairing is structural, not by name: ``end()`` takes the token ``begin()``
returned, so a begin can never be closed with a mismatched kind, and a
token can cross threads or asyncio callbacks (scheduler-queue and
dispatch spans ride on the pending-task object between the submitting
thread and the io loop).

Usage:
    tok = spans.begin("sched", "lease_wait", key=key)   # may return None
    ...
    spans.end(tok, granted=True)

    with spans.span("ingest", "h2d"):        # context form; nested spans
        device_put(batch)                    # become children via tracing
"""

from __future__ import annotations

import contextlib
import itertools
import secrets
import time
from typing import Any, Optional, Tuple

from ray_tpu.util import events, tracing

# Cluster-unique span ids: 3 random bytes of per-process prefix + local
# counter.  Distinct from tracing's token_hex(4) task span ids on
# purpose — a prefix collision between two processes would need ~2^12
# concurrent processes (birthday bound on 2^24).
_PREFIX = secrets.token_hex(3)
_SEQ = itertools.count()


class Span:
    """Token returned by :func:`begin`; pass it to :func:`end`."""

    __slots__ = ("plane", "kind", "trace_id", "sid", "t0")

    def __init__(self, plane: str, kind: str, trace_id: Optional[str],
                 sid: str, t0: float):
        self.plane = plane
        self.kind = kind
        self.trace_id = trace_id
        self.sid = sid
        self.t0 = t0

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"Span({self.plane}:{self.kind} sid={self.sid})"


def _new_sid() -> str:
    return f"{_PREFIX}{next(_SEQ):x}"


def begin(plane: str, kind: str,
          ctx: Optional[Tuple[Optional[str], Optional[str]]] = None,
          sid: Optional[str] = None, parent: Optional[str] = None,
          **payload: Any) -> Optional[Span]:
    """Open a span.  Returns None when the recorder is off (the disabled
    fast path is one global read, same as ``events.record``).

    ``ctx`` is an explicit (trace_id, parent_span_id) — e.g. a task
    spec's carried ``trace_ctx`` — and defaults to the calling context's
    active trace.  ``sid`` pins the span id (used when another layer,
    like ``tracing.enter_task``, already minted the id that children
    will reference as their parent)."""
    r = events._recorder
    if r is None:
        if events._initialized:
            return None
        r = events._init()
        if r is None:
            return None
    if ctx is None:
        ctx = tracing.current_context()
    trace_id = ctx[0] if ctx else None
    if parent is None and ctx is not None:
        parent = ctx[1]
    s = sid or _new_sid()
    p: dict = {"ph": "B"}
    if parent is not None:
        p["parent"] = parent
    if payload:
        p.update(payload)
    r.append(plane, kind, p, (trace_id, s))
    return Span(plane, kind, trace_id, s, time.time())


def end(tok: Optional[Span], **payload: Any) -> None:
    """Close a span.  No-op on a None token (recorder was off at begin)
    or when the recorder has been reset since."""
    if tok is None:
        return
    r = events._recorder
    if r is None:
        return
    p: dict = {"ph": "E", "dur": time.time() - tok.t0}
    if payload:
        p.update(payload)
    r.append(tok.plane, tok.kind, p, (tok.trace_id, tok.sid))


@contextlib.contextmanager
def span(plane: str, kind: str,
         ctx: Optional[Tuple[Optional[str], Optional[str]]] = None,
         **payload: Any):
    """Context-manager form.  While open, the span becomes the active
    trace context (when it belongs to a trace), so nested spans and any
    tasks submitted inside attach to it as children."""
    tok = begin(plane, kind, ctx=ctx, **payload)
    cv = None
    if tok is not None and tok.trace_id is not None:
        cv = tracing._ctx.set((tok.trace_id, tok.sid))
    try:
        yield tok
    finally:
        if cv is not None:
            tracing._ctx.reset(cv)
        end(tok)
