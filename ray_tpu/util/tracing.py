"""Distributed trace-context propagation across task/actor boundaries.

Reference parity: python/ray/util/tracing/tracing_helper.py:87 — the
reference injects the OpenTelemetry context into a reserved field of every
task/actor call at SUBMIT time and extracts it at EXECUTE time, so spans
from a driver and all its transitive tasks share one trace.  This build
carries the same (trace_id, span_id) pair in `TaskSpec.trace_ctx`, keeps
it in a contextvar inside executing tasks (nested submits propagate
automatically), and stamps every task timeline event with
trace_id/span_id/parent_id — the timeline IS the span store, so
`state.timeline()` / the Chrome trace groups a whole trace without an
external collector.

The switch is the `trace()` scope itself: outside any active trace the
context is None, submission attaches nothing, and execution skips span
bookkeeping — a contextvar read per submit is the entire idle cost.
A worker that receives a carried context always forwards it (its own
processes never need configuring).

Usage:
    from ray_tpu.util import tracing
    with tracing.trace("my-request"):
        ray_tpu.get(f.remote())   # f's span joins "my-request"'s trace
"""

from __future__ import annotations

import contextlib
import contextvars
import secrets
from typing import Optional, Tuple

# (trace_id_hex, span_id_hex) of the CURRENT span in this context.
_ctx: contextvars.ContextVar[Optional[Tuple[str, str]]] = \
    contextvars.ContextVar("ray_tpu_trace_ctx", default=None)


def current_context() -> Optional[Tuple[str, str]]:
    """(trace_id, span_id) to inject into an outgoing task spec, or None
    when no trace is active in this context."""
    return _ctx.get()


@contextlib.contextmanager
def trace(name: str = "trace"):
    """Open (or continue) a trace in this context; tasks submitted inside
    join it as child spans.  The scope itself is recorded as the trace's
    root span (a B/E pair on the flight recorder), so `state.spans()`
    reconstructs a rooted tree with the user's name on top."""
    parent = _ctx.get()
    if parent is None:
        trace_id = secrets.token_hex(8)
    else:
        trace_id = parent[0]
    sid = secrets.token_hex(4)
    token = _ctx.set((trace_id, sid))
    from ray_tpu.util import spans  # late: spans imports this module
    tok = spans.begin("proc", "trace",
                      ctx=(trace_id, parent[1] if parent else None),
                      sid=sid, name=name)
    try:
        yield trace_id
    finally:
        _ctx.reset(token)
        spans.end(tok)


def enter_task(spec) -> Optional[Tuple[str, str, str]]:
    """Called by the worker when a task starts executing.  Installs the
    propagated context (so the task's own submissions become children) and
    returns (trace_id, span_id, parent_span_id) for the timeline event —
    or None when the spec carries no context."""
    carried = getattr(spec, "trace_ctx", None)
    if carried is None:
        return None
    trace_id, parent_span = carried
    span_id = secrets.token_hex(4)
    _ctx.set((trace_id, span_id))
    return trace_id, span_id, parent_span


def exit_task() -> None:
    _ctx.set(None)
