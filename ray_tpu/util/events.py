"""Flight recorder: always-on per-process ring buffer of runtime events.

Reference parity: Ray's EventManager / export-event path
(src/ray/util/event.h, python/ray/_private/event/event_logger.py) records
structured per-component events to files; the debugging story here follows
an aircraft flight recorder instead — every plane (scheduler, object
store, engine, serve, checkpoint, ingest, train) appends decision events
to a fixed-size in-memory ring, and the ring is

  * dumped atomically to ``<logs>/flightrec-<pid>-<incarnation>.jsonl``
    on crash, SIGTERM, chaos kill, and fatal error (the black box),
  * scrapeable live over the hostd/CoreWorker ``CollectEvents`` RPC
    (``state.events()`` aggregates cluster-wide, normalizes clock skew,
    and joins by trace id),
  * mergeable into the Chrome task timeline (``cli timeline --events``).

The append fast path is lock-free-ish: slot allocation is one
``next(itertools.count())`` (a single C call, atomic under the GIL and
safe from signal handlers — no bytecode boundary splits it) plus one
list-item store.  Overflow overwrites the oldest slot; ``snapshot()``
reorders by the monotonic sequence number each event carries.  With
``RAY_TPU_EVENTS=0`` the whole module collapses to one global read per
``record()`` call.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.util import tracing

# Planes (the `plane` field of every event).  Free-form strings are
# accepted; these constants document the instrumented set.
PLANES = ("sched", "object", "engine", "serve", "ckpt", "ingest", "train",
          "proc", "gcs", "pp", "link", "kv", "rl")


class FlightRecorder:
    """Fixed-capacity ring of ``(ts, plane, kind, trace, payload, seq)``
    tuples.  ``append`` is re-entrant (signal handlers included): the
    sequence counter is a C-level ``itertools.count`` and the slot store
    is a single list assignment, so interleaved appenders race only for
    distinct slots."""

    def __init__(self, capacity: int = 4096):
        self.capacity = max(16, int(capacity))
        self._buf: List[Optional[tuple]] = [None] * self.capacity
        self._seq = itertools.count()

    def append(self, plane: str, kind: str,
               payload: Optional[Dict[str, Any]] = None,
               trace: Optional[Tuple[str, str]] = None) -> None:
        if trace is None:
            trace = tracing.current_context()
        i = next(self._seq)
        self._buf[i % self.capacity] = (
            time.time(), plane, kind, trace, payload, i)

    # -- read side (slow path: snapshots copy the ring) -------------------

    def snapshot(self, since: float = 0.0, plane: Optional[str] = None,
                 kind: Optional[str] = None) -> List[Dict[str, Any]]:
        """Events currently in the ring, oldest first, as dicts."""
        raw = [e for e in list(self._buf) if e is not None]
        raw.sort(key=lambda e: e[5])
        out = []
        for ts, pl, kd, trace, payload, seq in raw:
            if ts < since:
                continue
            if plane is not None and pl != plane:
                continue
            if kind is not None and kd != kind:
                continue
            out.append({
                "ts": ts, "plane": pl, "kind": kd,
                "trace_id": trace[0] if trace else None,
                "span_id": trace[1] if trace else None,
                "payload": payload, "seq": seq,
            })
        return out

    def tail(self, n: int = 50) -> List[Dict[str, Any]]:
        return self.snapshot()[-n:]

    def __len__(self) -> int:
        return sum(1 for e in self._buf if e is not None)


# ---------------------------------------------------------------------------
# Process-global recorder
# ---------------------------------------------------------------------------

_recorder: Optional[FlightRecorder] = None
_initialized = False
_init_lock = threading.Lock()


def _init() -> Optional[FlightRecorder]:
    global _recorder, _initialized
    with _init_lock:
        if _initialized:
            return _recorder
        from ray_tpu._private.config import GLOBAL_CONFIG
        if GLOBAL_CONFIG.events:
            _recorder = FlightRecorder(GLOBAL_CONFIG.events_ring_size)
        else:
            _recorder = None
        _initialized = True
        return _recorder


def record(plane: str, kind: str,
           trace: Optional[Tuple[str, str]] = None, **payload) -> None:
    """Append one event.  The disabled fast path is a global read; the
    enabled fast path is a dict build + ring append (< 5 µs, see
    `events_append` in MICROBENCH.json)."""
    r = _recorder
    if r is None:
        if _initialized:
            return
        r = _init()
        if r is None:
            return
    r.append(plane, kind, payload or None, trace)


def enabled() -> bool:
    if not _initialized:
        _init()
    return _recorder is not None


def get_recorder() -> Optional[FlightRecorder]:
    if not _initialized:
        _init()
    return _recorder


def snapshot(since: float = 0.0, plane: Optional[str] = None,
             kind: Optional[str] = None) -> List[Dict[str, Any]]:
    r = get_recorder()
    return r.snapshot(since, plane, kind) if r is not None else []


def tail(n: int = 50) -> List[Dict[str, Any]]:
    r = get_recorder()
    return r.tail(n) if r is not None else []


def reset() -> None:
    """Drop the process recorder (tests flip config flags between
    scenarios; the next record()/get_recorder() re-reads config)."""
    global _recorder, _initialized
    with _init_lock:
        _recorder = None
        _initialized = False


# ---------------------------------------------------------------------------
# Crash dumps (the black box)
# ---------------------------------------------------------------------------


def _dump_dir() -> str:
    # The env var wins over the (cached) config flag: hostd points itself
    # and every child at <session>/logs after the config may already have
    # been read in this process.
    d = os.environ.get("RAY_TPU_FLIGHTREC_DIR", "")
    if not d:
        try:
            from ray_tpu._private.config import GLOBAL_CONFIG
            d = GLOBAL_CONFIG.flightrec_dir
        except Exception:
            d = ""
    return d or os.path.join("/tmp", "ray_tpu", "flightrec")


def _incarnation() -> str:
    return os.environ.get("RAY_TPU_CHAOS_PROC_SALT") or "0"


def dump(path: str, reason: str = "") -> Optional[str]:
    """Write the ring to `path` as jsonl, atomically (tmp + fsync +
    rename): a reader either sees the whole dump or no file.  Returns
    the path, or None when the recorder is off/empty."""
    events = snapshot()
    if not events:
        return None
    header = {"_flightrec": 1, "pid": os.getpid(),
              "incarnation": _incarnation(), "reason": reason,
              "wall_time": time.time()}
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(tmp, "w") as f:
            f.write(json.dumps(header) + "\n")
            for e in events:
                f.write(json.dumps(e, default=repr) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return path
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


def dump_crash(reason: str) -> Optional[str]:
    """The black-box write: called from kill paths (chaos kills, SIGTERM,
    fatal errors, daemon teardown) right before the process dies.  Never
    raises — a failed forensics write must not mask the real exit."""
    try:
        record("proc", "crash_dump", reason=reason)
        path = os.path.join(
            _dump_dir(), f"flightrec-{os.getpid()}-{_incarnation()}.jsonl")
        return dump(path, reason)
    except Exception:
        return None


def read_dumps(directory: str) -> List[Dict[str, Any]]:
    """Parse every flightrec-*.jsonl in `directory`; each event gains
    ``pid``, ``source="crash"``, and the dump's ``reason``.  Corrupt or
    half-written files are skipped (dumps are atomic, but the directory
    may hold unrelated debris)."""
    out: List[Dict[str, Any]] = []
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return out
    for name in names:
        if not (name.startswith("flightrec-") and name.endswith(".jsonl")):
            continue
        try:
            with open(os.path.join(directory, name)) as f:
                lines = f.read().splitlines()
            header = json.loads(lines[0]) if lines else {}
            if header.get("_flightrec") != 1:
                continue
            for line in lines[1:]:
                e = json.loads(line)
                e["pid"] = header.get("pid")
                e["source"] = "crash"
                e["reason"] = header.get("reason")
                out.append(e)
        except Exception:
            continue
    return out
