"""Pub/sub over the GCS: named channels with long-poll subscribers.

Reference parity: src/ray/pubsub/ (Publisher publisher.h:302 long-poll
channels, SubscriberChannel subscriber.h:70) and the Python face
python/ray/_private/gcs_pubsub.py (GcsPublisher/GcsSubscriber).  The
worker-log stream and cluster-change events are specializations of this
mechanism; user code can publish/subscribe arbitrary channels.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional


def _worker():
    from ray_tpu import api
    if api._worker is None:
        raise RuntimeError("ray_tpu.init() has not been called")
    return api._worker


class Publisher:
    """Publish messages to a named channel."""

    def __init__(self, channel: str):
        self.channel = channel

    def publish(self, *messages: Any) -> int:
        w = _worker()
        reply = w.io.run(w.gcs.call(
            "Gcs", "pub_publish",
            {"channel": self.channel, "messages": list(messages)}))
        return reply["seq"]


class Subscriber:
    """Long-poll subscriber; `poll` blocks until messages or timeout."""

    def __init__(self, channel: str, *, from_seq: int = 0):
        self.channel = channel
        self._after = from_seq

    def poll(self, timeout_s: float = 10.0) -> List[Any]:
        w = _worker()
        reply = w.io.run(w.gcs.call(
            "Gcs", "pub_poll",
            {"channel": self.channel, "after_seq": self._after,
             "timeout_s": timeout_s}, timeout=timeout_s + 30),
            timeout=timeout_s + 45)
        msgs = reply.get("messages", [])
        if msgs:
            self._after = msgs[-1][0]
        else:
            self._after = max(self._after, reply.get("seq", self._after))
        return [m for _seq, m in msgs]

    def listen(self, callback: Callable[[Any], None],
               stop_event: Optional[threading.Event] = None
               ) -> threading.Thread:
        """Background delivery thread calling `callback` per message."""
        stop = stop_event or threading.Event()

        def loop():
            import logging
            log = logging.getLogger("ray_tpu.pubsub")
            while not stop.is_set():
                try:
                    msgs = self.poll(timeout_s=2.0)
                except Exception:
                    if stop.wait(1.0):
                        return
                    continue
                for msg in msgs:
                    try:
                        callback(msg)
                    except Exception:  # one bad message must not drop
                        log.exception("pubsub callback failed "
                                      "(channel %s)", self.channel)

        t = threading.Thread(target=loop, daemon=True,
                             name=f"pubsub-{self.channel}")
        t.stop_event = stop
        t.start()
        return t
