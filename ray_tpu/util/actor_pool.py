"""ActorPool: load-balance tasks over a fixed set of actors.

Reference parity: python/ray/util/actor_pool.py (submit, get_next,
get_next_unordered, map, map_unordered).
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, List

import ray_tpu


class ActorPool:
    def __init__(self, actors: List[Any]):
        self._idle: List[Any] = list(actors)
        self._future_to_actor: dict = {}
        self._index_to_future: dict = {}
        self._next_task_index = 0
        self._next_return_index = 0

    def submit(self, fn: Callable[[Any, Any], Any], value: Any) -> None:
        """fn(actor, value) -> ObjectRef; blocks-free, requires an idle
        actor (pop order round-robins through completions)."""
        if not self._idle:
            raise ValueError("no idle actors; call get_next() first")
        actor = self._idle.pop(0)
        future = fn(actor, value)
        self._future_to_actor[future] = (self._next_task_index, actor)
        self._index_to_future[self._next_task_index] = future
        self._next_task_index += 1

    def has_next(self) -> bool:
        return self._next_return_index < self._next_task_index

    def has_free(self) -> bool:
        return bool(self._idle)

    def get_next(self, timeout: float | None = None) -> Any:
        """Next result in SUBMISSION order.  A timeout leaves the pending
        task intact and retrievable (reference: wait-before-pop)."""
        if not self.has_next():
            raise StopIteration("no pending results")
        future = self._index_to_future[self._next_return_index]
        if timeout is not None:
            ready, _ = ray_tpu.wait([future], num_returns=1,
                                    timeout=timeout)
            if not ready:
                raise TimeoutError("get_next timed out; result still "
                                   "pending")
        self._index_to_future.pop(self._next_return_index)
        self._next_return_index += 1
        # Re-idle BEFORE get: a raising task must not leak the actor.
        _, actor = self._future_to_actor.pop(future)
        self._idle.append(actor)
        return ray_tpu.get(future)

    def get_next_unordered(self, timeout: float | None = None) -> Any:
        """Next result in COMPLETION order."""
        if not self._future_to_actor:
            raise StopIteration("no pending results")
        ready, _ = ray_tpu.wait(list(self._future_to_actor),
                                num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("get_next_unordered timed out")
        future = ready[0]
        idx, actor = self._future_to_actor.pop(future)
        self._index_to_future.pop(idx, None)
        if idx == self._next_return_index:
            while self._next_return_index not in self._index_to_future \
                    and self._next_return_index < self._next_task_index:
                self._next_return_index += 1
        self._idle.append(actor)  # before get: errors must not leak actors
        return ray_tpu.get(future)

    def map(self, fn: Callable, values) -> Iterator[Any]:
        values = list(values)
        sent = 0
        while sent < len(values) and self.has_free():
            self.submit(fn, values[sent])
            sent += 1
        while self.has_next():
            yield self.get_next()
            if sent < len(values):
                self.submit(fn, values[sent])
                sent += 1

    def map_unordered(self, fn: Callable, values) -> Iterator[Any]:
        values = list(values)
        sent = 0
        while sent < len(values) and self.has_free():
            self.submit(fn, values[sent])
            sent += 1
        while self._future_to_actor:
            yield self.get_next_unordered()
            if sent < len(values):
                self.submit(fn, values[sent])
                sent += 1

    def push(self, actor) -> None:
        self._idle.append(actor)

    def pop_idle(self):
        return self._idle.pop(0) if self._idle else None
