"""joblib parallel backend over the cluster.

Reference parity: python/ray/util/joblib/ — `register_ray()` makes
`joblib.parallel_backend("ray")` run scikit-learn style workloads
(GridSearchCV, cross_val_score, any joblib.Parallel) on cluster actors
instead of local processes.

Usage:
    import joblib
    from ray_tpu.util.joblib_backend import register_ray
    register_ray()
    with joblib.parallel_backend("ray"):
        joblib.Parallel()(joblib.delayed(f)(x) for x in data)
"""

from __future__ import annotations

from typing import Any, List


def register_ray() -> None:
    from joblib.parallel import register_parallel_backend
    register_parallel_backend("ray", _make_backend())


def _make_backend():
    """Subclass joblib's backend base so every protocol attribute
    (nesting levels, batching hooks) comes from joblib itself; this
    backend only redirects the pool to cluster tasks (reference:
    util/joblib/ray_backend.py takes the same pool-redirect shape)."""
    from joblib._parallel_backends import (
        ParallelBackendBase,
        PoolManagerMixin,
    )

    class _RayTpuBackend(PoolManagerMixin, ParallelBackendBase):
        supports_timeout = True

        def configure(self, n_jobs: int = 1, parallel=None,
                      **_: Any) -> int:
            import ray_tpu
            from ray_tpu.util.multiprocessing import Pool
            if not ray_tpu.is_initialized():
                ray_tpu.init()
            n_jobs = self.effective_n_jobs(n_jobs)
            self._pool = Pool(processes=n_jobs)
            self.parallel = parallel
            return n_jobs

        def effective_n_jobs(self, n_jobs: int) -> int:
            import ray_tpu
            if n_jobs == 1:
                return 1
            total = int(ray_tpu.cluster_resources().get("CPU", 1)) \
                if ray_tpu.is_initialized() else 1
            if n_jobs in (None, -1):
                return max(1, total)
            return max(1, min(n_jobs, total))

    return _RayTpuBackend


def check_serializability(obj: Any, name: str = "object") -> List[str]:
    """Diagnose why `obj` cannot cross the cluster boundary (reference:
    ray.util.check_serialize.inspect_serializability): returns a list of
    problem descriptions, empty when `obj` serializes cleanly."""
    import cloudpickle
    problems: List[str] = []
    try:
        cloudpickle.dumps(obj)
        return problems
    except Exception as root:  # noqa: BLE001
        problems.append(f"{name}: {type(root).__name__}: {root}")
    # Walk one level of attributes/items to localize the failure.
    children: List[tuple] = []
    if isinstance(obj, dict):
        children = [(f"{name}[{k!r}]", v) for k, v in obj.items()]
    elif isinstance(obj, (list, tuple, set)):
        children = [(f"{name}[{i}]", v) for i, v in enumerate(obj)]
    elif hasattr(obj, "__dict__"):
        children = [(f"{name}.{k}", v) for k, v in vars(obj).items()]
    for child_name, child in children:
        try:
            cloudpickle.dumps(child)
        except Exception as e:  # noqa: BLE001
            problems.append(f"{child_name}: {type(e).__name__}: {e}")
    return problems
