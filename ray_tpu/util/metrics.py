"""Metrics: counters/gauges/histograms with tag support + Prometheus text
exposition.

Reference parity: src/ray/stats/metric.h:26 (Count/Gauge/Histogram defs,
metric_defs.h:46-110) and the user API python/ray/util/metrics.py; export
follows the per-node agent -> Prometheus text format path
(_private/metrics_agent.py, prometheus_exporter.py) — here each daemon
serves its registry over a Metrics RPC and the CLI/state API renders the
exposition format.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, Iterable, List, Optional, Tuple

_registry: Dict[str, "Metric"] = {}
_registry_lock = threading.Lock()


class Metric:
    TYPE = "untyped"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Tuple[str, ...] = ()):
        if not name.replace("_", "").isalnum():
            raise ValueError(f"invalid metric name {name!r}")
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._lock = threading.Lock()
        self._values: Dict[Tuple[str, ...], float] = {}
        with _registry_lock:
            existing = _registry.get(name)
            if existing is not None:
                # Re-declaration returns the same underlying series store
                # (common for module reloads); types must agree.
                if existing.TYPE != self.TYPE:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.TYPE}, cannot redeclare as {self.TYPE}")
                self._values = existing._values
                self._lock = existing._lock
            _registry[name] = self

    def _key(self, tags: Optional[Dict[str, str]]) -> Tuple[str, ...]:
        tags = tags or {}
        return tuple(str(tags.get(k, "")) for k in self.tag_keys)

    def _series(self) -> List[Tuple[Tuple[str, ...], float]]:
        with self._lock:
            # Deep-copy mutable (histogram) values: snapshots outlive the
            # lock and merge_snapshot folds into them in place.
            return [(k, {**v, "buckets": list(v["buckets"])}
                     if isinstance(v, dict) and "buckets" in v
                     else dict(v) if isinstance(v, dict) else v)
                    for k, v in self._values.items()]


class Counter(Metric):
    TYPE = "counter"

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        key = self._key(tags)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value


class Gauge(Metric):
    TYPE = "gauge"

    def set(self, value: float,
            tags: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._values[self._key(tags)] = float(value)


# Default latency boundaries (seconds): 1 ms .. 5 min, roughly
# exponential — the reference's metric_defs.h latency buckets.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0)


class Histogram(Metric):
    """Bucketed histogram: each series tracks count/sum/min/max plus
    per-bucket counts over fixed boundaries, so snapshots merge
    bucket-exact across processes and quantiles (p50/p95/p99) export
    without shipping raw samples (reference: stats/metric.h Histogram +
    the Prometheus le= exposition)."""

    TYPE = "histogram"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Tuple[str, ...] = (),
                 buckets: Optional[Iterable[float]] = None):
        self.buckets = tuple(sorted(float(b) for b in
                                    (buckets or DEFAULT_BUCKETS)))
        super().__init__(name, description, tag_keys)

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None) -> None:
        key = self._key(tags)
        with self._lock:
            cur = self._values.get(key)
            if cur is None:
                cur = {"count": 0.0, "sum": 0.0, "min": value, "max": value,
                       "buckets": [0] * (len(self.buckets) + 1)}
                self._values[key] = cur
            cur["count"] += 1
            cur["sum"] += value
            cur["min"] = min(cur["min"], value)
            cur["max"] = max(cur["max"], value)
            b = cur.get("buckets")
            if b is not None and len(b) == len(self.buckets) + 1:
                b[bisect.bisect_left(self.buckets, value)] += 1


def quantiles_from_buckets(boundaries, counts, qs=(0.5, 0.95, 0.99),
                           lo: Optional[float] = None,
                           hi: Optional[float] = None) -> Dict[float, float]:
    """Streaming quantile estimates from bucket counts: find the bucket
    holding rank q*total, interpolate linearly inside it (Prometheus
    histogram_quantile semantics).  `lo`/`hi` (observed min/max) clamp
    the open-ended first/overflow buckets."""
    total = sum(counts)
    out: Dict[float, float] = {}
    if total <= 0:
        return {q: float("nan") for q in qs}
    bounds = list(boundaries)
    for q in qs:
        rank = q * total
        cum = 0.0
        val = hi if hi is not None else (bounds[-1] if bounds else 0.0)
        for i, c in enumerate(counts):
            if c <= 0:
                continue
            if cum + c >= rank:
                lower = bounds[i - 1] if i > 0 else (
                    lo if lo is not None else 0.0)
                upper = bounds[i] if i < len(bounds) else (
                    hi if hi is not None else bounds[-1])
                lower = min(lower, upper)
                frac = (rank - cum) / c
                val = lower + (upper - lower) * frac
                break
            cum += c
        if lo is not None:
            val = max(val, lo)
        if hi is not None:
            val = min(val, hi)
        out[q] = val
    return out


def series_quantiles(metric_snapshot: dict, series: dict,
                     qs=(0.5, 0.95, 0.99)) -> Optional[Dict[float, float]]:
    """Quantiles for one histogram series out of a collect() snapshot
    (or None when it carries no bucket counts)."""
    v = series.get("value")
    bounds = metric_snapshot.get("buckets")
    if not isinstance(v, dict) or not bounds or not v.get("buckets"):
        return None
    return quantiles_from_buckets(bounds, v["buckets"], qs,
                                  lo=v.get("min"), hi=v.get("max"))


class timer:
    """Context manager that adds the elapsed wall seconds to a Counter
    (e.g. the ingest producer/consumer wait accumulators) — the cheap
    idiom for 'how long was this side blocked':

        with metrics.timer(wait_counter):
            item = q.get()
    """

    __slots__ = ("_counter", "_tags", "_t0", "elapsed")

    def __init__(self, counter: Counter, tags: Optional[Dict[str, str]] = None):
        self._counter = counter
        self._tags = tags
        self.elapsed = 0.0

    def __enter__(self) -> "timer":
        import time
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        import time
        self.elapsed = time.perf_counter() - self._t0
        self._counter.inc(self.elapsed, self._tags)


def collect() -> Dict[str, dict]:
    """Snapshot of every metric in this process."""
    with _registry_lock:
        metrics = list(_registry.values())
    out: Dict[str, dict] = {}
    for m in metrics:
        entry = {
            "type": m.TYPE,
            "description": m.description,
            "tag_keys": list(m.tag_keys),
            "series": [
                {"tags": dict(zip(m.tag_keys, key)), "value": value}
                for key, value in m._series()],
        }
        if m.TYPE == "histogram":
            entry["buckets"] = list(getattr(m, "buckets", ()))
        out[m.name] = entry
    return out


def read(name: str, tags: Optional[Dict[str, str]] = None):
    """Current value of one series of an in-process metric, or None if
    the metric (or series) does not exist.  Tests and benches use this
    to assert on counters (e.g. serve shed/failover counts) without
    round-tripping through the exposition format."""
    with _registry_lock:
        m = _registry.get(name)
    if m is None:
        return None
    with m._lock:
        return m._values.get(m._key(tags))


def merge_snapshot(into: Dict[str, dict], other: Dict[str, dict]) -> None:
    """Fold one collect() snapshot into another, in place.  Series with
    identical tags combine by type: counters and gauges sum, histogram
    summaries sum count/sum and extend min/max.  Used by hostd to merge
    worker-process registries (e.g. serve replica engines) into the
    node-level scrape."""
    for name, m in other.items():
        dst = into.get(name)
        if dst is None:
            into[name] = {
                "type": m["type"],
                "description": m["description"],
                "tag_keys": list(m["tag_keys"]),
                "series": [dict(s) for s in m["series"]],
            }
            if m.get("buckets"):
                into[name]["buckets"] = list(m["buckets"])
            continue
        by_tags = {tuple(sorted(s["tags"].items())): s
                   for s in dst["series"]}
        if m.get("buckets") and not dst.get("buckets"):
            dst["buckets"] = list(m["buckets"])
        for s in m["series"]:
            key = tuple(sorted(s["tags"].items()))
            cur = by_tags.get(key)
            if cur is None:
                dst["series"].append(dict(s))
                continue
            if isinstance(s["value"], dict):  # histogram summary
                cv, sv = cur["value"], s["value"]
                cv["count"] += sv["count"]
                cv["sum"] += sv["sum"]
                cv["min"] = min(cv["min"], sv["min"])
                cv["max"] = max(cv["max"], sv["max"])
                cb, sb = cv.get("buckets"), sv.get("buckets")
                if cb is not None and sb is not None and len(cb) == len(sb):
                    # Bucket-exact fold: same boundaries (both sides
                    # declared the metric), counts sum element-wise.
                    cv["buckets"] = [a + b for a, b in zip(cb, sb)]
            else:
                cur["value"] += s["value"]


def prometheus_text(snapshot: Optional[Dict[str, dict]] = None,
                    extra_tags: Optional[Dict[str, str]] = None) -> str:
    """Render a collect() snapshot in Prometheus exposition format."""
    snapshot = snapshot if snapshot is not None else collect()
    extra = extra_tags or {}
    lines: List[str] = []
    for name, m in sorted(snapshot.items()):
        full = f"ray_tpu_{name}"
        if m.get("description"):
            lines.append(f"# HELP {full} {m['description']}")
        lines.append(f"# TYPE {full} {m['type']}")
        bounds = m.get("buckets") or ()
        for series in m["series"]:
            tags = {**extra, **series["tags"]}
            label = ",".join(f'{k}="{v}"' for k, v in sorted(tags.items()))
            braced = "{" + label + "}" if label else ""
            v = series["value"]
            if isinstance(v, dict):  # histogram
                counts = v.get("buckets")
                if bounds and counts and len(counts) == len(bounds) + 1:
                    cum = 0
                    for le, c in zip(bounds, counts):
                        cum += c
                        ltags = (label + "," if label else "") + f'le="{le}"'
                        lines.append(
                            f"{full}_bucket{{{ltags}}} {cum}")
                    itags = (label + "," if label else "") + 'le="+Inf"'
                    lines.append(
                        f"{full}_bucket{{{itags}}} {cum + counts[-1]}")
                for suffix in ("count", "sum", "min", "max"):
                    lines.append(f"{full}_{suffix}{braced} {v[suffix]}")
                qs = series_quantiles(m, series)
                if qs:
                    for q, qv in sorted(qs.items()):
                        tag = f"p{int(round(q * 100))}"
                        lines.append(f"{full}_{tag}{braced} {qv:.6g}")
            else:
                lines.append(f"{full}{braced} {v}")
    return "\n".join(lines) + "\n"
