"""Metrics: counters/gauges/histograms with tag support + Prometheus text
exposition.

Reference parity: src/ray/stats/metric.h:26 (Count/Gauge/Histogram defs,
metric_defs.h:46-110) and the user API python/ray/util/metrics.py; export
follows the per-node agent -> Prometheus text format path
(_private/metrics_agent.py, prometheus_exporter.py) — here each daemon
serves its registry over a Metrics RPC and the CLI/state API renders the
exposition format.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

_registry: Dict[str, "Metric"] = {}
_registry_lock = threading.Lock()


class Metric:
    TYPE = "untyped"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Tuple[str, ...] = ()):
        if not name.replace("_", "").isalnum():
            raise ValueError(f"invalid metric name {name!r}")
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._lock = threading.Lock()
        self._values: Dict[Tuple[str, ...], float] = {}
        with _registry_lock:
            existing = _registry.get(name)
            if existing is not None:
                # Re-declaration returns the same underlying series store
                # (common for module reloads); types must agree.
                if existing.TYPE != self.TYPE:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.TYPE}, cannot redeclare as {self.TYPE}")
                self._values = existing._values
                self._lock = existing._lock
            _registry[name] = self

    def _key(self, tags: Optional[Dict[str, str]]) -> Tuple[str, ...]:
        tags = tags or {}
        return tuple(str(tags.get(k, "")) for k in self.tag_keys)

    def _series(self) -> List[Tuple[Tuple[str, ...], float]]:
        with self._lock:
            return list(self._values.items())


class Counter(Metric):
    TYPE = "counter"

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        key = self._key(tags)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value


class Gauge(Metric):
    TYPE = "gauge"

    def set(self, value: float,
            tags: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._values[self._key(tags)] = float(value)


class Histogram(Metric):
    """Bucketless summary: tracks count/sum/min/max per series (the
    reference exports full buckets; sum+count cover rate/mean queries)."""

    TYPE = "histogram"

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None) -> None:
        key = self._key(tags)
        with self._lock:
            cur = self._values.get(key)
            if cur is None:
                cur = {"count": 0.0, "sum": 0.0, "min": value, "max": value}
                self._values[key] = cur
            cur["count"] += 1
            cur["sum"] += value
            cur["min"] = min(cur["min"], value)
            cur["max"] = max(cur["max"], value)


class timer:
    """Context manager that adds the elapsed wall seconds to a Counter
    (e.g. the ingest producer/consumer wait accumulators) — the cheap
    idiom for 'how long was this side blocked':

        with metrics.timer(wait_counter):
            item = q.get()
    """

    __slots__ = ("_counter", "_tags", "_t0", "elapsed")

    def __init__(self, counter: Counter, tags: Optional[Dict[str, str]] = None):
        self._counter = counter
        self._tags = tags
        self.elapsed = 0.0

    def __enter__(self) -> "timer":
        import time
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        import time
        self.elapsed = time.perf_counter() - self._t0
        self._counter.inc(self.elapsed, self._tags)


def collect() -> Dict[str, dict]:
    """Snapshot of every metric in this process."""
    with _registry_lock:
        metrics = list(_registry.values())
    out: Dict[str, dict] = {}
    for m in metrics:
        out[m.name] = {
            "type": m.TYPE,
            "description": m.description,
            "tag_keys": list(m.tag_keys),
            "series": [
                {"tags": dict(zip(m.tag_keys, key)), "value": value}
                for key, value in m._series()],
        }
    return out


def read(name: str, tags: Optional[Dict[str, str]] = None):
    """Current value of one series of an in-process metric, or None if
    the metric (or series) does not exist.  Tests and benches use this
    to assert on counters (e.g. serve shed/failover counts) without
    round-tripping through the exposition format."""
    with _registry_lock:
        m = _registry.get(name)
    if m is None:
        return None
    with m._lock:
        return m._values.get(m._key(tags))


def merge_snapshot(into: Dict[str, dict], other: Dict[str, dict]) -> None:
    """Fold one collect() snapshot into another, in place.  Series with
    identical tags combine by type: counters and gauges sum, histogram
    summaries sum count/sum and extend min/max.  Used by hostd to merge
    worker-process registries (e.g. serve replica engines) into the
    node-level scrape."""
    for name, m in other.items():
        dst = into.get(name)
        if dst is None:
            into[name] = {
                "type": m["type"],
                "description": m["description"],
                "tag_keys": list(m["tag_keys"]),
                "series": [dict(s) for s in m["series"]],
            }
            continue
        by_tags = {tuple(sorted(s["tags"].items())): s
                   for s in dst["series"]}
        for s in m["series"]:
            key = tuple(sorted(s["tags"].items()))
            cur = by_tags.get(key)
            if cur is None:
                dst["series"].append(dict(s))
                continue
            if isinstance(s["value"], dict):  # histogram summary
                cv, sv = cur["value"], s["value"]
                cv["count"] += sv["count"]
                cv["sum"] += sv["sum"]
                cv["min"] = min(cv["min"], sv["min"])
                cv["max"] = max(cv["max"], sv["max"])
            else:
                cur["value"] += s["value"]


def prometheus_text(snapshot: Optional[Dict[str, dict]] = None,
                    extra_tags: Optional[Dict[str, str]] = None) -> str:
    """Render a collect() snapshot in Prometheus exposition format."""
    snapshot = snapshot if snapshot is not None else collect()
    extra = extra_tags or {}
    lines: List[str] = []
    for name, m in sorted(snapshot.items()):
        full = f"ray_tpu_{name}"
        if m.get("description"):
            lines.append(f"# HELP {full} {m['description']}")
        ptype = m["type"] if m["type"] != "histogram" else "summary"
        lines.append(f"# TYPE {full} {ptype}")
        for series in m["series"]:
            tags = {**extra, **series["tags"]}
            label = ",".join(f'{k}="{v}"' for k, v in sorted(tags.items()))
            label = "{" + label + "}" if label else ""
            v = series["value"]
            if isinstance(v, dict):  # histogram summary
                for suffix in ("count", "sum", "min", "max"):
                    lines.append(f"{full}_{suffix}{label} {v[suffix]}")
            else:
                lines.append(f"{full}{label} {v}")
    return "\n".join(lines) + "\n"
