"""Placement groups: gang-reserve resource bundles across nodes.

Reference parity: python/ray/util/placement_group.py (placement_group(),
PlacementGroup.ready()/wait(), remove_placement_group,
placement_group_table) over the GCS manager's 2PC bundle protocol
(gcs_placement_group_manager.h, node_manager.proto:378-382).

TPU idiom: a STRICT_PACK group is one TPU host; a SPREAD group with one
bundle per host of a slice gang-reserves the whole slice for an SPMD job.
"""

from __future__ import annotations

from typing import List, Optional

from ray_tpu import api
from ray_tpu._private.ids import PlacementGroupID

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    """Handle to a placement group (reference: util/placement_group.py)."""

    def __init__(self, pg_id: PlacementGroupID,
                 bundles: Optional[List[dict]] = None):
        self.id = pg_id
        self._bundles = bundles

    def ready(self) -> bool:
        """Block until scheduled; True when CREATED.  (The reference returns
        an ObjectRef; here readiness is a control-plane wait — objects never
        get involved.)"""
        return api._get_worker().wait_placement_group_ready(self.id, None)

    def wait(self, timeout_seconds: float = 30) -> bool:
        return api._get_worker().wait_placement_group_ready(
            self.id, timeout_seconds)

    @property
    def bundle_specs(self) -> List[dict]:
        if self._bundles is None:
            info = api._get_worker().get_placement_group_info(self.id)
            self._bundles = list(info.bundles) if info else []
        return self._bundles

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)

    def __reduce__(self):
        return (PlacementGroup, (self.id, self._bundles))

    def __repr__(self):
        return f"PlacementGroup({self.id.hex()[:12]})"


def placement_group(bundles: List[dict], strategy: str = "PACK",
                    name: str = "", lifetime: Optional[str] = None
                    ) -> PlacementGroup:
    """Gang-reserve `bundles` (list of resource dicts) across the cluster."""
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"strategy must be one of {VALID_STRATEGIES}")
    if not bundles or not all(isinstance(b, dict) and b for b in bundles):
        raise ValueError("bundles must be a non-empty list of non-empty "
                         "resource dicts")
    for b in bundles:
        if any(v < 0 for v in b.values()):
            raise ValueError(f"negative resource in bundle {b}")
    pg_id = api._get_worker().create_placement_group(
        [dict(b) for b in bundles], strategy, name, lifetime)
    return PlacementGroup(pg_id, [dict(b) for b in bundles])


def remove_placement_group(pg: PlacementGroup) -> None:
    api._get_worker().remove_placement_group(pg.id)


def placement_group_table() -> dict:
    out = {}
    for info in api._get_worker().list_placement_groups():
        out[info.pg_id.hex()] = {
            "name": info.name,
            "strategy": info.strategy,
            "state": info.state,
            "bundles": {i: b for i, b in enumerate(info.bundles)},
            "bundle_nodes": [n.hex() if n else None
                             for n in info.bundle_nodes],
        }
    return out


def get_current_placement_group() -> Optional[PlacementGroup]:
    """The PG of the currently executing task/actor, if any."""
    worker = api._get_worker()
    spec = getattr(worker, "current_task_spec", None)
    if spec is not None and spec.placement_group is not None:
        return PlacementGroup(spec.placement_group)
    actor_pg = getattr(worker, "current_actor_pg", None)
    if actor_pg is not None:
        return PlacementGroup(actor_pg)
    return None
