"""multiprocessing.Pool drop-in over cluster tasks.

Reference parity: python/ray/util/multiprocessing/ (Pool shim — the
standard-library Pool API executed as Ray tasks so existing Pool code
scales past one machine).  Each submission is one task; `chunksize`
batches items per task as in the stdlib.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu


class _CallbackDrainer:
    """ONE shared thread fires every AsyncResult callback (the stdlib
    pool's result-handler role): a thread per callbacked submission
    would blow up under apply_async storms."""

    def __init__(self):
        import threading
        self._entries: list = []
        self._cv = threading.Condition()
        self._thread = None

    def register(self, result: "AsyncResult", callback, error_callback):
        import threading
        with self._cv:
            self._entries.append((result, callback, error_callback))
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._loop, daemon=True, name="pool-callbacks")
                self._thread.start()
            self._cv.notify()

    def _loop(self):
        while True:
            with self._cv:
                while not self._entries:
                    self._cv.wait()
                entries = list(self._entries)
            remaining = []
            for entry in entries:
                result, callback, error_callback = entry
                if not result.ready():
                    remaining.append(entry)
                    continue
                try:
                    value = result.get(timeout=0)
                except BaseException as e:  # noqa: BLE001
                    if error_callback is not None:
                        try:
                            error_callback(e)
                        except Exception:
                            pass
                    continue
                if callback is not None:
                    try:
                        callback(value)
                    except Exception:
                        pass
            with self._cv:
                done = set(map(id, entries)) - set(map(id, remaining))
                self._entries = [e for e in self._entries
                                 if id(e) not in done]
            import time as _time
            _time.sleep(0.02)


_drainer = _CallbackDrainer()


class AsyncResult:
    def __init__(self, refs, single: bool, callback=None,
                 error_callback=None):
        self._refs = refs
        self._single = single
        if callback is not None or error_callback is not None:
            _drainer.register(self, callback, error_callback)

    def get(self, timeout: Optional[float] = None):
        results = ray_tpu.get(self._refs, timeout=timeout)
        if self._single:
            return results[0][0]   # one chunk of one item
        return list(itertools.chain.from_iterable(results))

    def wait(self, timeout: Optional[float] = None) -> None:
        ray_tpu.wait(self._refs, num_returns=len(self._refs),
                     timeout=timeout)

    def ready(self) -> bool:
        done, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs),
                               timeout=0)
        return len(done) == len(self._refs)

    def successful(self) -> bool:
        if not self.ready():
            raise ValueError("AsyncResult not ready")
        try:
            ray_tpu.get(self._refs, timeout=0)
            return True
        except Exception:
            return False


class Pool:
    """stdlib-compatible surface: apply/apply_async/map/map_async/starmap/
    imap/imap_unordered/close/terminate/join, plus context-manager use."""

    def __init__(self, processes: Optional[int] = None, *,
                 ray_remote_args: Optional[dict] = None):
        if ray_tpu.api._worker is None:
            ray_tpu.init()
        self._size = processes or int(
            ray_tpu.cluster_resources().get("CPU", 1))
        args = dict(ray_remote_args or {})
        args.setdefault("num_cpus", 1)

        @ray_tpu.remote(**args)
        def _run_chunk(fn, chunk, star):
            return [fn(*item) if star else fn(item) for item in chunk]

        self._run_chunk = _run_chunk
        self._closed = False

    # -- helpers --

    def _chunks(self, iterable: Iterable, chunksize: Optional[int]):
        items = list(iterable)
        if chunksize is None:
            chunksize = max(1, len(items) // (self._size * 4) or 1)
        return [items[i:i + chunksize]
                for i in range(0, len(items), chunksize)]

    def _check_open(self):
        if self._closed:
            raise ValueError("Pool not running")

    # -- stdlib surface --

    def apply(self, fn: Callable, args=(), kwds=None):
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn: Callable, args=(), kwds=None,
                    callback=None, error_callback=None) -> AsyncResult:
        self._check_open()
        kwds = kwds or {}
        ref = self._run_chunk.remote(
            lambda *a: fn(*a, **kwds), [tuple(args)], True)
        return AsyncResult([ref], single=True, callback=callback,
                           error_callback=error_callback)

    def map(self, fn: Callable, iterable: Iterable,
            chunksize: Optional[int] = None) -> List[Any]:
        return self.map_async(fn, iterable, chunksize).get()

    def map_async(self, fn: Callable, iterable: Iterable,
                  chunksize: Optional[int] = None,
                  callback=None, error_callback=None) -> AsyncResult:
        self._check_open()
        refs = [self._run_chunk.remote(fn, chunk, False)
                for chunk in self._chunks(iterable, chunksize)]
        return AsyncResult(refs, single=False, callback=callback,
                           error_callback=error_callback)

    def starmap(self, fn: Callable, iterable: Iterable,
                chunksize: Optional[int] = None) -> List[Any]:
        return self.starmap_async(fn, iterable, chunksize).get()

    def starmap_async(self, fn: Callable, iterable: Iterable,
                      chunksize: Optional[int] = None,
                      callback=None, error_callback=None) -> AsyncResult:
        self._check_open()
        refs = [self._run_chunk.remote(fn, chunk, True)
                for chunk in self._chunks(iterable, chunksize)]
        return AsyncResult(refs, single=False, callback=callback,
                           error_callback=error_callback)

    def imap(self, fn: Callable, iterable: Iterable,
             chunksize: Optional[int] = None):
        self._check_open()
        for chunk_ref in [self._run_chunk.remote(fn, c, False)
                          for c in self._chunks(iterable, chunksize)]:
            yield from ray_tpu.get(chunk_ref)

    def imap_unordered(self, fn: Callable, iterable: Iterable,
                       chunksize: Optional[int] = None):
        self._check_open()
        pending = [self._run_chunk.remote(fn, c, False)
                   for c in self._chunks(iterable, chunksize)]
        while pending:
            done, pending = ray_tpu.wait(pending, num_returns=1)
            for ref in done:
                yield from ray_tpu.get(ref)

    def close(self) -> None:
        self._closed = True

    def terminate(self) -> None:
        self._closed = True

    def join(self) -> None:
        if not self._closed:
            raise ValueError("Pool is still running")

    def __enter__(self) -> "Pool":
        return self

    def __exit__(self, *exc) -> None:
        self.terminate()
