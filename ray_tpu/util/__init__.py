"""ray_tpu.util — utilities over the core primitives (reference: ray/util/)."""

from ray_tpu.util.placement_group import (  # noqa: F401
    PlacementGroup,
    get_current_placement_group,
    placement_group,
    placement_group_table,
    remove_placement_group,
)
from ray_tpu.util.actor_pool import ActorPool  # noqa: F401
from ray_tpu.util.queue import Queue  # noqa: F401
from ray_tpu.util.pubsub import Publisher, Subscriber  # noqa: F401
