"""Actor-group collectives: allreduce/allgather/broadcast/... between
cluster processes.

Reference parity: python/ray/util/collective/collective.py
(init_collective_group:120, allreduce:258, broadcast:373, allgather:423,
reducescatter:472, send:531/recv:594, barrier) with group rendezvous via a
named actor holding the NCCL unique id.

TPU-first split: this module is the HOST plane — control/bulk collectives
between actor processes over the object store (the reference's gloo
backend role).  The accelerator plane is NOT here: device-array
collectives compile to XLA psum/all-gather/reduce-scatter over the ICI
mesh (ray_tpu.parallel + jax shardings), which is the reference's NCCL
path re-imagined for TPU (SURVEY §2.5 mapping).

Usage (inside each participating actor/driver process):

    from ray_tpu.util import collective
    collective.init_collective_group(world_size=4, rank=r, group_name="g")
    out = collective.allreduce(np.ones(8), group_name="g")
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, Optional

import numpy as np

import ray_tpu

_COORD_PREFIX = "_collective_coord:"
_OPS = ("SUM", "PRODUCT", "MIN", "MAX")


class _Coordinator:
    """Named async actor: one per group; synchronizes each collective call
    and computes reductions (the reference's rendezvous-actor role, plus
    the gloo data plane since the host plane has no NCCL)."""

    def __init__(self, world_size: int):
        self.world = world_size
        self._calls: Dict[tuple, dict] = {}   # (kind, seq) -> state
        self._p2p: Dict[tuple, Any] = {}      # (seq-less src->dst tag) -> data
        self._p2p_events: Dict[tuple, asyncio.Event] = {}

    def _state(self, key):
        st = self._calls.get(key)
        if st is None:
            st = {"data": {}, "event": asyncio.Event()}
            self._calls[key] = st
        return st

    async def _gather(self, key, rank, data):
        st = self._state(key)
        st["data"][rank] = data
        if len(st["data"]) == self.world:
            st["event"].set()
        else:
            await st["event"].wait()
        return st

    def _maybe_gc(self, key, st):
        st.setdefault("done", 0)
        st["done"] += 1
        if st["done"] == self.world:
            del self._calls[key]

    async def allreduce(self, seq: int, rank: int, data, op: str):
        st = await self._gather(("ar", seq, op), rank, data)
        if "result" not in st:
            arrs = [np.asarray(st["data"][r]) for r in range(self.world)]
            if op == "SUM":
                out = sum(arrs[1:], arrs[0].copy())
            elif op == "PRODUCT":
                out = arrs[0].copy()
                for a in arrs[1:]:
                    out = out * a
            elif op == "MIN":
                out = np.minimum.reduce(arrs)
            elif op == "MAX":
                out = np.maximum.reduce(arrs)
            else:
                raise ValueError(f"unknown op {op}")
            st["result"] = out
        result = st["result"]
        self._maybe_gc(("ar", seq, op), st)
        return result

    async def allgather(self, seq: int, rank: int, data):
        st = await self._gather(("ag", seq), rank, data)
        result = [st["data"][r] for r in range(self.world)]
        self._maybe_gc(("ag", seq), st)
        return result

    async def reducescatter(self, seq: int, rank: int, data, op: str):
        st = await self._gather(("rs", seq, op), rank, data)
        if "result" not in st:
            arrs = [np.asarray(st["data"][r]) for r in range(self.world)]
            total = sum(arrs[1:], arrs[0].copy()) if op == "SUM" else None
            if total is None:
                raise ValueError(f"reducescatter supports SUM, got {op}")
            st["result"] = np.array_split(total, self.world)
        result = st["result"][rank]
        self._maybe_gc(("rs", seq, op), st)
        return result

    async def broadcast(self, seq: int, rank: int, data, src: int):
        st = self._state(("bc", seq, src))
        if rank == src:
            st["data"][src] = data
            st["event"].set()
        else:
            await st["event"].wait()
        result = st["data"][src]
        self._maybe_gc(("bc", seq, src), st)
        return result

    async def barrier(self, seq: int, rank: int):
        st = await self._gather(("ba", seq), rank, None)
        self._maybe_gc(("ba", seq), st)
        return True

    async def send(self, tag: tuple, data):
        self._p2p[tag] = data
        self._p2p_events.setdefault(tag, asyncio.Event()).set()
        return True

    async def recv(self, tag: tuple):
        ev = self._p2p_events.setdefault(tag, asyncio.Event())
        await ev.wait()
        data = self._p2p.pop(tag)
        del self._p2p_events[tag]
        return data


class _Group:
    def __init__(self, coordinator, world_size: int, rank: int, name: str):
        self.coord = coordinator
        self.world = world_size
        self.rank = rank
        self.name = name
        self.seq = 0           # collective-call counter (all ranks in step)
        self.p2p_seq: Dict[tuple, int] = {}

    def next_seq(self) -> int:
        s = self.seq
        self.seq += 1
        return s


_groups: Dict[str, _Group] = {}


def init_collective_group(world_size: int, rank: int,
                          group_name: str = "default",
                          backend: str = "objstore") -> None:
    """Join a collective group from THIS process (reference:
    collective.py:120 — every participant calls this; rank 0's call
    creates the rendezvous actor)."""
    if backend != "objstore":
        raise ValueError(
            "host-plane backend is 'objstore'; device collectives use the "
            "mesh/XLA plane (ray_tpu.parallel), not this API")
    if group_name in _groups:
        raise RuntimeError(f"group {group_name!r} already initialized here")
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} outside world {world_size}")
    coord = ray_tpu.remote(_Coordinator).options(
        name=_COORD_PREFIX + group_name, get_if_exists=True,
        num_cpus=0, max_concurrency=max(8, 2 * world_size),
    ).remote(world_size)
    _groups[group_name] = _Group(coord, world_size, rank, group_name)


def is_group_initialized(group_name: str = "default") -> bool:
    return group_name in _groups


def destroy_collective_group(group_name: str = "default") -> None:
    g = _groups.pop(group_name, None)
    if g is not None and g.rank == 0:
        try:
            ray_tpu.kill(g.coord)
        except Exception:
            pass


def get_rank(group_name: str = "default") -> int:
    return _groups[group_name].rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _groups[group_name].world


def _group(name: str) -> _Group:
    g = _groups.get(name)
    if g is None:
        raise RuntimeError(
            f"collective group {name!r} not initialized in this process")
    return g


def allreduce(tensor, group_name: str = "default", op: str = "SUM"):
    g = _group(group_name)
    if op not in _OPS:
        raise ValueError(f"op must be one of {_OPS}")
    return ray_tpu.get(g.coord.allreduce.remote(
        g.next_seq(), g.rank, np.asarray(tensor), op))


def allgather(tensor, group_name: str = "default"):
    g = _group(group_name)
    return [np.asarray(x) for x in ray_tpu.get(
        g.coord.allgather.remote(g.next_seq(), g.rank,
                                 np.asarray(tensor)))]


def reducescatter(tensor, group_name: str = "default", op: str = "SUM"):
    g = _group(group_name)
    return np.asarray(ray_tpu.get(g.coord.reducescatter.remote(
        g.next_seq(), g.rank, np.asarray(tensor), op)))


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    g = _group(group_name)
    return np.asarray(ray_tpu.get(g.coord.broadcast.remote(
        g.next_seq(), g.rank, np.asarray(tensor), src_rank)))


def barrier(group_name: str = "default") -> None:
    g = _group(group_name)
    ray_tpu.get(g.coord.barrier.remote(g.next_seq(), g.rank))


def send(tensor, dest_rank: int, group_name: str = "default") -> None:
    g = _group(group_name)
    key = (g.rank, dest_rank)
    n = g.p2p_seq.get(key, 0)
    g.p2p_seq[key] = n + 1
    ray_tpu.get(g.coord.send.remote(("p2p", g.rank, dest_rank, n),
                                    np.asarray(tensor)))


def recv(src_rank: int, group_name: str = "default"):
    g = _group(group_name)
    key = (src_rank, g.rank)
    n = g.p2p_seq.get(key, 0)
    g.p2p_seq[key] = n + 1
    return np.asarray(ray_tpu.get(
        g.coord.recv.remote(("p2p", src_rank, g.rank, n))))
