"""Actor-group collectives: allreduce/allgather/broadcast/... between
cluster processes.

Reference parity: python/ray/util/collective/collective.py
(init_collective_group:120, allreduce:258, broadcast:373, allgather:423,
reducescatter:472, send:531/recv:594, barrier) with the gloo backend's
ring data movement (collective_group/gloo_collective_group.py).

TPU-first split: this module is the HOST plane — control/bulk collectives
between actor processes over the object store.  The accelerator plane is
NOT here: device-array collectives compile to XLA psum/all-gather/
reduce-scatter over the ICI mesh (ray_tpu.parallel + jax shardings),
which is the reference's NCCL path re-imagined for TPU (SURVEY §2.5).

Data plane: bulk payloads are ring-passed as OBJECT-STORE objects —
rank r puts a segment, its neighbour pulls it store-to-store (the native
TCP plane moves the bytes shm-to-shm) — while the named coordinator
actor relays only ObjectRefs and acks (~100 bytes per hop).  Ring
allreduce moves 2*(W-1)/W of the tensor per rank, like gloo's ring.
Payloads under _SMALL bypass the store: the ref+pull round trips cost
more than shipping tiny arrays through the coordinator directly.

Usage (inside each participating actor/driver process):

    from ray_tpu.util import collective
    collective.init_collective_group(world_size=4, rank=r, group_name="g")
    out = collective.allreduce(np.ones(8), group_name="g")
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, Optional

import numpy as np

import ray_tpu

_COORD_PREFIX = "_collective_coord:"
_OPS = ("SUM", "PRODUCT", "MIN", "MAX")
_SMALL = 64 * 1024  # bytes: below this, data rides the coordinator


def _reduce2(a: np.ndarray, b: np.ndarray, op: str) -> np.ndarray:
    if op == "SUM":
        return a + b
    if op == "PRODUCT":
        return a * b
    if op == "MIN":
        return np.minimum(a, b)
    if op == "MAX":
        return np.maximum(a, b)
    raise ValueError(f"unknown op {op}")


class _Coordinator:
    """Named async actor: one per group.  For bulk collectives it is pure
    CONTROL plane — mailboxes of ObjectRefs + acks + barriers; payload
    bytes never pass through it.  Sub-_SMALL payloads use the legacy
    direct methods (gather/reduce in-actor)."""

    def __init__(self, world_size: int):
        self.world = world_size
        self._calls: Dict[tuple, dict] = {}   # (kind, seq) -> state
        self._boxes: Dict[tuple, Any] = {}    # mailbox tag -> ref/data
        self._box_events: Dict[tuple, asyncio.Event] = {}
        self._acks: Dict[tuple, asyncio.Event] = {}
        # Payload bytes that crossed THIS actor (small-path only; the ring
        # plane moves refs, so this must stay ~0 for bulk collectives —
        # asserted in tests).
        self.bytes_through = 0

    def payload_bytes_through(self) -> int:
        return self.bytes_through

    # ---- shared machinery ------------------------------------------------

    def _state(self, key):
        st = self._calls.get(key)
        if st is None:
            st = {"data": {}, "event": asyncio.Event()}
            self._calls[key] = st
        return st

    async def _gather(self, key, rank, data):
        st = self._state(key)
        st["data"][rank] = data
        if len(st["data"]) == self.world:
            st["event"].set()
        else:
            await st["event"].wait()
        return st

    def _maybe_gc(self, key, st):
        st.setdefault("done", 0)
        st["done"] += 1
        if st["done"] == self.world:
            del self._calls[key]

    def _ev(self, table: dict, tag) -> asyncio.Event:
        ev = table.get(tag)
        if ev is None:
            ev = table[tag] = asyncio.Event()
        return ev

    # ---- ring control plane (refs only) ---------------------------------

    async def exchange(self, out_tag, in_tag, ref):
        """Drop `ref` in out_tag's mailbox; wait for and return in_tag's."""
        self._boxes[out_tag] = ref
        self._ev(self._box_events, out_tag).set()
        await self._ev(self._box_events, in_tag).wait()
        got = self._boxes.pop(in_tag)
        del self._box_events[in_tag]
        return got

    async def ack_and_wait(self, acked_tag, my_tag):
        """Ack consumption of acked_tag's payload, then wait until MY
        outgoing payload was consumed — the sender may then free it
        (bounds live segments to ~2 per rank during a ring)."""
        self._ev(self._acks, acked_tag).set()
        await self._ev(self._acks, my_tag).wait()
        del self._acks[my_tag]
        return True

    async def ack(self, tag):
        self._ev(self._acks, tag).set()
        return True

    async def wait_ack(self, tag):
        await self._ev(self._acks, tag).wait()
        del self._acks[tag]
        return True

    async def gather_refs(self, seq, rank, ref):
        """All-to-all ref exchange (allgather/broadcast control)."""
        self.bytes_through += getattr(ref, "nbytes", 0)
        st = await self._gather(("gr", seq), rank, ref)
        result = [st["data"][r] for r in range(self.world)]
        self._maybe_gc(("gr", seq), st)
        return result

    async def barrier(self, seq, rank):
        st = await self._gather(("ba", seq), rank, None)
        self._maybe_gc(("ba", seq), st)
        return True

    # ---- small-payload direct plane -------------------------------------

    async def allreduce_small(self, seq, rank, data, op: str):
        self.bytes_through += getattr(data, "nbytes", 0)
        st = await self._gather(("ar", seq, op), rank, data)
        if "result" not in st:
            arrs = [np.asarray(st["data"][r]) for r in range(self.world)]
            out = arrs[0].copy()
            for a in arrs[1:]:
                out = _reduce2(out, a, op)
            st["result"] = out
        result = st["result"]
        self._maybe_gc(("ar", seq, op), st)
        return result

    async def allgather_small(self, seq, rank, data):
        self.bytes_through += getattr(data, "nbytes", 0)
        st = await self._gather(("ag", seq), rank, data)
        result = [st["data"][r] for r in range(self.world)]
        self._maybe_gc(("ag", seq), st)
        return result

    async def reducescatter_small(self, seq, rank, data, op: str):
        self.bytes_through += getattr(data, "nbytes", 0)
        st = await self._gather(("rs", seq, op), rank, data)
        if "result" not in st:
            arrs = [np.asarray(st["data"][r]) for r in range(self.world)]
            total = arrs[0].copy()
            for a in arrs[1:]:
                total = _reduce2(total, a, op)
            st["result"] = np.array_split(total, self.world)
        result = st["result"][rank]
        self._maybe_gc(("rs", seq, op), st)
        return result

    async def send(self, tag: tuple, data):
        self.bytes_through += getattr(data, "nbytes", 0)
        self._boxes[tag] = data
        self._ev(self._box_events, tag).set()
        return True

    async def recv(self, tag: tuple):
        await self._ev(self._box_events, tag).wait()
        data = self._boxes.pop(tag)
        del self._box_events[tag]
        return data


class _Group:
    def __init__(self, coordinator, world_size: int, rank: int, name: str):
        self.coord = coordinator
        self.world = world_size
        self.rank = rank
        self.name = name
        self.seq = 0           # collective-call counter (all ranks in step)
        self.p2p_seq: Dict[tuple, int] = {}

    def next_seq(self) -> int:
        s = self.seq
        self.seq += 1
        return s


_groups: Dict[str, _Group] = {}


def init_collective_group(world_size: int, rank: int,
                          group_name: str = "default",
                          backend: str = "objstore") -> None:
    """Join a collective group from THIS process (reference:
    collective.py:120 — every participant calls this; rank 0's call
    creates the rendezvous actor)."""
    if backend != "objstore":
        raise ValueError(
            "host-plane backend is 'objstore'; device collectives use the "
            "mesh/XLA plane (ray_tpu.parallel), not this API")
    if group_name in _groups:
        raise RuntimeError(f"group {group_name!r} already initialized here")
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} outside world {world_size}")
    coord = ray_tpu.remote(_Coordinator).options(
        name=_COORD_PREFIX + group_name, get_if_exists=True,
        num_cpus=0, max_concurrency=max(8, 2 * world_size),
    ).remote(world_size)
    _groups[group_name] = _Group(coord, world_size, rank, group_name)


def is_group_initialized(group_name: str = "default") -> bool:
    return group_name in _groups


def destroy_collective_group(group_name: str = "default") -> None:
    g = _groups.pop(group_name, None)
    if g is not None and g.rank == 0:
        try:
            ray_tpu.kill(g.coord)
        except Exception:
            pass


def get_rank(group_name: str = "default") -> int:
    return _groups[group_name].rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _groups[group_name].world


def _group(name: str) -> _Group:
    g = _groups.get(name)
    if g is None:
        raise RuntimeError(
            f"collective group {name!r} not initialized in this process")
    return g


# ---------------------------------------------------------------------------
# Ring data plane
# ---------------------------------------------------------------------------

def _ring_exchange(g: _Group, tag: tuple, payload: np.ndarray) -> np.ndarray:
    """One ring step: hand `payload` to the right neighbour, receive the
    left neighbour's, via refs through the coordinator.  Returns after the
    right neighbour has CONSUMED our payload, so the put ref may be freed
    immediately (live segments stay O(1)).

    The ref rides inside a 1-tuple: a BARE ObjectRef argument is resolved
    to its value at the callee (reference semantics, _resolve_arg), which
    would ship the whole segment through the coordinator process; a
    nested ref stays a ref."""
    right = (g.rank + 1) % g.world
    left = (g.rank - 1) % g.world
    ref = ray_tpu.put(payload)
    out_tag = tag + (g.rank, right)
    in_tag = tag + (left, g.rank)
    got = ray_tpu.get(g.coord.exchange.remote(out_tag, in_tag, (ref,)))
    data = np.asarray(ray_tpu.get(got[0]))
    ray_tpu.get(g.coord.ack_and_wait.remote(in_tag, out_tag))
    return data


def _ring_reduce_scatter(g: _Group, flat: np.ndarray, seq: int,
                         op: str) -> list:
    """In-place ring reduce-scatter over np.array_split segments; after
    W-1 steps rank r holds the fully reduced segment r (matching the
    reducescatter contract: rank i receives reduced partition i)."""
    segs = [s.copy() for s in np.array_split(flat, g.world)]
    for step in range(g.world - 1):
        send_idx = (g.rank - step - 1) % g.world
        recv_idx = (g.rank - step - 2) % g.world
        incoming = _ring_exchange(g, ("rs", seq, step), segs[send_idx])
        segs[recv_idx] = _reduce2(segs[recv_idx], incoming, op)
    return segs


def allreduce(tensor, group_name: str = "default", op: str = "SUM"):
    g = _group(group_name)
    if op not in _OPS:
        raise ValueError(f"op must be one of {_OPS}")
    arr = np.asarray(tensor)
    seq = g.next_seq()
    if g.world == 1:
        return arr.copy()
    if arr.nbytes < _SMALL:
        return np.asarray(ray_tpu.get(g.coord.allreduce_small.remote(
            seq, g.rank, arr, op))).reshape(arr.shape)
    flat = arr.reshape(-1)
    segs = _ring_reduce_scatter(g, flat, seq, op)
    # Ring allgather of the reduced segments (rank r starts holding seg r).
    for step in range(g.world - 1):
        send_idx = (g.rank - step) % g.world
        recv_idx = (g.rank - step - 1) % g.world
        segs[recv_idx] = _ring_exchange(g, ("ag", seq, step),
                                        segs[send_idx])
    return np.concatenate(segs).reshape(arr.shape)


def reducescatter(tensor, group_name: str = "default", op: str = "SUM"):
    g = _group(group_name)
    if op not in _OPS:
        raise ValueError(f"op must be one of {_OPS}")
    arr = np.asarray(tensor)
    seq = g.next_seq()
    if g.world == 1:
        return arr.copy()
    if arr.nbytes < _SMALL:
        return np.asarray(ray_tpu.get(g.coord.reducescatter_small.remote(
            seq, g.rank, arr, op)))
    segs = _ring_reduce_scatter(g, arr.reshape(-1), seq, op)
    return segs[g.rank]


def allgather(tensor, group_name: str = "default"):
    g = _group(group_name)
    arr = np.asarray(tensor)
    seq = g.next_seq()
    if g.world == 1:
        return [arr.copy()]
    if arr.nbytes < _SMALL:
        return [np.asarray(x) for x in ray_tpu.get(
            g.coord.allgather_small.remote(seq, g.rank, arr))]
    # Refs through the coordinator (tuple-wrapped so they STAY refs),
    # payloads store-to-store.
    ref = ray_tpu.put(arr)
    boxes = ray_tpu.get(g.coord.gather_refs.remote(seq, g.rank, (ref,)))
    out = [np.asarray(ray_tpu.get(b[0])) for b in boxes]
    # Everyone fetched before any rank's put ref can die.
    ray_tpu.get(g.coord.barrier.remote(("agf", seq), g.rank))
    return out


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    g = _group(group_name)
    arr = np.asarray(tensor)
    seq = g.next_seq()
    if g.world == 1:
        return arr.copy()
    if arr.nbytes < _SMALL:
        refs = ray_tpu.get(g.coord.gather_refs.remote(seq, g.rank, arr))
        return np.asarray(refs[src_rank])
    box = (ray_tpu.put(arr),) if g.rank == src_rank else None
    boxes = ray_tpu.get(g.coord.gather_refs.remote(seq, g.rank, box))
    out = (arr.copy() if g.rank == src_rank
           else np.asarray(ray_tpu.get(boxes[src_rank][0])))
    ray_tpu.get(g.coord.barrier.remote(("bcf", seq), g.rank))
    return out


def barrier(group_name: str = "default") -> None:
    g = _group(group_name)
    ray_tpu.get(g.coord.barrier.remote(g.next_seq(), g.rank))


def send(tensor, dest_rank: int, group_name: str = "default") -> None:
    g = _group(group_name)
    arr = np.asarray(tensor)
    key = (g.rank, dest_rank)
    n = g.p2p_seq.get(key, 0)
    g.p2p_seq[key] = n + 1
    tag = ("p2p", g.rank, dest_rank, n)
    if arr.nbytes < _SMALL:
        ray_tpu.get(g.coord.send.remote(tag, arr))
        return
    ref = ray_tpu.put(arr)
    ray_tpu.get(g.coord.send.remote(tag, (ref,)))
    # Block until the receiver consumed the payload; the ref may then die.
    ray_tpu.get(g.coord.wait_ack.remote(tag + ("ack",)))


def recv(src_rank: int, group_name: str = "default"):
    g = _group(group_name)
    key = (src_rank, g.rank)
    n = g.p2p_seq.get(key, 0)
    g.p2p_seq[key] = n + 1
    tag = ("p2p", src_rank, g.rank, n)
    got = ray_tpu.get(g.coord.recv.remote(tag))
    if isinstance(got, tuple) and isinstance(got[0], ray_tpu.ObjectRef):
        data = np.asarray(ray_tpu.get(got[0]))
        ray_tpu.get(g.coord.ack.remote(tag + ("ack",)))
        return data
    return np.asarray(got)
