"""Distributed FIFO queue backed by an async actor.

Reference parity: python/ray/util/queue.py (Queue over a _QueueActor).
The actor is ASYNC: blocking put/get park coroutines on the actor's event
loop instead of pinning threads, so thousands of waiters are cheap.
"""

from __future__ import annotations

from typing import Any, List, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


@ray_tpu.remote
class _QueueActor:
    def __init__(self, maxsize: int):
        import asyncio
        self._q: "asyncio.Queue" = asyncio.Queue(
            maxsize=maxsize if maxsize > 0 else 0)

    async def put(self, item, timeout: Optional[float] = None) -> bool:
        import asyncio
        try:
            if timeout is None:
                await self._q.put(item)
            else:
                await asyncio.wait_for(self._q.put(item), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    async def get(self, timeout: Optional[float] = None):
        import asyncio
        try:
            if timeout is None:
                return True, await self._q.get()
            return True, await asyncio.wait_for(self._q.get(), timeout)
        except asyncio.TimeoutError:
            return False, None

    async def put_nowait(self, item) -> bool:
        try:
            self._q.put_nowait(item)
            return True
        except Exception:
            return False

    async def get_nowait(self):
        try:
            return True, self._q.get_nowait()
        except Exception:
            return False, None

    async def qsize(self) -> int:
        return self._q.qsize()


class Queue:
    """Client handle; picklable (travels by actor handle)."""

    def __init__(self, maxsize: int = 0, *, _actor=None):
        self.maxsize = maxsize
        self._actor = _actor or _QueueActor.options(num_cpus=0.05).remote(
            maxsize)

    def put(self, item, block: bool = True,
            timeout: Optional[float] = None) -> None:
        if not block:
            if not ray_tpu.get(self._actor.put_nowait.remote(item)):
                raise Full("queue is full")
            return
        if not ray_tpu.get(self._actor.put.remote(item, timeout)):
            raise Full("put timed out")

    def get(self, block: bool = True,
            timeout: Optional[float] = None) -> Any:
        if not block:
            ok, item = ray_tpu.get(self._actor.get_nowait.remote())
            if not ok:
                raise Empty("queue is empty")
            return item
        ok, item = ray_tpu.get(self._actor.get.remote(timeout))
        if not ok:
            raise Empty("get timed out")
        return item

    def put_nowait(self, item) -> None:
        self.put(item, block=False)

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def qsize(self) -> int:
        return ray_tpu.get(self._actor.qsize.remote())

    def empty(self) -> bool:
        return self.qsize() == 0

    def shutdown(self) -> None:
        try:
            ray_tpu.kill(self._actor)
        except Exception:
            pass

    def __reduce__(self):
        return (_rebuild_queue, (self.maxsize, self._actor))


def _rebuild_queue(maxsize, actor):
    return Queue(maxsize, _actor=actor)
