"""Self-speculative decoding: draft proposers for the batching engine.

The continuous-batching engine emits ONE token per jitted step, so every
per-step cost — dispatch, host scheduling, the sampling commit — is paid
per token.  Speculative decoding breaks that coupling: a cheap DRAFT of
up to k candidate tokens is verified by the real model in a single step.
The verify dispatch feeds ``[last_token, d_1 .. d_k]`` at positions
``[n .. n+k]`` (the chunked-prefill shape, so causal masking inside the
chunk already holds) and samples ALL k+1 next-token positions in-graph;
the longest prefix of drafts that matches the model's own sampled output
commits as one burst, and the blocks claimed for the rejected tail roll
back through ``PagedKVCache.truncate_lane``.

Output is token-exact vs the non-speculative engine by construction:
every emitted token IS the model's sampled token for its position (same
``fold_in(seed, produced)`` key the plain step would use) — drafts only
decide how many of those positions one step may confirm.

The core proposer is **n-gram / prompt-lookup** drafting (no second
model, so it runs on CPU CI): the request's own prompt + produced
history is scanned for the most recent earlier occurrence of the current
suffix n-gram, and the tokens that followed it are proposed verbatim.
On repetitive text (code, templated prose, multi-turn transcripts)
acceptance is high and decode collapses toward (k+1) tokens per step; on
incompressible text the per-request adaptive draft length backs off so
rejected verify FLOPs stay bounded.

``ModelDraftProposer`` is the optional small-draft-model path: a second
(cheaper) model greedily drafts from the tail of the context.  Anything
implementing :class:`DraftProposer` plugs into
``InferenceEngine(draft_proposer=...)``.
"""

from __future__ import annotations

from typing import List, Sequence


class DraftProposer:
    """Pluggable draft source for speculative decoding.

    ``propose`` receives the request's full known token context
    (prompt + everything emitted so far; the last element is the token
    the next step feeds) and may return up to ``k`` candidate
    continuation tokens — fewer (or none) when it has no confident
    guess, which degrades that lane to a plain one-token decode step.
    ``observe`` is acceptance feedback after each verify, for proposers
    that tune themselves.
    """

    def propose(self, context: Sequence[int], k: int) -> List[int]:
        raise NotImplementedError

    def observe(self, drafted: int, accepted: int) -> None:
        """Called after each verify with how many tokens this proposer
        drafted for the lane and how many the model accepted."""


class NgramProposer(DraftProposer):
    """Prompt-lookup drafting: match the longest suffix n-gram of the
    context against its own earlier occurrences (most recent match wins)
    and propose the tokens that followed that occurrence.

    ``max_ngram`` trades precision for match rate: longer suffixes
    produce fewer, better-targeted matches.  The scan falls through to
    shorter n-grams (down to ``min_ngram``) when a longer one has no
    earlier occurrence, and prefers the most RECENT match that still
    has k following tokens — on a cyclic stream the nearest occurrence
    sits only one period back with few followers, so older occurrences
    are what let the draft span several periods.
    """

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1):
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError("need 1 <= min_ngram <= max_ngram")
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram

    def propose(self, context: Sequence[int], k: int) -> List[int]:
        n_ctx = len(context)
        best: List[int] = []
        # The suffix itself (ending at n_ctx) must not count as a match,
        # hence the scan stops one short of the trailing occurrence — so
        # every hit has at least one following token to propose.
        for n in range(min(self.max_ngram, n_ctx - 1),
                       self.min_ngram - 1, -1):
            pattern = tuple(context[n_ctx - n:])
            for i in range(n_ctx - n - 1, -1, -1):
                if tuple(context[i:i + n]) != pattern:
                    continue
                cont = [int(t) for t in context[i + n:i + n + k]]
                if len(cont) >= k:
                    return cont
                if len(cont) > len(best):
                    best = cont
        return best


class ModelDraftProposer(DraftProposer):
    """Small-draft-model drafting: a second (cheaper) model greedily
    continues the tail of the context for up to k tokens.

    The draft model only needs to agree with the target model often
    enough to pay for its own forward passes — classic two-model
    speculative decoding.  ``window`` bounds the context the draft
    forward sees (full forward, no KV cache: the draft model is assumed
    small enough that re-running its prefix is cheaper than managing a
    second paged pool).
    """

    def __init__(self, model="gpt", config="nano", params=None, *,
                 window: int = 64, seed: int = 0):
        import jax
        import jax.numpy as jnp

        if isinstance(model, str):
            if model == "gpt":
                from ray_tpu.models import gpt as mod
            elif model == "llama":
                from ray_tpu.models import llama as mod
            else:
                raise ValueError(f"unknown draft model family {model!r}")
            model = mod
        self.model = model
        self.config = (model.CONFIGS[config] if isinstance(config, str)
                       else config)
        if params is None:
            params = model.init_params(self.config, jax.random.key(seed))
        self.params = params
        self.window = int(window)

        def _next(params, toks):
            out = model.forward(params, toks, self.config)
            logits = out[0] if isinstance(out, tuple) else out
            return jnp.argmax(logits[0, -1]).astype(jnp.int32)

        self._next = jax.jit(_next)
        self._jnp = jnp

    def propose(self, context: Sequence[int], k: int) -> List[int]:
        toks = [int(t) for t in context[-self.window:]]
        out: List[int] = []
        for _ in range(k):
            nxt = int(self._next(
                self.params,
                self._jnp.asarray([toks[-self.window:]], self._jnp.int32)))
            out.append(nxt)
            toks.append(nxt)
        return out


def resolve_draft_proposer(spec) -> DraftProposer:
    """Engine-side resolution of the ``draft_proposer=`` argument:
    ``"ngram"`` (the CPU-cheap default), or any DraftProposer
    instance."""
    if isinstance(spec, DraftProposer):
        return spec
    if spec == "ngram":
        return NgramProposer()
    raise ValueError(
        f"unknown draft proposer {spec!r}: pass 'ngram' or a "
        f"DraftProposer instance")
