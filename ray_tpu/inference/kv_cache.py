"""Paged KV cache: fixed-size blocks in a preallocated device pool.

The pool is [n_layers, num_blocks, block_size, kv_heads, head_dim] per
K and V (one allocation for the engine's lifetime — no per-request HBM
churn).  Each live sequence owns an ordered list of block ids; the
per-lane block tables map logical context positions onto pool blocks so
sequences of wildly different lengths pack the same pool with at most
block_size - 1 wasted slots each (the vLLM memory model).  Allocation
and free are host-side free-list operations; the device arrays are
functional — the jitted step returns updated pools and the cache rebinds
them (donated on TPU, so the update is in place).
"""

from __future__ import annotations

import math
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np


class BlockAllocator:
    """Free-list over pool block ids.  No implicit growth: exhaustion
    raises, and the scheduler's admission control is built on can_alloc
    — a sequence is only admitted when its prompt fits."""

    def __init__(self, num_blocks: int):
        if num_blocks < 1:
            raise ValueError("need at least one block")
        self.num_blocks = num_blocks
        # LIFO: recently-freed blocks are re-used first (their pool slots
        # are warm in HBM caches on real hardware).
        self._free: List[int] = list(range(num_blocks - 1, -1, -1))
        self._allocated = [False] * num_blocks

    @property
    def num_free(self) -> int:
        return len(self._free)

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    def alloc(self, n: int = 1) -> List[int]:
        if n > len(self._free):
            raise RuntimeError(
                f"KV pool exhausted: want {n} blocks, {len(self._free)} free")
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._allocated[b] = True
        return out

    def free(self, blocks: List[int]) -> None:
        for b in blocks:
            if not self._allocated[b]:
                raise ValueError(f"double free of block {b}")
            self._allocated[b] = False
            self._free.append(b)


class PagedKVCache:
    """Device pools + per-lane block tables for a fixed lane capacity.

    Host state (numpy block tables, sequence lengths, the allocator) is
    mirrored to device lazily: `device_tables()` re-uploads only after a
    host-side mutation, so steady-state decode ships two tiny arrays per
    step at most.
    """

    def __init__(self, n_layers: int, kv_heads: int, head_dim: int, *,
                 num_blocks: int, block_size: int, max_lanes: int,
                 max_seq_len: int, dtype=jnp.float32):
        self.block_size = block_size
        self.max_lanes = max_lanes
        self.max_seq_len = max_seq_len
        self.max_blocks_per_seq = math.ceil(max_seq_len / block_size)
        shape = (n_layers, num_blocks, block_size, kv_heads, head_dim)
        self.k = jnp.zeros(shape, dtype)
        self.v = jnp.zeros(shape, dtype)
        self.allocator = BlockAllocator(num_blocks)
        # Unused table entries stay 0 — always a valid pool index; the
        # attention mask (positions >= ctx_len) hides whatever lives there.
        self.block_tables = np.zeros((max_lanes, self.max_blocks_per_seq),
                                     np.int32)
        self.seq_lens = np.zeros((max_lanes,), np.int32)
        self._lane_blocks: List[List[int]] = [[] for _ in range(max_lanes)]
        self._dev_tables: Optional[jax.Array] = None

    @classmethod
    def for_model(cls, model, config, **kw) -> "PagedKVCache":
        """Build a cache shaped for a models/ module (gpt or llama)."""
        kv_heads = getattr(config, "n_kv_heads", config.n_heads)
        kw.setdefault("max_seq_len", config.max_seq_len)
        kw.setdefault("dtype", config.dtype)
        return cls(config.n_layers, kv_heads, config.head_dim, **kw)

    # ---------------- host-side lane lifecycle ----------------

    def blocks_needed(self, seq_len: int) -> int:
        return math.ceil(max(seq_len, 1) / self.block_size)

    def can_admit(self, prompt_len: int) -> bool:
        return self.allocator.can_alloc(self.blocks_needed(prompt_len))

    def alloc_lane(self, lane: int, prompt_len: int) -> None:
        """Sequence start: claim blocks covering the prompt."""
        if self._lane_blocks[lane]:
            raise ValueError(f"lane {lane} already allocated")
        if prompt_len > self.max_seq_len:
            raise ValueError(f"prompt of {prompt_len} exceeds max_seq_len "
                             f"{self.max_seq_len}")
        blocks = self.allocator.alloc(self.blocks_needed(prompt_len))
        self._lane_blocks[lane] = blocks
        self.block_tables[lane, :len(blocks)] = blocks
        self.seq_lens[lane] = 0
        self._dev_tables = None

    def ensure_capacity(self, lane: int, new_len: int) -> None:
        """Grow the lane's table as decode crosses block boundaries."""
        if new_len > self.max_seq_len:
            raise RuntimeError(f"lane {lane} exceeded max_seq_len")
        need = self.blocks_needed(new_len)
        blocks = self._lane_blocks[lane]
        while len(blocks) < need:
            (b,) = self.allocator.alloc(1)
            self.block_tables[lane, len(blocks)] = b
            blocks.append(b)
            self._dev_tables = None

    def free_lane(self, lane: int) -> None:
        """Sequence finish: return every block to the pool."""
        blocks = self._lane_blocks[lane]
        if blocks:
            self.allocator.free(blocks)
        self._lane_blocks[lane] = []
        self.block_tables[lane, :] = 0
        self.seq_lens[lane] = 0
        self._dev_tables = None

    def lane_blocks(self, lane: int) -> List[int]:
        return list(self._lane_blocks[lane])

    # ---------------- device mirrors ----------------

    def device_tables(self) -> jax.Array:
        if self._dev_tables is None:
            self._dev_tables = jnp.asarray(self.block_tables)
        return self._dev_tables

    def update_pools(self, k: jax.Array, v: jax.Array) -> None:
        """Rebind the functional pools returned by a jitted step."""
        self.k = k
        self.v = v
