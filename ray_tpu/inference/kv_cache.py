"""Paged KV cache: fixed-size blocks in a preallocated device pool.

The pool is [n_layers, num_blocks, block_size, kv_heads, head_dim] per
K and V (one allocation for the engine's lifetime — no per-request HBM
churn).  Each live sequence owns an ordered list of block ids; the
per-lane block tables map logical context positions onto pool blocks so
sequences of wildly different lengths pack the same pool with at most
block_size - 1 wasted slots each (the vLLM memory model).  Allocation
and free are host-side refcount operations; the device arrays are
functional — the jitted step returns updated pools and the cache rebinds
them (donated on TPU, so the update is in place).

Prefix caching (content-addressed block sharing): a block that has been
completely written ("sealed") is indexed by a hash chain over
(parent_hash, block_tokens) — the chain hash of a block is a function of
every token up to and including its own, and K/V at a position depend on
exactly that token prefix, so two sequences whose prefixes agree
block-for-block may share the physical blocks.  Sealed blocks are
immutable (decode writes always land at positions past the sealed
boundary, i.e. in each lane's private tail), so copy-on-write semantics
come for free.  When a sequence finishes, its sealed blocks stay in the
index at refcount 0 on an LRU list and are evicted only when the
allocator needs the space; a new request reuses the longest
block-aligned cached prefix instead of re-prefilling it.
"""

from __future__ import annotations

import collections
import math
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Root of every hash chain (a block with no parent).
_ROOT_HASH = 0


def chain_hashes(tokens: Sequence[int], block_size: int) -> List[int]:
    """Cumulative chain hash of every block-aligned prefix of `tokens`,
    in the exact convention the prefix index uses (`hash((parent,
    block_tokens))`, root 0) and with the same one-token-left cap as
    match_prefix.  Tuple-of-int hashing is deterministic across
    processes (PYTHONHASHSEED randomizes str/bytes only), so a router
    can score replica summaries against a request without shipping
    tokens."""
    out: List[int] = []
    parent = _ROOT_HASH
    for i in range((len(tokens) - 1) // block_size):
        parent = hash((parent, tuple(int(t) for t in
                                     tokens[i * block_size:
                                            (i + 1) * block_size])))
        out.append(parent)
    return out


class BlockAllocator:
    """Refcounted free-list over pool block ids.

    Three states per block: free (no content), live (refcount >= 1) and
    evictable (refcount 0 but still holding indexed cached content —
    reusable without recompute, reclaimable under pressure).  `num_free`
    counts free + evictable: both are available capacity, and the
    scheduler's admission control is built on can_alloc — a sequence is
    only admitted when its prompt fits.  No implicit growth: exhaustion
    raises.
    """

    def __init__(self, num_blocks: int,
                 on_evict: Optional[Callable[[int], None]] = None):
        if num_blocks < 1:
            raise ValueError("need at least one block")
        self.num_blocks = num_blocks
        # LIFO: recently-freed blocks are re-used first (their pool slots
        # are warm in HBM caches on real hardware).
        self._free: List[int] = list(range(num_blocks - 1, -1, -1))
        self._ref = [0] * num_blocks
        self._cached = [False] * num_blocks   # block holds indexed content
        # refcount-0 cached blocks, insertion order = LRU eviction order.
        self._evictable: "collections.OrderedDict[int, None]" = \
            collections.OrderedDict()
        self.on_evict = on_evict
        self.evictions = 0

    @property
    def num_free(self) -> int:
        return len(self._free) + len(self._evictable)

    def can_alloc(self, n: int) -> bool:
        return n <= self.num_free

    def alloc(self, n: int = 1) -> List[int]:
        if n > self.num_free:
            raise RuntimeError(
                f"KV pool exhausted: want {n} blocks, {self.num_free} free")
        out = []
        for _ in range(n):
            if self._free:
                b = self._free.pop()
            else:
                # Reclaim the least-recently-used cached block; the index
                # owner drops its entry via the eviction hook.
                b, _ = self._evictable.popitem(last=False)
                self._cached[b] = False
                self.evictions += 1
                if self.on_evict is not None:
                    self.on_evict(b)
            self._ref[b] = 1
            out.append(b)
        return out

    def incref(self, block: int) -> None:
        """Take a share of a cached block (prefix reuse)."""
        if self._ref[block] == 0:
            if block not in self._evictable:
                raise ValueError(f"incref of free block {block}")
            del self._evictable[block]
        self._ref[block] += 1

    def decref(self, block: int) -> None:
        """Drop one share.  At refcount 0 an indexed block parks on the
        LRU evictable list (content stays reusable); anything else goes
        straight back to the free list."""
        if self._ref[block] <= 0:
            raise ValueError(f"double free of block {block}")
        self._ref[block] -= 1
        if self._ref[block] == 0:
            if self._cached[block]:
                self._evictable[block] = None    # most-recently-used end
            else:
                self._free.append(block)

    def free(self, blocks: Sequence[int]) -> None:
        for b in blocks:
            self.decref(b)

    def refcount(self, block: int) -> int:
        return self._ref[block]

    def is_evictable(self, block: int) -> bool:
        return block in self._evictable

    def mark_cached(self, block: int) -> None:
        """The prefix index now references this block's content."""
        self._cached[block] = True

    def uncache(self, block: int) -> None:
        """The prefix index dropped this block; if it was parked
        evictable it becomes plain free."""
        self._cached[block] = False
        if block in self._evictable:
            del self._evictable[block]
            self._free.append(block)


class PagedKVCache:
    """Device pools + per-lane block tables for a fixed lane capacity.

    Host state (numpy block tables, sequence lengths, the allocator, the
    prefix index) is mirrored to device lazily: `device_tables()`
    re-uploads only after a host-side mutation, so steady-state decode
    ships two tiny arrays per step at most.
    """

    def __init__(self, n_layers: int, kv_heads: int, head_dim: int, *,
                 num_blocks: int, block_size: int, max_lanes: int,
                 max_seq_len: int, dtype=jnp.float32,
                 prefix_cache: bool = True):
        self.block_size = block_size
        self.max_lanes = max_lanes
        self.max_seq_len = max_seq_len
        self.max_blocks_per_seq = math.ceil(max_seq_len / block_size)
        shape = (n_layers, num_blocks, block_size, kv_heads, head_dim)
        self.k = jnp.zeros(shape, dtype)
        self.v = jnp.zeros(shape, dtype)
        self.allocator = BlockAllocator(num_blocks, on_evict=self._on_evict)
        # Unused table entries stay 0 — always a valid pool index; the
        # attention mask (positions >= ctx_len) hides whatever lives there.
        self.block_tables = np.zeros((max_lanes, self.max_blocks_per_seq),
                                     np.int32)
        self.seq_lens = np.zeros((max_lanes,), np.int32)
        self._lane_blocks: List[List[int]] = [[] for _ in range(max_lanes)]
        self._dev_tables: Optional[jax.Array] = None
        # ---- prefix index (content-addressed sealed blocks) ----
        self.prefix_cache_enabled = prefix_cache
        # (parent_chain_hash, block_tokens) -> block id.  Keys compare by
        # equality, so within one chain level collisions are impossible;
        # the int parent hash aliasing two distinct prefixes is the usual
        # 64-bit-hash-chain gamble (vLLM makes the same one).
        self._index: Dict[Tuple[int, Tuple[int, ...]], int] = {}
        self._block_key: Dict[int, Tuple[int, Tuple[int, ...]]] = {}
        self._lane_sealed = [0] * max_lanes     # sealed block count per lane
        self._lane_parent = [_ROOT_HASH] * max_lanes   # chain hash cursor
        self.stats = {"hit_tokens": 0, "miss_tokens": 0, "hits": 0,
                      "misses": 0, "sealed_blocks": 0, "imported_blocks": 0,
                      "restored_blocks": 0}
        # Optional tiered spill cache (serve/kv_tier): evicted sealed
        # blocks move here instead of being destroyed, and the match /
        # adopt path restores them on hit (the SPILLED index state).
        self.tier = None

    def attach_tier(self, tier) -> None:
        """Attach a spill tier (duck-typed: contains/put/pop/discard/
        summary_hashes/__len__).  Evictions start spilling immediately;
        match/adopt start seeing spilled chains."""
        self.tier = tier

    @classmethod
    def for_model(cls, model, config, **kw) -> "PagedKVCache":
        """Build a cache shaped for a models/ module (gpt or llama)."""
        kv_heads = getattr(config, "n_kv_heads", config.n_heads)
        kw.setdefault("max_seq_len", config.max_seq_len)
        kw.setdefault("dtype", config.dtype)
        return cls(config.n_layers, kv_heads, config.head_dim, **kw)

    # ---------------- host-side lane lifecycle ----------------

    def blocks_needed(self, seq_len: int) -> int:
        return math.ceil(max(seq_len, 1) / self.block_size)

    def can_admit(self, prompt_len: int) -> bool:
        return self.allocator.can_alloc(self.blocks_needed(prompt_len))

    def alloc_lane(self, lane: int, prompt_len: int) -> None:
        """Sequence start without prefix reuse: claim fresh blocks
        covering the prompt."""
        if self._lane_blocks[lane]:
            raise ValueError(f"lane {lane} already allocated")
        if prompt_len > self.max_seq_len:
            raise ValueError(f"prompt of {prompt_len} exceeds max_seq_len "
                             f"{self.max_seq_len}")
        blocks = self.allocator.alloc(self.blocks_needed(prompt_len))
        self._install_lane(lane, blocks, cached_len=0)

    def _install_lane(self, lane: int, blocks: List[int],
                      cached_len: int) -> None:
        self._lane_blocks[lane] = blocks
        self.block_tables[lane, :len(blocks)] = blocks
        self.seq_lens[lane] = cached_len
        self._lane_sealed[lane] = cached_len // self.block_size
        self._lane_parent[lane] = _ROOT_HASH
        self._dev_tables = None

    # ---------------- prefix cache ----------------

    def match_prefix(self, tokens: Sequence[int]) -> List[int]:
        """Longest chain of cached sealed blocks covering a block-aligned
        prefix of `tokens`, capped so at least one prompt token is always
        left to prefill (its logits seed the first sampled token).  Pure
        lookup — takes no references.  Device blocks only; spilled chain
        nodes (see `_match_chain`) do not appear here."""
        if not self.prefix_cache_enabled:
            return []
        out: List[int] = []
        for kind, _key, block in self._match_chain(tokens):
            if kind != "dev":
                break
            out.append(block)
        return out

    def _match_chain(self, tokens: Sequence[int]) -> List[Tuple]:
        """Longest cached chain covering a block-aligned prefix of
        `tokens`, walking THROUGH the spill tier: each entry is
        ("dev", key, block) for a device-resident sealed block or
        ("tier", key, None) for a spilled one (restorable on adopt).  A
        device child behind a spilled parent is reachable again — the
        chain is content-addressed, so the restored parent revalidates
        it by construction."""
        if not self.prefix_cache_enabled:
            return []
        bs = self.block_size
        out: List[Tuple] = []
        parent = _ROOT_HASH
        for i in range((len(tokens) - 1) // bs):
            key = (parent, tuple(int(t) for t in tokens[i * bs:(i + 1) * bs]))
            block = self._index.get(key)
            if block is not None:
                out.append(("dev", key, block))
            elif self.tier is not None and self.tier.contains(key):
                out.append(("tier", key, None))
            else:
                break
            parent = hash(key)
        return out

    def can_admit_prefix(self, tokens: Sequence[int],
                         headroom_blocks: int = 0) -> bool:
        """Admission check that accounts for reuse: device-matched blocks
        are referenced (not allocated), but matched blocks currently
        parked evictable stop counting as free capacity once taken.
        Spilled matches still cost an allocation (they restore into
        fresh blocks), so they stay inside `need`."""
        dev = [b for kind, _k, b in self._match_chain(tokens)
               if kind == "dev"]
        need = (self.blocks_needed(len(tokens)) - len(dev)
                + headroom_blocks)
        free_after = (self.allocator.num_free
                      - sum(self.allocator.is_evictable(b) for b in dev))
        return need <= free_after

    def adopt_prefix(self, lane: int, tokens: Sequence[int]) -> int:
        """Sequence start with prefix reuse: take shares of the longest
        cached prefix chain (restoring any spilled links from the tier),
        allocate fresh blocks for the rest of the prompt, and report how
        many context tokens came from the cache (the engine skips
        prefilling them)."""
        if self._lane_blocks[lane]:
            raise ValueError(f"lane {lane} already allocated")
        if len(tokens) > self.max_seq_len:
            raise ValueError(f"prompt of {len(tokens)} exceeds max_seq_len "
                             f"{self.max_seq_len}")
        entries = self._match_chain(tokens)
        # Pop spilled payloads out of the tier FIRST: once held here,
        # the allocations below can spill other blocks into the tier
        # without LRU pressure dropping the very chain being restored.
        # A pop that misses (aged out since the match) truncates the
        # usable chain at the hole — later links have no K/V under them.
        restores: List[Tuple] = []      # (chain_pos, key, (k_np, v_np))
        usable = len(entries)
        for pos, (kind, key, _b) in enumerate(entries):
            if kind != "tier":
                continue
            payload = self.tier.pop(key)
            if payload is None:
                usable = pos
                break
            restores.append((pos, key, payload))
        entries = entries[:usable]
        restores = [r for r in restores if r[0] < usable]
        dev_blocks = [b for kind, _k, b in entries if kind == "dev"]
        # Take the device shares FIRST so the fresh allocation below can
        # never evict a block this very request is about to reuse.
        for b in dev_blocks:
            self.allocator.incref(b)
        try:
            fresh = self.allocator.alloc(
                self.blocks_needed(len(tokens)) - len(dev_blocks))
        except RuntimeError:
            for b in dev_blocks:
                self.allocator.decref(b)
            for _pos, key, (k_np, v_np) in restores:
                self.tier.put(key, k_np, v_np)   # undo the pops
            raise
        # Assemble the lane's block list in chain order: device hits
        # keep their blocks, spilled hits consume fresh blocks (their
        # contents scatter in below), the prompt tail takes the rest.
        fresh_iter = iter(fresh)
        chain_blocks: List[int] = []
        restored: List[Tuple] = []      # (block, chain_pos, key)
        for pos, (kind, key, b) in enumerate(entries):
            if kind == "dev":
                chain_blocks.append(b)
            else:
                nb = next(fresh_iter)
                chain_blocks.append(nb)
                restored.append((nb, pos, key))
        tail = list(fresh_iter)
        if restored:
            idx = jnp.asarray(np.asarray([b for b, _p, _k in restored],
                                         np.int32))
            kstack = np.stack([restores[i][2][0]
                               for i in range(len(restores))], axis=1)
            vstack = np.stack([restores[i][2][1]
                               for i in range(len(restores))], axis=1)
            self.k = self.k.at[:, idx].set(jnp.asarray(kstack))
            self.v = self.v.at[:, idx].set(jnp.asarray(vstack))
            for nb, _pos, key in restored:
                # Restored blocks re-enter the device index (live now,
                # evictable again once the lane lets go).
                self._index[key] = nb
                self._block_key[nb] = key
                self.allocator.mark_cached(nb)
                self.stats["restored_blocks"] += 1
        cached = chain_blocks
        cached_len = len(cached) * self.block_size
        self._install_lane(lane, cached + tail, cached_len)
        self._lane_parent[lane] = _ROOT_HASH
        if cached:
            # Rebuild the chain cursor at the sealed boundary so blocks
            # sealed later extend the same chain.
            parent = _ROOT_HASH
            bs = self.block_size
            for i in range(len(cached)):
                parent = hash((parent,
                               tuple(int(t) for t in
                                     tokens[i * bs:(i + 1) * bs])))
            self._lane_parent[lane] = parent
            self.stats["hits"] += 1
            self.stats["hit_tokens"] += cached_len
        else:
            self.stats["misses"] += 1
        self.stats["miss_tokens"] += len(tokens) - cached_len
        return cached_len

    def seal_full_blocks(self, lane: int, tokens: Sequence[int]) -> None:
        """Index every newly-full block of this lane.  `tokens` is the
        lane's full token sequence (prompt + generated); only the first
        seq_lens[lane] of them have K/V in the pool, and a block seals
        the moment the write cursor crosses its end — mid-prefill too,
        so a concurrent identical prompt can start reusing the prefix
        before the first request even finishes."""
        if not self.prefix_cache_enabled:
            return
        bs = self.block_size
        full = int(self.seq_lens[lane]) // bs
        blocks = self._lane_blocks[lane]
        while self._lane_sealed[lane] < full:
            i = self._lane_sealed[lane]
            key = (self._lane_parent[lane],
                   tuple(int(t) for t in tokens[i * bs:(i + 1) * bs]))
            block = blocks[i]
            # First writer wins: if an identical block is already indexed
            # this one stays un-indexed freight (freed normally later);
            # an adopted shared block re-seals as itself (no-op).
            if key not in self._index and block not in self._block_key:
                self._index[key] = block
                self._block_key[block] = key
                self.allocator.mark_cached(block)
                self.stats["sealed_blocks"] += 1
                if self.tier is not None:
                    # Re-sealed on device: the spilled copy is stale
                    # freight now (content-addressed, so identical).
                    self.tier.discard(key)
            self._lane_parent[lane] = hash(key)
            self._lane_sealed[lane] += 1

    def _on_evict(self, block: int) -> None:
        """Allocator reclaimed a cached block: drop its index entry —
        spilling the content into the attached tier first, so the chain
        link survives eviction in SPILLED state.  Children of the
        evicted chain node stay indexed; with a tier they remain
        reachable THROUGH the spilled parent, without one they are
        unreachable until an identical parent is re-sealed — at which
        point they are valid again by construction (content-addressed,
        not block-addressed)."""
        key = self._block_key.pop(block, None)
        if key is not None and self._index.get(key) == block:
            del self._index[key]
            if self.tier is not None:
                self.tier.put(key, np.asarray(self.k[:, block]),
                              np.asarray(self.v[:, block]))

    @property
    def num_indexed_blocks(self) -> int:
        return len(self._index)

    # ---------------- disaggregated handoff / summaries ----------------

    def export_prefix(self, tokens: Sequence[int]) -> Optional[dict]:
        """Snapshot the longest DEVICE-cached chain covering a
        block-aligned prefix of `tokens` as a codec payload: chain
        token-blocks plus gathered K/V contents, enough for a foreign
        cache to rebuild the same content-addressed links.  None when
        nothing is cached."""
        entries = []
        for kind, key, block in self._match_chain(tokens):
            if kind != "dev":
                break           # spilled links don't ship (restore is local)
            entries.append((key, block))
        if not entries:
            return None
        idx = jnp.asarray(np.asarray([b for _k, b in entries], np.int32))
        return {
            "v": 1,
            "block_size": self.block_size,
            "chain": [list(key[1]) for key, _b in entries],
            "k": np.asarray(self.k[:, idx]),
            "v_pool": np.asarray(self.v[:, idx]),
        }

    def install_prefix(self, payload: dict) -> int:
        """Adopt foreign sealed blocks (the prefill→decode handoff): for
        each shipped chain node not already present locally, allocate a
        block, scatter the shipped K/V in, and index it at refcount 0
        (evictable) — a subsequent adopt_prefix on the same prompt then
        takes shares exactly as if the blocks had been sealed here.
        Content-addressed and idempotent: repeating the import after a
        failover is a no-op for links already present.  Returns how many
        blocks were installed."""
        if not self.prefix_cache_enabled or not payload:
            return 0
        if payload.get("v") != 1 or payload.get("block_size") != \
                self.block_size:
            return 0
        k_arr, v_arr = payload["k"], payload["v_pool"]
        if tuple(k_arr.shape[2:]) != tuple(self.k.shape[2:]) or \
                k_arr.shape[0] != self.k.shape[0]:
            return 0            # foreign model shape: refuse quietly
        parent = _ROOT_HASH
        new = []                # (chain_pos, key, block)
        for i, blk_tokens in enumerate(payload["chain"]):
            key = (parent, tuple(int(t) for t in blk_tokens))
            present = (key in self._index
                       or (self.tier is not None
                           and self.tier.contains(key)))
            if not present:
                try:
                    # May evict LRU cached blocks (new prefix beats old)
                    # but never steals live capacity: alloc raises only
                    # when everything is referenced, and we stop there.
                    (b,) = self.allocator.alloc(1)
                except RuntimeError:
                    break
                new.append((i, key, b))
            parent = hash(key)
        if not new:
            return 0
        idx = jnp.asarray(np.asarray([b for _i, _k, b in new], np.int32))
        pos = np.asarray([i for i, _k, _b in new])
        self.k = self.k.at[:, idx].set(jnp.asarray(k_arr[:, pos]))
        self.v = self.v.at[:, idx].set(jnp.asarray(v_arr[:, pos]))
        # Index + park evictable only AFTER every alloc: the blocks stay
        # at refcount 1 through the loop above so a later alloc in the
        # same import can never reclaim an earlier install.
        for _i, key, b in new:
            self._index[key] = b
            self._block_key[b] = key
            self.allocator.mark_cached(b)
            self.allocator.decref(b)
            self.stats["imported_blocks"] += 1
        return len(new)

    def prefix_summary(self, limit: int = 256) -> dict:
        """Compact routing summary: the cumulative chain hashes of every
        sealed block this cache can serve (device index + spill tier),
        newest last, capped at `limit`.  A router holding the request's
        own chain hashes scores this replica by deepest overlap without
        ever shipping tokens."""
        hashes = [hash(k) for k in self._block_key.values()]
        if self.tier is not None:
            hashes.extend(self.tier.summary_hashes())
        # Order-preserving dedup; newest sealed blocks win the cap.
        hashes = list(dict.fromkeys(hashes))[-max(int(limit), 1):]
        return {
            "v": 1,
            "block_size": self.block_size,
            "hashes": hashes,
            "indexed_blocks": len(self._index),
            "tier_blocks": 0 if self.tier is None else len(self.tier),
        }

    # ---------------- lane growth / teardown ----------------

    def ensure_capacity(self, lane: int, new_len: int) -> None:
        """Grow the lane's table as decode crosses block boundaries."""
        if new_len > self.max_seq_len:
            raise RuntimeError(f"lane {lane} exceeded max_seq_len")
        need = self.blocks_needed(new_len)
        blocks = self._lane_blocks[lane]
        while len(blocks) < need:
            (b,) = self.allocator.alloc(1)
            self.block_tables[lane, len(blocks)] = b
            blocks.append(b)
            self._dev_tables = None

    def truncate_lane(self, lane: int, new_len: int) -> None:
        """Speculative rollback: release the table-tail blocks past what
        ``new_len`` committed tokens need.  Rejected draft tokens were
        written at positions >= the committed length; their K/V is
        garbage the attention mask already hides (positions >= ctx_len
        never get attended, and real tokens overwrite those slots before
        the context grows across them), so rollback is pure block
        accounting.  Only wholly-uncommitted tail blocks are released —
        they are always fresh, exclusively-owned allocations (shared
        prefix blocks live at the front of the table, and the sealed
        boundary never passes the committed length), so decref returns
        them straight to the free list."""
        blocks = self._lane_blocks[lane]
        keep = max(self.blocks_needed(new_len), self._lane_sealed[lane])
        while len(blocks) > keep:
            b = blocks.pop()
            self.allocator.decref(b)
            self.block_tables[lane, len(blocks)] = 0
            self._dev_tables = None

    def free_lane(self, lane: int) -> None:
        """Sequence finish: drop this lane's share of every block.
        Sealed+indexed blocks whose refcount hits 0 park on the LRU
        evictable list (warm for the next matching prefix); everything
        else returns to the free list."""
        blocks = self._lane_blocks[lane]
        for b in blocks:
            self.allocator.decref(b)
        self._lane_blocks[lane] = []
        self.block_tables[lane, :] = 0
        self.seq_lens[lane] = 0
        self._lane_sealed[lane] = 0
        self._lane_parent[lane] = _ROOT_HASH
        self._dev_tables = None

    def lane_blocks(self, lane: int) -> List[int]:
        return list(self._lane_blocks[lane])

    # ---------------- device mirrors ----------------

    def device_tables(self) -> jax.Array:
        if self._dev_tables is None:
            self._dev_tables = jnp.asarray(self.block_tables)
        return self._dev_tables

    def update_pools(self, k: jax.Array, v: jax.Array) -> None:
        """Rebind the functional pools returned by a jitted step."""
        self.k = k
        self.v = v
