"""Paged KV cache: fixed-size blocks in a preallocated device pool.

The pool is [n_layers, num_blocks, block_size, kv_heads, head_dim] per
K and V (one allocation for the engine's lifetime — no per-request HBM
churn).  Each live sequence owns an ordered list of block ids; the
per-lane block tables map logical context positions onto pool blocks so
sequences of wildly different lengths pack the same pool with at most
block_size - 1 wasted slots each (the vLLM memory model).  Allocation
and free are host-side refcount operations; the device arrays are
functional — the jitted step returns updated pools and the cache rebinds
them (donated on TPU, so the update is in place).

Prefix caching (content-addressed block sharing): a block that has been
completely written ("sealed") is indexed by a hash chain over
(parent_hash, block_tokens) — the chain hash of a block is a function of
every token up to and including its own, and K/V at a position depend on
exactly that token prefix, so two sequences whose prefixes agree
block-for-block may share the physical blocks.  Sealed blocks are
immutable (decode writes always land at positions past the sealed
boundary, i.e. in each lane's private tail), so copy-on-write semantics
come for free.  When a sequence finishes, its sealed blocks stay in the
index at refcount 0 on an LRU list and are evicted only when the
allocator needs the space; a new request reuses the longest
block-aligned cached prefix instead of re-prefilling it.
"""

from __future__ import annotations

import collections
import math
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Root of every hash chain (a block with no parent).
_ROOT_HASH = 0


class BlockAllocator:
    """Refcounted free-list over pool block ids.

    Three states per block: free (no content), live (refcount >= 1) and
    evictable (refcount 0 but still holding indexed cached content —
    reusable without recompute, reclaimable under pressure).  `num_free`
    counts free + evictable: both are available capacity, and the
    scheduler's admission control is built on can_alloc — a sequence is
    only admitted when its prompt fits.  No implicit growth: exhaustion
    raises.
    """

    def __init__(self, num_blocks: int,
                 on_evict: Optional[Callable[[int], None]] = None):
        if num_blocks < 1:
            raise ValueError("need at least one block")
        self.num_blocks = num_blocks
        # LIFO: recently-freed blocks are re-used first (their pool slots
        # are warm in HBM caches on real hardware).
        self._free: List[int] = list(range(num_blocks - 1, -1, -1))
        self._ref = [0] * num_blocks
        self._cached = [False] * num_blocks   # block holds indexed content
        # refcount-0 cached blocks, insertion order = LRU eviction order.
        self._evictable: "collections.OrderedDict[int, None]" = \
            collections.OrderedDict()
        self.on_evict = on_evict
        self.evictions = 0

    @property
    def num_free(self) -> int:
        return len(self._free) + len(self._evictable)

    def can_alloc(self, n: int) -> bool:
        return n <= self.num_free

    def alloc(self, n: int = 1) -> List[int]:
        if n > self.num_free:
            raise RuntimeError(
                f"KV pool exhausted: want {n} blocks, {self.num_free} free")
        out = []
        for _ in range(n):
            if self._free:
                b = self._free.pop()
            else:
                # Reclaim the least-recently-used cached block; the index
                # owner drops its entry via the eviction hook.
                b, _ = self._evictable.popitem(last=False)
                self._cached[b] = False
                self.evictions += 1
                if self.on_evict is not None:
                    self.on_evict(b)
            self._ref[b] = 1
            out.append(b)
        return out

    def incref(self, block: int) -> None:
        """Take a share of a cached block (prefix reuse)."""
        if self._ref[block] == 0:
            if block not in self._evictable:
                raise ValueError(f"incref of free block {block}")
            del self._evictable[block]
        self._ref[block] += 1

    def decref(self, block: int) -> None:
        """Drop one share.  At refcount 0 an indexed block parks on the
        LRU evictable list (content stays reusable); anything else goes
        straight back to the free list."""
        if self._ref[block] <= 0:
            raise ValueError(f"double free of block {block}")
        self._ref[block] -= 1
        if self._ref[block] == 0:
            if self._cached[block]:
                self._evictable[block] = None    # most-recently-used end
            else:
                self._free.append(block)

    def free(self, blocks: Sequence[int]) -> None:
        for b in blocks:
            self.decref(b)

    def refcount(self, block: int) -> int:
        return self._ref[block]

    def is_evictable(self, block: int) -> bool:
        return block in self._evictable

    def mark_cached(self, block: int) -> None:
        """The prefix index now references this block's content."""
        self._cached[block] = True

    def uncache(self, block: int) -> None:
        """The prefix index dropped this block; if it was parked
        evictable it becomes plain free."""
        self._cached[block] = False
        if block in self._evictable:
            del self._evictable[block]
            self._free.append(block)


class PagedKVCache:
    """Device pools + per-lane block tables for a fixed lane capacity.

    Host state (numpy block tables, sequence lengths, the allocator, the
    prefix index) is mirrored to device lazily: `device_tables()`
    re-uploads only after a host-side mutation, so steady-state decode
    ships two tiny arrays per step at most.
    """

    def __init__(self, n_layers: int, kv_heads: int, head_dim: int, *,
                 num_blocks: int, block_size: int, max_lanes: int,
                 max_seq_len: int, dtype=jnp.float32,
                 prefix_cache: bool = True):
        self.block_size = block_size
        self.max_lanes = max_lanes
        self.max_seq_len = max_seq_len
        self.max_blocks_per_seq = math.ceil(max_seq_len / block_size)
        shape = (n_layers, num_blocks, block_size, kv_heads, head_dim)
        self.k = jnp.zeros(shape, dtype)
        self.v = jnp.zeros(shape, dtype)
        self.allocator = BlockAllocator(num_blocks, on_evict=self._on_evict)
        # Unused table entries stay 0 — always a valid pool index; the
        # attention mask (positions >= ctx_len) hides whatever lives there.
        self.block_tables = np.zeros((max_lanes, self.max_blocks_per_seq),
                                     np.int32)
        self.seq_lens = np.zeros((max_lanes,), np.int32)
        self._lane_blocks: List[List[int]] = [[] for _ in range(max_lanes)]
        self._dev_tables: Optional[jax.Array] = None
        # ---- prefix index (content-addressed sealed blocks) ----
        self.prefix_cache_enabled = prefix_cache
        # (parent_chain_hash, block_tokens) -> block id.  Keys compare by
        # equality, so within one chain level collisions are impossible;
        # the int parent hash aliasing two distinct prefixes is the usual
        # 64-bit-hash-chain gamble (vLLM makes the same one).
        self._index: Dict[Tuple[int, Tuple[int, ...]], int] = {}
        self._block_key: Dict[int, Tuple[int, Tuple[int, ...]]] = {}
        self._lane_sealed = [0] * max_lanes     # sealed block count per lane
        self._lane_parent = [_ROOT_HASH] * max_lanes   # chain hash cursor
        self.stats = {"hit_tokens": 0, "miss_tokens": 0, "hits": 0,
                      "misses": 0, "sealed_blocks": 0}

    @classmethod
    def for_model(cls, model, config, **kw) -> "PagedKVCache":
        """Build a cache shaped for a models/ module (gpt or llama)."""
        kv_heads = getattr(config, "n_kv_heads", config.n_heads)
        kw.setdefault("max_seq_len", config.max_seq_len)
        kw.setdefault("dtype", config.dtype)
        return cls(config.n_layers, kv_heads, config.head_dim, **kw)

    # ---------------- host-side lane lifecycle ----------------

    def blocks_needed(self, seq_len: int) -> int:
        return math.ceil(max(seq_len, 1) / self.block_size)

    def can_admit(self, prompt_len: int) -> bool:
        return self.allocator.can_alloc(self.blocks_needed(prompt_len))

    def alloc_lane(self, lane: int, prompt_len: int) -> None:
        """Sequence start without prefix reuse: claim fresh blocks
        covering the prompt."""
        if self._lane_blocks[lane]:
            raise ValueError(f"lane {lane} already allocated")
        if prompt_len > self.max_seq_len:
            raise ValueError(f"prompt of {prompt_len} exceeds max_seq_len "
                             f"{self.max_seq_len}")
        blocks = self.allocator.alloc(self.blocks_needed(prompt_len))
        self._install_lane(lane, blocks, cached_len=0)

    def _install_lane(self, lane: int, blocks: List[int],
                      cached_len: int) -> None:
        self._lane_blocks[lane] = blocks
        self.block_tables[lane, :len(blocks)] = blocks
        self.seq_lens[lane] = cached_len
        self._lane_sealed[lane] = cached_len // self.block_size
        self._lane_parent[lane] = _ROOT_HASH
        self._dev_tables = None

    # ---------------- prefix cache ----------------

    def match_prefix(self, tokens: Sequence[int]) -> List[int]:
        """Longest chain of cached sealed blocks covering a block-aligned
        prefix of `tokens`, capped so at least one prompt token is always
        left to prefill (its logits seed the first sampled token).  Pure
        lookup — takes no references."""
        if not self.prefix_cache_enabled:
            return []
        bs = self.block_size
        out: List[int] = []
        parent = _ROOT_HASH
        for i in range((len(tokens) - 1) // bs):
            key = (parent, tuple(int(t) for t in tokens[i * bs:(i + 1) * bs]))
            block = self._index.get(key)
            if block is None:
                break
            out.append(block)
            parent = hash(key)
        return out

    def can_admit_prefix(self, tokens: Sequence[int],
                         headroom_blocks: int = 0) -> bool:
        """Admission check that accounts for reuse: matched blocks are
        referenced (not allocated), but matched blocks currently parked
        evictable stop counting as free capacity once taken."""
        matched = self.match_prefix(tokens)
        need = (self.blocks_needed(len(tokens)) - len(matched)
                + headroom_blocks)
        free_after = (self.allocator.num_free
                      - sum(self.allocator.is_evictable(b) for b in matched))
        return need <= free_after

    def adopt_prefix(self, lane: int, tokens: Sequence[int]) -> int:
        """Sequence start with prefix reuse: take shares of the longest
        cached prefix chain, allocate fresh blocks for the rest of the
        prompt, and report how many context tokens came from the cache
        (the engine skips prefilling them)."""
        if self._lane_blocks[lane]:
            raise ValueError(f"lane {lane} already allocated")
        if len(tokens) > self.max_seq_len:
            raise ValueError(f"prompt of {len(tokens)} exceeds max_seq_len "
                             f"{self.max_seq_len}")
        cached = self.match_prefix(tokens)
        # Take the shares FIRST so the fresh allocation below can never
        # evict a block this very request is about to reuse.
        for b in cached:
            self.allocator.incref(b)
        try:
            fresh = self.allocator.alloc(
                self.blocks_needed(len(tokens)) - len(cached))
        except RuntimeError:
            for b in cached:
                self.allocator.decref(b)
            raise
        cached_len = len(cached) * self.block_size
        self._install_lane(lane, cached + fresh, cached_len)
        self._lane_parent[lane] = _ROOT_HASH
        if cached:
            # Rebuild the chain cursor at the sealed boundary so blocks
            # sealed later extend the same chain.
            parent = _ROOT_HASH
            bs = self.block_size
            for i in range(len(cached)):
                parent = hash((parent,
                               tuple(int(t) for t in
                                     tokens[i * bs:(i + 1) * bs])))
            self._lane_parent[lane] = parent
            self.stats["hits"] += 1
            self.stats["hit_tokens"] += cached_len
        else:
            self.stats["misses"] += 1
        self.stats["miss_tokens"] += len(tokens) - cached_len
        return cached_len

    def seal_full_blocks(self, lane: int, tokens: Sequence[int]) -> None:
        """Index every newly-full block of this lane.  `tokens` is the
        lane's full token sequence (prompt + generated); only the first
        seq_lens[lane] of them have K/V in the pool, and a block seals
        the moment the write cursor crosses its end — mid-prefill too,
        so a concurrent identical prompt can start reusing the prefix
        before the first request even finishes."""
        if not self.prefix_cache_enabled:
            return
        bs = self.block_size
        full = int(self.seq_lens[lane]) // bs
        blocks = self._lane_blocks[lane]
        while self._lane_sealed[lane] < full:
            i = self._lane_sealed[lane]
            key = (self._lane_parent[lane],
                   tuple(int(t) for t in tokens[i * bs:(i + 1) * bs]))
            block = blocks[i]
            # First writer wins: if an identical block is already indexed
            # this one stays un-indexed freight (freed normally later);
            # an adopted shared block re-seals as itself (no-op).
            if key not in self._index and block not in self._block_key:
                self._index[key] = block
                self._block_key[block] = key
                self.allocator.mark_cached(block)
                self.stats["sealed_blocks"] += 1
            self._lane_parent[lane] = hash(key)
            self._lane_sealed[lane] += 1

    def _on_evict(self, block: int) -> None:
        """Allocator reclaimed a cached block: drop its index entry.
        Children of the evicted chain node stay indexed but unreachable
        until an identical parent is re-sealed — at which point they are
        valid again by construction (content-addressed, not
        block-addressed)."""
        key = self._block_key.pop(block, None)
        if key is not None and self._index.get(key) == block:
            del self._index[key]

    @property
    def num_indexed_blocks(self) -> int:
        return len(self._index)

    # ---------------- lane growth / teardown ----------------

    def ensure_capacity(self, lane: int, new_len: int) -> None:
        """Grow the lane's table as decode crosses block boundaries."""
        if new_len > self.max_seq_len:
            raise RuntimeError(f"lane {lane} exceeded max_seq_len")
        need = self.blocks_needed(new_len)
        blocks = self._lane_blocks[lane]
        while len(blocks) < need:
            (b,) = self.allocator.alloc(1)
            self.block_tables[lane, len(blocks)] = b
            blocks.append(b)
            self._dev_tables = None

    def truncate_lane(self, lane: int, new_len: int) -> None:
        """Speculative rollback: release the table-tail blocks past what
        ``new_len`` committed tokens need.  Rejected draft tokens were
        written at positions >= the committed length; their K/V is
        garbage the attention mask already hides (positions >= ctx_len
        never get attended, and real tokens overwrite those slots before
        the context grows across them), so rollback is pure block
        accounting.  Only wholly-uncommitted tail blocks are released —
        they are always fresh, exclusively-owned allocations (shared
        prefix blocks live at the front of the table, and the sealed
        boundary never passes the committed length), so decref returns
        them straight to the free list."""
        blocks = self._lane_blocks[lane]
        keep = max(self.blocks_needed(new_len), self._lane_sealed[lane])
        while len(blocks) > keep:
            b = blocks.pop()
            self.allocator.decref(b)
            self.block_tables[lane, len(blocks)] = 0
            self._dev_tables = None

    def free_lane(self, lane: int) -> None:
        """Sequence finish: drop this lane's share of every block.
        Sealed+indexed blocks whose refcount hits 0 park on the LRU
        evictable list (warm for the next matching prefix); everything
        else returns to the free list."""
        blocks = self._lane_blocks[lane]
        for b in blocks:
            self.allocator.decref(b)
        self._lane_blocks[lane] = []
        self.block_tables[lane, :] = 0
        self.seq_lens[lane] = 0
        self._lane_sealed[lane] = 0
        self._lane_parent[lane] = _ROOT_HASH
        self._dev_tables = None

    def lane_blocks(self, lane: int) -> List[int]:
        return list(self._lane_blocks[lane])

    # ---------------- device mirrors ----------------

    def device_tables(self) -> jax.Array:
        if self._dev_tables is None:
            self._dev_tables = jnp.asarray(self.block_tables)
        return self._dev_tables

    def update_pools(self, k: jax.Array, v: jax.Array) -> None:
        """Rebind the functional pools returned by a jitted step."""
        self.k = k
        self.v = v
