"""Continuous-batching generation engine over the paged KV cache.

One jitted step advances a fixed-capacity LANE array: every live
sequence owns a lane, new requests are admitted into lanes the moment
their previous occupant finishes (mid-flight — no batch barrier), and
padding lanes ride along masked.  Two compiled step shapes total — the
pure decode step (T=1, single-query paged attention — the Pallas kernel
path) and the prefill step (T=prefill_chunk) — and when both
populations are live they dispatch SEPARATELY each scheduler
iteration: decode lanes advance at T=1 cost instead of being charged a
whole prefill chunk of FLOPs just because some other lane is still
prefilling.

Admission rides the prefix cache (kv_cache.py): the longest
block-aligned cached prefix of a prompt is adopted by reference instead
of re-prefilled, so shared system prompts / few-shot templates /
multi-turn history cost their FLOPs once.  Newly-full blocks are sealed
into the content-addressed index as the write cursor crosses them —
mid-prefill included.

Sampling is part of the jitted step: greedy is argmax, temperature
sampling draws from a per-lane PRNG key folded from (request seed,
tokens produced), so sampled output is reproducible per request seed
regardless of batch composition, and the per-step device->host transfer
is one int32 per lane — never the [B, V] logits.

Speculative decoding (``spec_k > 0``, speculative.py) lifts the
one-token-per-step ceiling: a host-side draft proposer suggests up to k
continuation tokens per decode lane from the request's own history, the
step verifies all k+1 positions at once (the chunked-prefill dispatch
shape, per-position in-graph sampling with the SAME fold_in(seed,
produced+j) keys the plain step would use), the longest draft prefix
matching the model's own sampled output commits as one atomic burst,
and the rejected tail rolls back through paged-KV block truncation —
token-exact vs the non-speculative engine by construction, for greedy
and seeded sampling alike.

The engine is host-driven: block allocation, admission and stream
fan-out are Python; the model math (sampling included) is one jax.jit'ed
call per dispatched population with pools donated on TPU (in-place
cache update).
"""

from __future__ import annotations

import collections
import itertools
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.inference.kv_cache import PagedKVCache
from ray_tpu.util import events, spans
from ray_tpu.util.metrics import Counter, Gauge, Histogram

_DONE = object()

_MET = None

# SLO latency buckets: generation latencies live in the 1ms–60s range;
# sub-ms resolution at the low end keeps TBT percentiles meaningful.
_LATENCY_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


def _metrics() -> dict:
    global _MET
    if _MET is None:
        _MET = {
            "hit_tokens": Counter(
                "inference_prefix_hit_tokens",
                "Prompt tokens served from the KV prefix cache"),
            "miss_tokens": Counter(
                "inference_prefix_miss_tokens",
                "Prompt tokens prefilled from scratch"),
            "hits": Counter(
                "inference_prefix_hits",
                "Admissions that reused at least one cached block"),
            "misses": Counter(
                "inference_prefix_misses",
                "Admissions with no cached prefix"),
            "evicted": Counter(
                "inference_kv_blocks_evicted",
                "Cached KV blocks reclaimed under pool pressure"),
            "queue_depth": Gauge(
                "inference_waiting_requests",
                "Requests queued behind lane admission"),
            "ttft": Histogram(
                "inference_ttft_s",
                "Time to first token (submit -> first emit)",
                buckets=_LATENCY_BUCKETS),
            "tbt": Histogram(
                "inference_tbt_s",
                "Time between tokens (per-decode emit gap)",
                buckets=_LATENCY_BUCKETS),
            "spec_drafted": Counter(
                "inference_spec_drafted_tokens",
                "Draft tokens proposed for speculative verification"),
            "spec_accepted": Counter(
                "inference_spec_accepted_tokens",
                "Draft tokens accepted by the verify step"),
            "spec_steps": Counter(
                "inference_spec_steps",
                "Speculative verify dispatches"),
            "spec_per_step": Histogram(
                "inference_spec_tokens_per_step",
                "Tokens emitted per lane per speculative verify step "
                "(plain decode would be exactly 1)",
                buckets=(1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0)),
        }
    return _MET


@dataclass
class _Request:
    rid: int
    prompt: List[int]
    max_new_tokens: int
    temperature: float = 0.0
    eos_id: Optional[int] = None
    seed: int = 0
    out: "queue.Queue" = field(default_factory=queue.Queue)
    # Sampling-counter base: a request resumed after a mid-stream
    # failover re-prefills prompt+produced but must keep drawing from
    # fold_in(seed, OVERALL position) to stay seed-consistent with the
    # unfaulted run.
    sample_offset: int = 0
    deadline: Optional[float] = None   # monotonic; lane evicted past it
    # Flight-recorder / SLO bookkeeping: the trace context is captured at
    # submit() time because every later hop (scheduler thread, _commit)
    # runs outside the submitter's contextvars.
    trace: Optional[tuple] = None
    submitted: float = 0.0             # wall time of submit()
    last_emit: float = 0.0             # wall time of the previous token
    fed: int = 0            # prompt tokens in the cache (prefilled OR reused)
    produced: int = 0
    # Open engine span for TRACED requests only: the prefill span
    # (submit -> first token) until produced==1, then the current
    # inter-token decode span.  Untraced requests never pay for these.
    span_tok: object = None
    last_token: int = 0
    emitted: List[int] = field(default_factory=list)
    finish_reason: Optional[str] = None
    # Speculative state: the lane's current adaptive draft ceiling and
    # the draft tokens riding the in-flight verify dispatch.
    spec_k: int = 0
    draft: tuple = ()
    # Per-token behavior log-probs (capture_logp engines only), parallel
    # to `emitted` — the RL rollout path needs the sampling
    # distribution's log-prob of every committed token for V-trace.
    logps: List[float] = field(default_factory=list)
    # Disaggregated prefill: run chunked prefill + seal the prompt's
    # blocks, then finish WITHOUT sampling — the sealed chain is the
    # product (export_prefix ships it to a decode engine).
    prefill_only: bool = False

    @property
    def prefilling(self) -> bool:
        return self.fed < len(self.prompt)


class GenerationHandle:
    """Streaming view of one request: iterate to receive token ids as
    the engine emits them (the serve stream-ticket path pulls these)."""

    def __init__(self, req: _Request, engine: "InferenceEngine" = None):
        self._req = req
        self._engine = engine
        # A speculative burst arrives as ONE queue item (a list): the
        # commit is atomic — a consumer never observes a partially
        # delivered draft burst — and iteration unwraps it here.
        self._buf: collections.deque = collections.deque()

    def cancel(self) -> bool:
        """Abort the request: evict its engine lane (or dequeue it) and
        unblock any consumer with end-of-stream.  Idempotent; False if
        the request had already finished."""
        if self._engine is None:
            return False
        return self._engine.cancel(self._req)

    def __iter__(self):
        return self

    def __next__(self) -> int:
        if self._buf:
            return self._buf.popleft()
        item = self._req.out.get()
        if item is _DONE:
            raise StopIteration
        if isinstance(item, list):
            self._buf.extend(item)
            return self._buf.popleft()
        return item

    def tokens(self, timeout: Optional[float] = None) -> List[int]:
        """Block until the request finishes; returns all generated ids.

        `timeout` is an OVERALL deadline for the whole generation, not a
        per-token gap: if the request has not finished `timeout` seconds
        from this call, the request is CANCELLED (its lane evicted — a
        vanished consumer must not leave the engine generating for
        nobody) and TimeoutError is raised (never queue.Empty)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        out: List[int] = list(self._buf)
        self._buf.clear()
        while True:
            if deadline is None:
                item = self._req.out.get()
            else:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self.cancel()
                    raise TimeoutError(
                        f"generation did not finish within {timeout}s "
                        f"({len(out)} token(s) received)")
                try:
                    item = self._req.out.get(timeout=remaining)
                except queue.Empty:
                    self.cancel()
                    raise TimeoutError(
                        f"generation did not finish within {timeout}s "
                        f"({len(out)} token(s) received)") from None
            if item is _DONE:
                return out
            if isinstance(item, list):
                out.extend(item)
            else:
                out.append(item)

    @property
    def finish_reason(self) -> Optional[str]:
        return self._req.finish_reason

    @property
    def logps(self) -> List[float]:
        """Behavior log-probs of the committed tokens (parallel to the
        emitted stream).  Empty unless the engine was built with
        ``capture_logp=True``."""
        return list(self._req.logps)


def _resolve_model(model):
    if isinstance(model, str):
        if model == "gpt":
            from ray_tpu.models import gpt as mod
        elif model == "llama":
            from ray_tpu.models import llama as mod
        else:
            raise ValueError(f"unknown model family {model!r}")
        return mod
    return model  # a module implementing forward_cached/lm_head/CONFIGS


class InferenceEngine:
    """max_lanes concurrent sequences over one shared paged KV pool.

    `auto_start=True` (default) runs the scheduler on a daemon thread —
    submit() returns a streaming GenerationHandle immediately.  With
    auto_start=False the caller drives `step()` (deterministic tests,
    microbenchmarks).  `prefix_cache=False` disables content-addressed
    block reuse (every prompt prefills from token zero — the cold
    baseline bench_prefix.py measures against).

    `spec_k > 0` enables speculative decoding: `draft_proposer`
    (``"ngram"`` or a speculative.DraftProposer) suggests up to spec_k
    continuation tokens per decode lane and one verify dispatch commits
    the accepted prefix as a burst.  `spec_adaptive` backs each lane's
    draft length off when its acceptance is low (and grows it back on
    full acceptance) so incompressible streams stop paying rejected
    verify FLOPs.
    """

    def __init__(self, model="gpt", config="nano", params=None, *,
                 max_lanes: int = 8, block_size: int = 16,
                 num_blocks: Optional[int] = None,
                 max_seq_len: Optional[int] = None,
                 prefill_chunk: int = 32, seed: int = 0,
                 prefix_cache: bool = True, auto_start: bool = True,
                 spec_k: int = 0, draft_proposer="ngram",
                 spec_adaptive: bool = True,
                 kv_tier: Optional[bool] = None,
                 capture_logp: bool = False):
        self.model = _resolve_model(model)
        self.config = (self.model.CONFIGS[config] if isinstance(config, str)
                       else config)
        if params is None:
            params = self.model.init_params(self.config,
                                            jax.random.key(seed))
        self.params = params
        self.max_lanes = max_lanes
        self.prefill_chunk = prefill_chunk
        self.seed = seed
        max_seq_len = min(max_seq_len or self.config.max_seq_len,
                          self.config.max_seq_len)
        if num_blocks is None:
            num_blocks = max_lanes * -(-max_seq_len // block_size)
        self.cache = PagedKVCache.for_model(
            self.model, self.config, num_blocks=num_blocks,
            block_size=block_size, max_lanes=max_lanes,
            max_seq_len=max_seq_len, prefix_cache=prefix_cache)
        if kv_tier is None:
            from ray_tpu._private.config import GLOBAL_CONFIG
            kv_tier = bool(GLOBAL_CONFIG.kv_tier)
        if kv_tier and prefix_cache:
            # Runtime import: the tier lives with the serving subsystem
            # but depends only on util/, so the cycle never closes.
            from ray_tpu.serve.kv_tier.tier import KVTierCache
            self.cache.attach_tier(KVTierCache.from_config())
        self.spec_k = int(spec_k)
        self._spec_adaptive = bool(spec_adaptive)
        if self.spec_k > 0:
            from ray_tpu.inference.speculative import resolve_draft_proposer
            self._proposer = resolve_draft_proposer(draft_proposer)
        else:
            self._proposer = None
        self._spec_stats = {"drafted": 0, "accepted": 0, "emitted": 0,
                            "steps": 0, "bursts": 0}
        # RL rollout support: per-token behavior log-prob capture (the
        # step fns grow one [B(,T)] float32 output) and a policy version
        # stamp advanced by update_params().
        self._capture_logp = bool(capture_logp)
        self.policy_version = 0
        self._lanes: List[Optional[_Request]] = [None] * max_lanes
        self._waiting: "collections.deque[_Request]" = collections.deque()
        self._rid = itertools.count(1)
        self._step_fns: Dict = {}
        self._step_impls: Dict = {}   # un-jitted twins (shape introspection)
        self._evictions_reported = 0
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._thread: Optional[threading.Thread] = None
        self._stopped = False
        self._auto = auto_start

    # ---------------- public API ----------------

    def submit(self, prompt, max_new_tokens: int = 16, *,
               temperature: float = 0.0, eos_id: Optional[int] = None,
               seed: Optional[int] = None, sample_offset: int = 0,
               deadline_s: Optional[float] = None,
               prefill_only: bool = False) -> GenerationHandle:
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        vocab = self.config.vocab_size
        for t in prompt:
            if not 0 <= t < vocab:
                raise ValueError(
                    f"prompt token id {t} out of range for vocab_size "
                    f"{vocab}")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if len(prompt) > self.cache.max_seq_len:
            raise ValueError("prompt longer than max_seq_len")
        rid = next(self._rid)
        from ray_tpu.util import tracing
        req = _Request(rid=rid, prompt=prompt,
                       max_new_tokens=max_new_tokens,
                       temperature=temperature, eos_id=eos_id,
                       seed=seed if seed is not None else self.seed + rid,
                       sample_offset=int(sample_offset),
                       deadline=(None if deadline_s is None
                                 else time.monotonic() + deadline_s),
                       trace=tracing.current_context(),
                       submitted=time.time(),
                       spec_k=self.spec_k,
                       prefill_only=prefill_only)
        events.record("engine", "submit", trace=req.trace, rid=rid,
                      prompt_len=len(prompt), max_new=max_new_tokens)
        if req.trace is not None:
            # Prefill span: submit -> first emitted token (TTFT, queue
            # wait included).  _commit swaps it for per-token decode
            # spans once tokens flow.
            req.span_tok = spans.begin("engine", "prefill", ctx=req.trace,
                                       rid=rid, prompt_len=len(prompt))
        with self._work:
            if self._stopped:
                raise RuntimeError("engine is shut down")
            self._waiting.append(req)
            self._work.notify()
        if self._auto:
            self._ensure_thread()
        return GenerationHandle(req, self)

    def generate(self, prompt, max_new_tokens: int = 16, *,
                 temperature: float = 0.0, eos_id: Optional[int] = None,
                 seed: Optional[int] = None) -> List[int]:
        """Blocking convenience wrapper: submit + drain."""
        h = self.submit(prompt, max_new_tokens, temperature=temperature,
                        eos_id=eos_id, seed=seed)
        if not self._auto:
            while self.step():
                pass
        return h.tokens()

    def update_params(self, params, version: Optional[int] = None) -> int:
        """Swap the model weights IN PLACE between scheduler steps.

        The jitted step reads ``self.params`` afresh at every dispatch,
        so the swap is a boundary between steps: in-flight lanes keep
        their KV state and continue generating under the NEW weights at
        the next dispatch — no lane is dropped, no request restarted.
        (The actor/learner RL path publishes learner weights through
        here at version boundaries.)  Returns the new policy version
        (``version`` when given, else the previous version + 1)."""
        with self._work:
            self.params = params
            self.policy_version = (int(version) if version is not None
                                   else self.policy_version + 1)
            events.record("engine", "weights_swap",
                          version=self.policy_version,
                          live_lanes=self.num_active)
            self._work.notify()
            return self.policy_version

    # -------- disaggregated prefill/decode (serve/kv_tier) --------

    def prefill(self, prompt, *, seed: Optional[int] = None,
                deadline_s: Optional[float] = None) -> GenerationHandle:
        """Run chunked prefill for `prompt` and seal its KV blocks into
        the prefix index WITHOUT sampling a token (finish_reason
        "prefill").  The handle drains empty; the product is the sealed
        chain, which `export_prefix` snapshots for a decode engine."""
        h = self.submit(prompt, 1, seed=seed, deadline_s=deadline_s,
                        prefill_only=True)
        if not self._auto:
            while self.step():
                pass
        return h

    def export_prefix(self, tokens) -> Optional[dict]:
        """Snapshot the longest device-cached chain covering `tokens`
        (see PagedKVCache.export_prefix) under the engine lock, so the
        scheduler can't reshuffle blocks mid-gather."""
        tokens = [int(t) for t in tokens]
        with self._lock:
            with spans.span("kv", "export", tokens=len(tokens)):
                return self.cache.export_prefix(tokens)

    def import_prefix(self, payload: dict) -> int:
        """Adopt a foreign sealed chain (the prefill→decode handoff)
        under the engine lock; returns blocks installed.  Idempotent —
        see PagedKVCache.install_prefix."""
        with self._lock:
            with spans.span("kv", "import"):
                return self.cache.install_prefix(payload)

    def prefix_summary(self, limit: Optional[int] = None) -> dict:
        """Routing summary of this engine's cached chains (device index
        + spill tier), bounded by `limit` (config
        serve_prefix_summary_size when None)."""
        if limit is None:
            from ray_tpu._private.config import GLOBAL_CONFIG
            limit = GLOBAL_CONFIG.serve_prefix_summary_size
        with self._lock:
            return self.cache.prefix_summary(limit)

    def cancel(self, req: "_Request") -> bool:
        """Abort one request: dequeue it if still waiting, or evict its
        lane (freeing the KV blocks) if live.  The consumer is unblocked
        with end-of-stream; finish_reason becomes "cancelled".  False if
        the request had already finished (idempotent)."""
        with self._work:
            try:
                self._waiting.remove(req)
            except ValueError:
                pass
            else:
                req.finish_reason = "cancelled"
                req.out.put(_DONE)
                spans.end(req.span_tok, ok=False)
                req.span_tok = None
                return True
            for lane, r in enumerate(self._lanes):
                if r is req:
                    req.finish_reason = "cancelled"
                    req.out.put(_DONE)
                    spans.end(req.span_tok, ok=False)
                    req.span_tok = None
                    self.cache.free_lane(lane)
                    self._lanes[lane] = None
                    events.record("engine", "lane_evict", trace=req.trace,
                                  rid=req.rid, lane=lane,
                                  reason="cancelled")
                    return True
        return False

    def _expire_deadlines(self) -> None:
        """Evict every lane (and drop every queued request) whose
        deadline lapsed — the consumer is gone or has given up, so
        spending decode steps on it only steals FLOPs from live lanes.
        Caller holds the lock."""
        now = time.monotonic()
        for lane, req in enumerate(self._lanes):
            if req is not None and req.deadline is not None \
                    and now > req.deadline:
                req.finish_reason = "deadline"
                req.out.put(_DONE)
                self.cache.free_lane(lane)
                self._lanes[lane] = None
                spans.end(req.span_tok, ok=False)
                req.span_tok = None
                events.record("engine", "deadline_kill", trace=req.trace,
                              rid=req.rid, lane=lane,
                              produced=req.produced)
        expired = [r for r in self._waiting
                   if r.deadline is not None and now > r.deadline]
        for req in expired:
            self._waiting.remove(req)
            req.finish_reason = "deadline"
            req.out.put(_DONE)
            spans.end(req.span_tok, ok=False)
            req.span_tok = None
            events.record("engine", "deadline_kill", trace=req.trace,
                          rid=req.rid, lane=None, produced=0)

    def shutdown(self) -> None:
        with self._work:
            self._stopped = True
            for req in list(self._waiting):
                req.out.put(_DONE)
            self._waiting.clear()
            for lane, req in enumerate(self._lanes):
                if req is not None:
                    req.out.put(_DONE)
                    self.cache.free_lane(lane)
                    self._lanes[lane] = None
            self._work.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)

    @property
    def num_active(self) -> int:
        return sum(r is not None for r in self._lanes)

    @property
    def num_waiting(self) -> int:
        return len(self._waiting)

    def stats(self) -> dict:
        """Engine occupancy + prefix-cache effectiveness counters +
        speculative acceptance counters."""
        cs = self.cache.stats
        st = self._spec_stats
        return {
            "active": self.num_active,
            "waiting": self.num_waiting,
            "max_lanes": self.max_lanes,
            "free_blocks": self.cache.allocator.num_free,
            "cached_blocks": self.cache.num_indexed_blocks,
            "prefix_hits": cs["hits"],
            "prefix_misses": cs["misses"],
            "prefix_hit_tokens": cs["hit_tokens"],
            "prefix_miss_tokens": cs["miss_tokens"],
            "blocks_evicted": self.cache.allocator.evictions,
            "imported_blocks": cs["imported_blocks"],
            "restored_blocks": cs["restored_blocks"],
            **(self.cache.tier.counters if self.cache.tier is not None
               else {}),
            "policy_version": self.policy_version,
            "spec_k": self.spec_k,
            "spec_drafted_tokens": st["drafted"],
            "spec_accepted_tokens": st["accepted"],
            "spec_emitted_tokens": st["emitted"],
            "spec_steps": st["steps"],
            # Tokens per lane per verify step — plain decode is 1.0, so
            # anything above 1 is the speculative multiplier.
            "spec_accepted_per_step": (st["emitted"] / st["bursts"]
                                       if st["bursts"] else 0.0),
        }

    # ---------------- scheduler ----------------

    def _ensure_thread(self):
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="inference-engine")
            self._thread.start()

    def _loop(self):
        while True:
            with self._work:
                while (not self._stopped and not self._waiting
                       and all(r is None for r in self._lanes)):
                    self._work.wait()
                if self._stopped:
                    return
            self.step()

    def _final_len(self, req) -> int:
        return min(len(req.prompt) + req.max_new_tokens,
                   self.cache.max_seq_len)

    def _growth_reserve(self) -> int:
        """Blocks every LIVE lane may still claim before finishing (its
        worst-case final length minus what it already owns).  Admission
        must leave this much unclaimed or a decode step's block-boundary
        growth can exhaust the pool mid-flight — with no preemption, the
        only safe policy is never to admit past the worst case."""
        reserve = 0
        for lane, req in enumerate(self._lanes):
            if req is None:
                continue
            reserve += (self.cache.blocks_needed(self._final_len(req))
                        - len(self.cache.lane_blocks(lane)))
        return reserve

    def _admit(self):
        """Fill free lanes from the FIFO queue — admission control is
        block-level: a request enters only when its worst-case final
        length fits alongside every live lane's worst case, counting
        cached prefix blocks as references, not allocations."""
        met = _metrics()
        for lane in range(self.max_lanes):
            if self._lanes[lane] is not None or not self._waiting:
                continue
            req = self._waiting[0]
            growth = (self.cache.blocks_needed(self._final_len(req))
                      - self.cache.blocks_needed(len(req.prompt)))
            if not self.cache.can_admit_prefix(
                    req.prompt,
                    headroom_blocks=self._growth_reserve() + growth):
                break  # FIFO: don't starve the head with later requests
            reused = self.cache.adopt_prefix(lane, req.prompt)
            self._waiting.popleft()
            req.fed = reused
            self._lanes[lane] = req
            met["hit_tokens"].inc(reused)
            met["miss_tokens"].inc(len(req.prompt) - reused)
            met["hits" if reused else "misses"].inc()
            events.record("engine",
                          "prefix_hit" if reused else "prefix_miss",
                          trace=req.trace, rid=req.rid, lane=lane,
                          reused_tokens=reused,
                          prompt_len=len(req.prompt))
        met["queue_depth"].set(len(self._waiting))
        evictions = self.cache.allocator.evictions
        if evictions > self._evictions_reported:
            met["evicted"].inc(evictions - self._evictions_reported)
            events.record("engine", "blocks_evicted",
                          n=evictions - self._evictions_reported)
            self._evictions_reported = evictions

    def _propose(self, lane: int, req: _Request) -> tuple:
        """Draft for one decode lane: ask the proposer for up to the
        lane's adaptive draft length, clamped so the verify chunk can
        never write past max_seq_len and never drafts beyond the token
        budget (the burst from k drafts is at most k+1 tokens)."""
        limit = min(req.spec_k,
                    req.max_new_tokens - req.produced - 1,
                    self.cache.max_seq_len - 1
                    - int(self.cache.seq_lens[lane]))
        if limit <= 0:
            return ()
        draft = self._proposer.propose(req.prompt + req.emitted, limit)
        vocab = self.config.vocab_size
        out = []
        for t in draft[:limit]:
            t = int(t)
            if not 0 <= t < vocab:
                break       # garbage proposal: verify nothing past it
            out.append(t)
        return tuple(out)

    def step(self) -> bool:
        """One scheduler iteration: admit, then advance every live lane.
        Decode lanes and prefilling lanes dispatch as SEPARATE jitted
        steps (T=1 and T=prefill_chunk) so neither population pays the
        other's FLOP shape.  When speculation is on and any decode lane
        drafted, the decode population dispatches as ONE verify step
        sized to the WIDEST draft actually proposed this step
        (T = 1+max drafts, never more than spec_k+1) — draftless lanes
        ride along at chunk=1, so mixed speculative/plain lanes share
        the step, and adaptive-k backoff shrinks the verify FLOPs it
        pays for instead of padding to the configured maximum.
        Returns False when fully idle."""
        with self._lock:
            self._expire_deadlines()
            self._admit()
            live = [(i, r) for i, r in enumerate(self._lanes)
                    if r is not None]
            if not live:
                return False
            plans = []
            decode = [(i, r) for i, r in live if not r.prefilling]
            if decode:
                spec = False
                if self._proposer is not None:
                    dtok = spans.begin("engine", "spec_draft")
                    drafted = 0
                    for lane, req in decode:
                        req.draft = self._propose(lane, req)
                        drafted += len(req.draft)
                    spec = drafted > 0
                    spans.end(dtok, lanes=len(decode), drafted=drafted)
                t = 1 + max(len(r.draft) for _, r in decode) if spec else 1
                plans.append((spec, decode) + self._build_batch(decode, t))
            prefill = [(i, r) for i, r in live if r.prefilling]
            if prefill:
                plans.append((False, prefill)
                             + self._build_batch(prefill, self.prefill_chunk))
            events.record("engine", "step", decode=len(decode),
                          prefill=len(prefill),
                          waiting=len(self._waiting))
        done = []
        for spec, lanes, batch, chunks in plans:
            vtok = spans.begin("engine", "spec_verify") if spec else None
            next_tok, lps = self._run_step(batch, spec)
            toks = np.asarray(next_tok)
            if toks.ndim == 1:      # plain/prefill: one token per lane
                toks = toks[:, None]
            if lps is not None:
                lps = np.asarray(lps)
                if lps.ndim == 1:
                    lps = lps[:, None]
            spans.end(vtok, lanes=len(lanes))
            if spec:
                self._spec_stats["steps"] += 1
                _metrics()["spec_steps"].inc()
            done.append((lanes, chunks, toks, lps))
        with self._work:
            for lanes, chunks, toks, lps in done:
                self._commit(lanes, chunks, toks, lps)
            self._work.notify()
        return True

    def _build_batch(self, live, t):
        """Host-side assembly of the fixed-shape lane arrays for one
        population (lanes not in `live` ride along fully masked)."""
        n = self.max_lanes
        tokens = np.zeros((n, t), np.int32)
        positions = np.zeros((n, t), np.int32)
        valid = np.zeros((n, t), bool)
        ctx_lens = np.ones((n,), np.int32)
        gather = np.zeros((n,), np.int32)
        temps = np.zeros((n,), np.float32)
        seeds = np.zeros((n,), np.uint32)
        counters = np.zeros((n,), np.int32)
        chunks = {}
        sample = False
        for lane, req in live:
            start = int(self.cache.seq_lens[lane])
            if req.prefilling:
                chunk = min(t, len(req.prompt) - req.fed)
                tokens[lane, :chunk] = req.prompt[req.fed:req.fed + chunk]
            else:
                # Speculative lanes feed [last_token, d_1 .. d_k]; the
                # verify step samples every position.  Draftless lanes
                # are the plain chunk=1 decode, masked alongside.
                chunk = 1 + len(req.draft)
                tokens[lane, :chunk] = (req.last_token,) + tuple(req.draft)
            positions[lane] = start + np.arange(t)
            valid[lane, :chunk] = True
            ctx_lens[lane] = start + chunk
            gather[lane] = chunk - 1
            temps[lane] = req.temperature
            seeds[lane] = req.seed & 0xFFFFFFFF
            counters[lane] = req.produced + req.sample_offset
            sample = sample or req.temperature > 0
            chunks[lane] = chunk
            # Table entries must exist before the step writes K/V.
            self.cache.ensure_capacity(lane, start + chunk)
        batch = (t, sample,
                 (jnp.asarray(tokens), jnp.asarray(positions),
                  jnp.asarray(valid), self.cache.device_tables(),
                  jnp.asarray(ctx_lens), jnp.asarray(gather),
                  jnp.asarray(temps), jnp.asarray(seeds),
                  jnp.asarray(counters)))
        return batch, chunks

    def _run_step(self, batch, spec: bool = False):
        t, sample, args = batch
        key = (t, sample, spec)
        fn = self._step_fns.get(key)
        if fn is None:
            fn = self._step_fns[key] = self._make_step_fn(sample, spec)
        if self._capture_logp:
            next_tok, logp, k, v = fn(self.params, self.cache.k,
                                      self.cache.v, *args)
        else:
            next_tok, k, v = fn(self.params, self.cache.k, self.cache.v,
                                *args)
            logp = None
        self.cache.update_pools(k, v)
        return next_tok, logp

    def _make_step_fn(self, sample: bool, spec: bool = False):
        model, config = self.model, self.config
        capture = self._capture_logp

        def _logp_at(logits, out, temps_b):
            # Behavior log-prob of the chosen token under the ACTUAL
            # sampling distribution — softmax(logits/temp) when temp > 0,
            # plain softmax for greedy lanes (argmax is deterministic;
            # its soft log-prob is still the importance-weighting anchor
            # the V-trace learner corrects against).
            z = logits.astype(jnp.float32)
            lp = jax.nn.log_softmax(
                jnp.where(temps_b > 0, z / jnp.maximum(temps_b, 1e-6), z))
            return jnp.take_along_axis(lp, out[..., None], axis=-1)[..., 0]

        def step(params, k, v, tokens, positions, valid, tables, ctx_lens,
                 gather, temps, seeds, counters):
            x, k, v = model.forward_cached(
                params, tokens, positions, valid, k, v, tables, ctx_lens,
                config)
            if spec:
                # Verify shape: EVERY position's next token is sampled
                # in-graph — position j draws with the key the plain
                # step would use after j more commits, fold_in(seed,
                # counter + j), so the accepted prefix is token-exact
                # with non-speculative decode.  T = spec_k+1 is small;
                # the [B, T, V] logits stay on device and the step's
                # only non-pool output is [B, T] int32.
                logits = model.lm_head(params, x, config)    # [B, T, V]
                greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                if not sample:
                    out = greedy
                else:
                    offs = jnp.arange(logits.shape[1], dtype=jnp.int32)

                    def draw_lane(rows, temp, seed, counter):
                        def draw_pos(row, off):
                            key = jax.random.fold_in(jax.random.key(seed),
                                                     counter + off)
                            z = row.astype(jnp.float32) / jnp.maximum(temp,
                                                                      1e-6)
                            return jax.random.categorical(key, z).astype(
                                jnp.int32)

                        return jax.vmap(draw_pos)(rows, offs)

                    sampled = jax.vmap(draw_lane)(logits, temps, seeds,
                                                  counters)
                    out = jnp.where(temps[:, None] > 0, sampled, greedy)
                if capture:
                    return out, _logp_at(logits, out,
                                         temps[:, None, None]), k, v
                return out, k, v
            # Only each lane's last valid position reaches the lm head —
            # a prefill chunk never materializes [B, T, V], and the
            # logits never leave the device: sampling happens HERE and
            # the step's only non-pool output is one token id per lane.
            xg = jnp.take_along_axis(
                x, gather[:, None, None].astype(jnp.int32), axis=1)[:, 0]
            logits = model.lm_head(params, xg, config)       # [B, V]
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            if not sample:
                if capture:
                    return greedy, _logp_at(logits, greedy,
                                            temps[:, None]), k, v
                return greedy, k, v

            def draw(row, temp, seed, counter):
                # Key = f(request seed, tokens produced): reproducible
                # per request regardless of lane index or who else is
                # in the batch.
                key = jax.random.fold_in(jax.random.key(seed), counter)
                z = row.astype(jnp.float32) / jnp.maximum(temp, 1e-6)
                return jax.random.categorical(key, z).astype(jnp.int32)

            sampled = jax.vmap(draw)(logits, temps, seeds, counters)
            next_tok = jnp.where(temps > 0, sampled, greedy)
            if capture:
                return next_tok, _logp_at(logits, next_tok,
                                          temps[:, None]), k, v
            return next_tok, k, v

        self._step_impls[(sample, "spec") if spec else sample] = step
        # Donating the pools makes the cache update in-place on TPU; CPU
        # ignores donation with a warning, so only ask for it on TPU.
        donate = (1, 2) if jax.default_backend() == "tpu" else ()
        return jax.jit(step, donate_argnums=donate)

    def _commit(self, live, chunks, toks, lps=None):
        """Apply one dispatch's results: advance prefill cursors, seal
        newly-full blocks into the prefix index, stream sampled tokens
        (a multi-token speculative burst commits ATOMICALLY — one queue
        item), roll back rejected draft blocks, finish + free lanes.

        `toks` is [max_lanes, T]: T=1 rows for prefill/plain decode, the
        per-position verify samples for a speculative dispatch."""
        met = _metrics()
        for lane, req in live:
            if self._lanes[lane] is not req:
                continue  # shutdown()/cancel() cleared the lane mid-step
            row = toks[lane]
            draft = req.draft
            req.draft = ()
            was_prefill = req.prefilling
            if was_prefill:
                req.fed += chunks[lane]
                self.cache.seq_lens[lane] += chunks[lane]
                self.cache.seal_full_blocks(lane, req.prompt)
                if req.prefilling:
                    continue  # more prompt to go; nothing sampled yet
                if req.prefill_only:
                    # Disaggregated prefill: the prompt's K/V is sealed
                    # in the prefix index (it survives the lane free as
                    # evictable blocks); no token is sampled or
                    # streamed.  The sampled row is discarded — the
                    # decode replica draws it with the same fold_in
                    # keys, so output stays token-exact.
                    req.finish_reason = "prefill"
                    req.out.put(_DONE)
                    self.cache.free_lane(lane)
                    self._lanes[lane] = None
                    spans.end(req.span_tok, tokens=0)
                    req.span_tok = None
                    events.record("engine", "finish", trace=req.trace,
                                  rid=req.rid, reason="prefill",
                                  produced=0)
                    continue
                burst = [int(row[0])]
                accepted = 0
            else:
                # Exact-match verification: position j's K/V and sample
                # are only valid if every earlier fed draft matched the
                # model's own output, so the burst is the accepted draft
                # prefix plus the first divergent (or bonus) sample.
                accepted = 0
                while (accepted < len(draft)
                       and int(row[accepted]) == draft[accepted]):
                    accepted += 1
                burst = [int(row[j]) for j in range(accepted + 1)]
            # Clamp the burst when a stop condition lands mid-burst:
            # tokens past eos / the max_new_tokens budget were never
            # "generated" — they are discarded, not streamed.
            emit: List[int] = []
            for tok in burst:
                emit.append(tok)
                if req.eos_id is not None and tok == req.eos_id:
                    req.finish_reason = "eos"
                    break
                if req.produced + len(emit) >= req.max_new_tokens:
                    req.finish_reason = "length"
                    break
            m = len(emit)
            if not was_prefill:
                # Commit K/V for the m verified positions, release the
                # blocks the rejected tail claimed, and seal only what
                # is now committed history (drafted blocks never enter
                # the prefix index early: sealing is bounded by
                # seq_lens, which counts accepted tokens only).
                self.cache.seq_lens[lane] += m
                if chunks[lane] > m:
                    self.cache.truncate_lane(
                        lane, int(self.cache.seq_lens[lane]))
                self.cache.seal_full_blocks(
                    lane, req.prompt + req.emitted + emit)
            # SLO latency accounting: first emit is TTFT (queue wait +
            # prefill included); a later burst of m tokens closes m TBT
            # gaps of the mean inter-token latency this step achieved.
            now = time.time()
            first = req.produced == 0
            if first:
                if req.submitted:
                    met["ttft"].observe(now - req.submitted)
            elif req.last_emit:
                gap = (now - req.last_emit) / m
                for _ in range(m):
                    met["tbt"].observe(gap)
            req.last_emit = now
            req.last_token = emit[-1]
            req.emitted.extend(emit)
            if lps is not None:
                # lps rows are position-parallel with toks rows, so the
                # clamped emit prefix maps 1:1 onto the first m entries.
                req.logps.extend(float(lps[lane, j]) for j in range(m))
            req.produced += m
            if self._proposer is not None and not was_prefill:
                self._spec_stats["emitted"] += m
                self._spec_stats["bursts"] += 1
                met["spec_per_step"].observe(m)
            if draft:
                self._spec_stats["drafted"] += len(draft)
                self._spec_stats["accepted"] += accepted
                met["spec_drafted"].inc(len(draft))
                met["spec_accepted"].inc(accepted)
                events.record("engine", "spec_accept", trace=req.trace,
                              rid=req.rid, lane=lane, drafted=len(draft),
                              accepted=accepted, emitted=m)
                if self._spec_adaptive:
                    # Per-lane draft length: grow on full acceptance,
                    # halve on total rejection, otherwise track what
                    # the stream actually sustains.
                    if accepted == len(draft):
                        req.spec_k = min(self.spec_k, req.spec_k + 1)
                    elif accepted == 0:
                        req.spec_k = max(1, req.spec_k // 2)
                    else:
                        req.spec_k = max(1, min(req.spec_k, accepted + 1))
                self._proposer.observe(len(draft), accepted)
            # The consumer sees a burst as ONE item: no partial-draft
            # exposure, and failover snapshots never split a burst.
            req.out.put(emit[0] if m == 1 else list(emit))
            if req.finish_reason is None \
                    and int(self.cache.seq_lens[lane]) >= self.cache.max_seq_len:
                req.finish_reason = "max_seq_len"
            if req.trace is not None:
                # Close the span ending at this emit (prefill for the
                # first token, the previous decode gap otherwise) and
                # open the next decode span unless the request is done.
                spans.end(req.span_tok, tokens=req.produced)
                req.span_tok = (
                    None if req.finish_reason is not None else
                    spans.begin("engine", "decode", ctx=req.trace,
                                rid=req.rid, t=req.produced))
            if req.finish_reason is not None:
                req.out.put(_DONE)
                self.cache.free_lane(lane)
                self._lanes[lane] = None
                events.record("engine", "finish", trace=req.trace,
                              rid=req.rid, reason=req.finish_reason,
                              produced=req.produced)
