"""Continuous-batching generation engine over the paged KV cache.

One jitted step advances a fixed-capacity LANE array: every live
sequence owns a lane, new requests are admitted into lanes the moment
their previous occupant finishes (mid-flight — no batch barrier), and
padding lanes ride along masked.  Two compiled shapes total: the pure
decode step (T=1, single-query paged attention — the Pallas kernel
path) and the mixed step (T=prefill_chunk) used whenever any lane is
still prefilling; in a mixed step decoding lanes keep advancing with
one valid token, so prefill chunks interleave with decode instead of
stalling the batch.  Throughput therefore scales with concurrent
requests instead of resetting per batch — the property bench_decode.py
measures.

The engine is host-driven: block allocation, admission, sampling
dispatch and stream fan-out are Python; the model math is one
jax.jit'ed call per step with pools donated on TPU (in-place cache
update).
"""

from __future__ import annotations

import collections
import itertools
import queue
import threading
from dataclasses import dataclass, field
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.inference.kv_cache import PagedKVCache

_DONE = object()


@dataclass
class _Request:
    rid: int
    prompt: List[int]
    max_new_tokens: int
    temperature: float = 0.0
    eos_id: Optional[int] = None
    out: "queue.Queue" = field(default_factory=queue.Queue)
    fed: int = 0            # prompt tokens written to the cache so far
    produced: int = 0
    last_token: int = 0
    finish_reason: Optional[str] = None

    @property
    def prefilling(self) -> bool:
        return self.fed < len(self.prompt)


class GenerationHandle:
    """Streaming view of one request: iterate to receive token ids as
    the engine emits them (the serve stream-ticket path pulls these)."""

    def __init__(self, req: _Request):
        self._req = req

    def __iter__(self):
        return self

    def __next__(self) -> int:
        item = self._req.out.get()
        if item is _DONE:
            raise StopIteration
        return item

    def tokens(self, timeout: Optional[float] = None) -> List[int]:
        """Block until the request finishes; returns all generated ids."""
        out = []
        while True:
            item = self._req.out.get(timeout=timeout)
            if item is _DONE:
                return out
            out.append(item)

    @property
    def finish_reason(self) -> Optional[str]:
        return self._req.finish_reason


def _resolve_model(model):
    if isinstance(model, str):
        if model == "gpt":
            from ray_tpu.models import gpt as mod
        elif model == "llama":
            from ray_tpu.models import llama as mod
        else:
            raise ValueError(f"unknown model family {model!r}")
        return mod
    return model  # a module implementing forward_cached/lm_head/CONFIGS


class InferenceEngine:
    """max_lanes concurrent sequences over one shared paged KV pool.

    `auto_start=True` (default) runs the scheduler on a daemon thread —
    submit() returns a streaming GenerationHandle immediately.  With
    auto_start=False the caller drives `step()` (deterministic tests,
    microbenchmarks).
    """

    def __init__(self, model="gpt", config="nano", params=None, *,
                 max_lanes: int = 8, block_size: int = 16,
                 num_blocks: Optional[int] = None,
                 max_seq_len: Optional[int] = None,
                 prefill_chunk: int = 32, seed: int = 0,
                 auto_start: bool = True):
        self.model = _resolve_model(model)
        self.config = (self.model.CONFIGS[config] if isinstance(config, str)
                       else config)
        if params is None:
            params = self.model.init_params(self.config,
                                            jax.random.key(seed))
        self.params = params
        self.max_lanes = max_lanes
        self.prefill_chunk = prefill_chunk
        max_seq_len = min(max_seq_len or self.config.max_seq_len,
                          self.config.max_seq_len)
        if num_blocks is None:
            num_blocks = max_lanes * -(-max_seq_len // block_size)
        self.cache = PagedKVCache.for_model(
            self.model, self.config, num_blocks=num_blocks,
            block_size=block_size, max_lanes=max_lanes,
            max_seq_len=max_seq_len)
        self._lanes: List[Optional[_Request]] = [None] * max_lanes
        self._waiting: "collections.deque[_Request]" = collections.deque()
        self._rid = itertools.count(1)
        self._rng = np.random.default_rng(seed)
        self._step_fns = {}
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._thread: Optional[threading.Thread] = None
        self._stopped = False
        self._auto = auto_start

    # ---------------- public API ----------------

    def submit(self, prompt, max_new_tokens: int = 16, *,
               temperature: float = 0.0,
               eos_id: Optional[int] = None) -> GenerationHandle:
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if len(prompt) > self.cache.max_seq_len:
            raise ValueError("prompt longer than max_seq_len")
        req = _Request(rid=next(self._rid), prompt=prompt,
                       max_new_tokens=max_new_tokens,
                       temperature=temperature, eos_id=eos_id)
        with self._work:
            if self._stopped:
                raise RuntimeError("engine is shut down")
            self._waiting.append(req)
            self._work.notify()
        if self._auto:
            self._ensure_thread()
        return GenerationHandle(req)

    def generate(self, prompt, max_new_tokens: int = 16, *,
                 temperature: float = 0.0,
                 eos_id: Optional[int] = None) -> List[int]:
        """Blocking convenience wrapper: submit + drain."""
        h = self.submit(prompt, max_new_tokens, temperature=temperature,
                        eos_id=eos_id)
        if not self._auto:
            while self.step():
                pass
        return h.tokens()

    def shutdown(self) -> None:
        with self._work:
            self._stopped = True
            for req in list(self._waiting):
                req.out.put(_DONE)
            self._waiting.clear()
            for lane, req in enumerate(self._lanes):
                if req is not None:
                    req.out.put(_DONE)
                    self.cache.free_lane(lane)
                    self._lanes[lane] = None
            self._work.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)

    @property
    def num_active(self) -> int:
        return sum(r is not None for r in self._lanes)

    @property
    def num_waiting(self) -> int:
        return len(self._waiting)

    # ---------------- scheduler ----------------

    def _ensure_thread(self):
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="inference-engine")
            self._thread.start()

    def _loop(self):
        while True:
            with self._work:
                while (not self._stopped and not self._waiting
                       and all(r is None for r in self._lanes)):
                    self._work.wait()
                if self._stopped:
                    return
            self.step()

    def _admit(self):
        """Fill free lanes from the FIFO queue — admission control is
        block-level: a request enters only when its whole prompt fits
        the pool (plus one block of decode headroom)."""
        for lane in range(self.max_lanes):
            if self._lanes[lane] is not None or not self._waiting:
                continue
            req = self._waiting[0]
            need = self.cache.blocks_needed(len(req.prompt)) + 1
            if not self.cache.allocator.can_alloc(need):
                break  # FIFO: don't starve the head with later requests
            self._waiting.popleft()
            self.cache.alloc_lane(lane, len(req.prompt))
            self._lanes[lane] = req

    def step(self) -> bool:
        """One scheduler iteration: admit, then one jitted model step
        advancing every live lane.  Returns False when fully idle."""
        with self._lock:
            self._admit()
            live = [(i, r) for i, r in enumerate(self._lanes)
                    if r is not None]
            if not live:
                return False
            t = (self.prefill_chunk
                 if any(r.prefilling for _, r in live) else 1)
            batch, chunks = self._build_batch(live, t)
        next_tok, logits = self._run_step(t, *batch)
        with self._work:
            self._commit(live, chunks, np.asarray(next_tok), logits)
            self._work.notify()
        return True

    def _build_batch(self, live, t):
        """Host-side assembly of the fixed-shape lane arrays."""
        n = self.max_lanes
        tokens = np.zeros((n, t), np.int32)
        positions = np.zeros((n, t), np.int32)
        valid = np.zeros((n, t), bool)
        ctx_lens = np.ones((n,), np.int32)
        gather = np.zeros((n,), np.int32)
        chunks = {}
        for lane, req in live:
            start = int(self.cache.seq_lens[lane])
            if req.prefilling:
                chunk = min(t, len(req.prompt) - req.fed)
                tokens[lane, :chunk] = req.prompt[req.fed:req.fed + chunk]
            else:
                chunk = 1
                tokens[lane, 0] = req.last_token
            positions[lane] = start + np.arange(t)
            valid[lane, :chunk] = True
            ctx_lens[lane] = start + chunk
            gather[lane] = chunk - 1
            chunks[lane] = chunk
            # Table entries must exist before the step writes K/V.
            self.cache.ensure_capacity(lane, start + chunk)
        return (jnp.asarray(tokens), jnp.asarray(positions),
                jnp.asarray(valid), self.cache.device_tables(),
                jnp.asarray(ctx_lens), jnp.asarray(gather)), chunks

    def _run_step(self, t, tokens, positions, valid, tables, ctx_lens,
                  gather):
        fn = self._step_fns.get(t)
        if fn is None:
            fn = self._step_fns[t] = self._make_step_fn()
        next_tok, logits, k, v = fn(self.params, self.cache.k, self.cache.v,
                                    tokens, positions, valid, tables,
                                    ctx_lens, gather)
        self.cache.update_pools(k, v)
        return next_tok, logits

    def _make_step_fn(self):
        model, config = self.model, self.config

        def step(params, k, v, tokens, positions, valid, tables, ctx_lens,
                 gather):
            x, k, v = model.forward_cached(
                params, tokens, positions, valid, k, v, tables, ctx_lens,
                config)
            # Only each lane's last valid position reaches the lm head —
            # a prefill chunk never materializes [B, T, V].
            xg = jnp.take_along_axis(
                x, gather[:, None, None].astype(jnp.int32), axis=1)[:, 0]
            logits = model.lm_head(params, xg, config)       # [B, V]
            next_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return next_tok, logits, k, v

        # Donating the pools makes the cache update in-place on TPU; CPU
        # ignores donation with a warning, so only ask for it on TPU.
        donate = (1, 2) if jax.default_backend() == "tpu" else ()
        return jax.jit(step, donate_argnums=donate)

    def _commit(self, live, chunks, next_tok, logits):
        """Apply one step's results: advance prefill cursors, sample,
        stream tokens, finish + free lanes."""
        logits_np = None
        for lane, req in live:
            if self._lanes[lane] is not req:
                continue  # shutdown() cleared the lane mid-step
            if req.prefilling:
                req.fed += chunks[lane]
                self.cache.seq_lens[lane] += chunks[lane]
                if req.prefilling:
                    continue  # more prompt to go; nothing sampled yet
            else:
                self.cache.seq_lens[lane] += 1
            if req.temperature > 0:
                if logits_np is None:
                    logits_np = np.asarray(logits, np.float32)
                tok = self._sample(logits_np[lane], req.temperature)
            else:
                tok = int(next_tok[lane])
            req.last_token = tok
            req.produced += 1
            req.out.put(tok)
            if req.eos_id is not None and tok == req.eos_id:
                req.finish_reason = "eos"
            elif req.produced >= req.max_new_tokens:
                req.finish_reason = "length"
            elif int(self.cache.seq_lens[lane]) >= self.cache.max_seq_len:
                req.finish_reason = "max_seq_len"
            if req.finish_reason is not None:
                req.out.put(_DONE)
                self.cache.free_lane(lane)
                self._lanes[lane] = None

    def _sample(self, row: np.ndarray, temperature: float) -> int:
        z = row / max(temperature, 1e-6)
        z = z - z.max()
        p = np.exp(z)
        p /= p.sum()
        return int(self._rng.choice(len(p), p=p))
