"""ray_tpu.inference — TPU-native autoregressive generation engine.

Paged KV cache (fixed-size blocks in a preallocated pool, per-sequence
block tables — the vLLM memory model, PAPERS.md), single-query decode
attention (Pallas kernel in ops/attention.py, masked-dense fallback),
and a continuous-batching scheduler: one jitted decode step over a
fixed-capacity lane array, sequences admitted into free lanes as others
finish, so decode throughput scales with concurrency instead of
resetting per batch.  Self-speculative decoding (speculative.py) lifts
the one-token-per-step ceiling: n-gram / prompt-lookup drafts verified
k+1-at-a-time by the same jitted step, token-exact by construction.
serve/llm.py exposes it all as an LLMDeployment.
"""

from ray_tpu.inference.kv_cache import BlockAllocator, PagedKVCache  # noqa: F401
from ray_tpu.inference.engine import InferenceEngine  # noqa: F401
from ray_tpu.inference.speculative import (  # noqa: F401
    DraftProposer, ModelDraftProposer, NgramProposer,
    resolve_draft_proposer)
