"""ray_tpu.inference — TPU-native autoregressive generation engine.

Paged KV cache (fixed-size blocks in a preallocated pool, per-sequence
block tables — the vLLM memory model, PAPERS.md), single-query decode
attention (Pallas kernel in ops/attention.py, masked-dense fallback),
and a continuous-batching scheduler: one jitted decode step over a
fixed-capacity lane array, sequences admitted into free lanes as others
finish, so decode throughput scales with concurrency instead of
resetting per batch.  serve/llm.py exposes it as an LLMDeployment.
"""

from ray_tpu.inference.kv_cache import BlockAllocator, PagedKVCache  # noqa: F401
from ray_tpu.inference.engine import InferenceEngine  # noqa: F401
