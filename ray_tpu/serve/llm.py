"""LLM serving: serve-plane front end for the continuous-batching engine.

One InferenceEngine per replica.  Every serve request — streaming or
not — submits into the replica's shared lane array, so concurrent
requests batch onto the same jitted decode step instead of running the
model once per request; tokens flow back through the existing serve
stream-ticket path (`handle.options("generate").stream(...)` pulls them
incrementally, replica-pinned).
"""

from typing import List, Optional

from ray_tpu.serve.api import deployment


@deployment(name="llm", max_concurrent_queries=64)
class LLMDeployment:
    """Replica callable wrapping an InferenceEngine.

    Usage::

        app = serve.LLMDeployment.bind(model="gpt", config="nano",
                                       max_lanes=8)
        handle = serve.run(app)
        for tok in handle.options("generate").stream([1, 2, 3],
                                                     max_new_tokens=16):
            ...                      # token ids, streamed as generated
        handle.remote([1, 2, 3]).result()   # non-streaming: full list
    """

    def __init__(self, model="gpt", config="nano", params=None, *,
                 max_lanes: int = 8, block_size: int = 16,
                 num_blocks: Optional[int] = None,
                 max_seq_len: Optional[int] = None,
                 prefill_chunk: int = 32, seed: int = 0,
                 prefix_cache: bool = True):
        from ray_tpu.inference import InferenceEngine  # jax: replica-only
        self._engine = InferenceEngine(
            model, config, params, max_lanes=max_lanes,
            block_size=block_size, num_blocks=num_blocks,
            max_seq_len=max_seq_len, prefill_chunk=prefill_chunk,
            seed=seed, prefix_cache=prefix_cache)

    def generate(self, prompt, max_new_tokens: int = 16,
                 temperature: float = 0.0, eos_id: Optional[int] = None,
                 seed: Optional[int] = None):
        """Streaming entry point: a generator, so serve hands the caller
        a stream ticket and each token is pulled as the engine emits it."""
        handle = self._engine.submit(prompt, max_new_tokens,
                                     temperature=temperature,
                                     eos_id=eos_id, seed=seed)
        for tok in handle:
            yield int(tok)

    def __call__(self, prompt, max_new_tokens: int = 16,
                 temperature: float = 0.0,
                 eos_id: Optional[int] = None,
                 seed: Optional[int] = None) -> List[int]:
        """Non-streaming: block until the sequence finishes."""
        return self._engine.generate(prompt, max_new_tokens,
                                     temperature=temperature,
                                     eos_id=eos_id, seed=seed)

    def stats(self) -> dict:
        """Engine occupancy + prefix-cache counters (the same numbers the
        engine exports through util.metrics, so `cli metrics` scrapes
        them from the replica process)."""
        return self._engine.stats()
