"""LLM serving: serve-plane front end for the continuous-batching engine.

One InferenceEngine per replica.  Every serve request — streaming or
not — submits into the replica's shared lane array, so concurrent
requests batch onto the same jitted decode step instead of running the
model once per request; tokens flow back through the existing serve
stream-ticket path (`handle.options("generate").stream(...)` pulls them
incrementally, replica-pinned).

Mid-stream failover: pair the handle with the `llm_stream_resume`
policy (``handle.options("generate", failover=llm_stream_resume)``) and
a replica death mid-generation is absorbed by resubmitting with the
already-produced tokens appended to the prompt.  The prefix cache makes
the re-prefill cheap, and the resumed stream is token-exact for greedy
decoding; sampled decoding is seed-consistent too when the request
carries an explicit ``seed`` (the engine folds the per-step sampling key
from (seed, produced+sample_offset), so the resumed request draws the
same keys the dead replica would have drawn).
"""

from typing import List, Optional

from ray_tpu.serve.api import deployment


def llm_stream_resume(args, kwargs, received):
    """Failover policy for LLMDeployment.generate streams: resume the
    generation where the dead replica stopped instead of replaying it.

    Rewrites (args, kwargs) so the resubmitted request carries
    ``prompt + received`` as its prompt, a decremented token budget, and
    ``_produced_offset=len(received)`` to keep the in-jit sampling keys
    aligned with the original request.  Returns None when the stream was
    already complete (budget exhausted or EOS emitted), which ends the
    stream cleanly instead of resubmitting a no-op request."""
    args = list(args)
    kwargs = dict(kwargs)
    if args:
        prompt = args.pop(0)
    else:
        prompt = kwargs.pop("prompt")
    if args:
        budget = args.pop(0)
    else:
        budget = kwargs.pop("max_new_tokens", 16)
    # Anything left positionally maps onto generate()'s signature order.
    for name, val in zip(("temperature", "eos_id", "seed"), args):
        kwargs.setdefault(name, val)
    received = [int(t) for t in received]
    remaining = int(budget) - len(received)
    if remaining <= 0:
        return None
    eos_id = kwargs.get("eos_id")
    if eos_id is not None and received and received[-1] == int(eos_id):
        return None
    new_prompt = [int(t) for t in prompt] + received
    kwargs["max_new_tokens"] = remaining
    kwargs["_produced_offset"] = len(received)
    return (new_prompt,), kwargs


@deployment(name="llm", max_concurrent_queries=64)
class LLMDeployment:
    """Replica callable wrapping an InferenceEngine.

    Usage::

        app = serve.LLMDeployment.bind(model="gpt", config="nano",
                                       max_lanes=8)
        handle = serve.run(app)
        for tok in handle.options("generate").stream([1, 2, 3],
                                                     max_new_tokens=16):
            ...                      # token ids, streamed as generated
        handle.remote([1, 2, 3]).result()   # non-streaming: full list
    """

    def __init__(self, model="gpt", config="nano", params=None, *,
                 max_lanes: int = 8, block_size: int = 16,
                 num_blocks: Optional[int] = None,
                 max_seq_len: Optional[int] = None,
                 prefill_chunk: int = 32, seed: int = 0,
                 prefix_cache: bool = True, speculative: bool = False,
                 spec_k: Optional[int] = None, draft_proposer="ngram",
                 kv_tier: Optional[bool] = None):
        from ray_tpu._private.config import GLOBAL_CONFIG
        from ray_tpu.inference import InferenceEngine  # jax: replica-only
        # `speculative=True` opts the replica into speculative decoding;
        # the draft length defaults to the cluster-wide `spec_k` config
        # knob unless pinned per deployment.
        if spec_k is None:
            spec_k = GLOBAL_CONFIG.spec_k if speculative else 0
        self._engine = InferenceEngine(
            model, config, params, max_lanes=max_lanes,
            block_size=block_size, num_blocks=num_blocks,
            max_seq_len=max_seq_len, prefill_chunk=prefill_chunk,
            seed=seed, prefix_cache=prefix_cache,
            spec_k=int(spec_k), draft_proposer=draft_proposer,
            spec_adaptive=GLOBAL_CONFIG.spec_adaptive,
            kv_tier=kv_tier)

    def generate(self, prompt, max_new_tokens: int = 16,
                 temperature: float = 0.0, eos_id: Optional[int] = None,
                 seed: Optional[int] = None, _produced_offset: int = 0,
                 _deadline_s: Optional[float] = None):
        """Streaming entry point: a generator, so serve hands the caller
        a stream ticket and each token is pulled as the engine emits it.

        `_produced_offset` / `_deadline_s` are serve-plane plumbing:
        the failover policy sets the offset so a resumed request samples
        with the original request's key sequence, and the replica
        injects the remaining deadline budget so the engine evicts the
        lane (instead of decoding for nobody) once it lapses."""
        handle = self._engine.submit(prompt, max_new_tokens,
                                     temperature=temperature,
                                     eos_id=eos_id, seed=seed,
                                     sample_offset=_produced_offset,
                                     deadline_s=_deadline_s)
        try:
            for tok in handle:
                yield int(tok)
        finally:
            # Consumer gone mid-stream (cancel, deadline, disconnect):
            # evict the lane so the engine stops decoding for nobody.
            handle.cancel()

    def __call__(self, prompt, max_new_tokens: int = 16,
                 temperature: float = 0.0,
                 eos_id: Optional[int] = None,
                 seed: Optional[int] = None,
                 _deadline_s: Optional[float] = None) -> List[int]:
        """Non-streaming: block until the sequence finishes (or the
        propagated request deadline cancels it)."""
        handle = self._engine.submit(prompt, max_new_tokens,
                                     temperature=temperature,
                                     eos_id=eos_id, seed=seed)
        return handle.tokens(timeout=_deadline_s)

    def prefix_summary(self) -> dict:
        """Compact prefix-index summary for prefix-cache-aware routing:
        the router scrapes this periodically and scores this replica by
        the deepest prompt hash-chain prefix it already holds.  Bounded
        by ``serve_prefix_summary_size`` — never the full index."""
        return self._engine.prefix_summary()

    def stats(self) -> dict:
        """Engine occupancy + prefix-cache + speculative-acceptance
        counters (the same numbers the engine exports through
        util.metrics, so `cli metrics` scrapes them from the replica
        process)."""
        return self._engine.stats()
