"""Wire format for sealed KV blocks + hash-chain metadata.

One encoded payload carries a contiguous chain of sealed blocks — the
per-block token tuples (enough to rebuild every content-addressed chain
key from the root) and the gathered K/V pool contents, dtype and all.
The decode-side ``PagedKVCache.install_prefix`` adopts the blocks as if
it had sealed them itself, so a prefill→decode handoff is bit-exact by
construction and idempotent on retry (content-addressed links already
present are skipped).

The payload is bytes on the wire: beyond the inline-object threshold it
automatically rides the native shm object plane (``objtransfer.cc`` via
``object_transfer.py``) like any other big serve argument — the codec
never needs to know about transports.
"""

from __future__ import annotations

import io
import pickle
from typing import Optional

import numpy as np

_MAGIC = b"KVT1"


class KVCodecError(ValueError):
    """Payload is not a KVBlockCodec frame (or an incompatible one)."""


class KVBlockCodec:
    """Encode/decode ``PagedKVCache.export_prefix`` payloads.

    The frame is a 4-byte magic + a pickled dict whose arrays are plain
    numpy (pickle round-trips them bit-exactly, dtype included).  A
    version field inside the dict gates forward compatibility; the
    magic catches whole-payload confusion early (a truncated or foreign
    blob raises KVCodecError, never a half-installed cache)."""

    @staticmethod
    def encode(payload: dict) -> bytes:
        if not payload or payload.get("v") != 1:
            raise KVCodecError("not an export_prefix v1 payload")
        buf = io.BytesIO()
        buf.write(_MAGIC)
        pickle.dump(
            {
                "v": 1,
                "block_size": int(payload["block_size"]),
                "chain": [list(map(int, blk)) for blk in payload["chain"]],
                "k": np.ascontiguousarray(payload["k"]),
                "v_pool": np.ascontiguousarray(payload["v_pool"]),
            },
            buf, protocol=pickle.HIGHEST_PROTOCOL)
        return buf.getvalue()

    @staticmethod
    def decode(blob: bytes) -> dict:
        if not isinstance(blob, (bytes, bytearray, memoryview)):
            raise KVCodecError(f"expected bytes, got {type(blob).__name__}")
        blob = bytes(blob)
        if blob[:4] != _MAGIC:
            raise KVCodecError("bad magic: not a KV block frame")
        try:
            payload = pickle.loads(blob[4:])
        except Exception as exc:
            raise KVCodecError(f"corrupt KV block frame: {exc}") from exc
        if payload.get("v") != 1:
            raise KVCodecError(f"unknown KV frame version {payload.get('v')}")
        k, v = payload["k"], payload["v_pool"]
        n = len(payload["chain"])
        bs = payload["block_size"]
        if k.shape != v.shape or k.shape[1] != n or k.shape[2] != bs:
            raise KVCodecError(
                f"frame shape mismatch: k{k.shape} v{v.shape} vs "
                f"{n} chain blocks of size {bs}")
        return payload

    @staticmethod
    def try_decode(blob) -> Optional[dict]:
        """Decode-or-None: the decode path treats a bad handoff as a
        cache miss (re-prefill), never a failed request."""
        try:
            return KVBlockCodec.decode(blob)
        except KVCodecError:
            return None
