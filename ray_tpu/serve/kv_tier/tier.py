"""Tiered spill cache for refcount-0 sealed KV blocks.

Attached to a ``PagedKVCache`` (``cache.attach_tier``), this catches
blocks the allocator would otherwise destroy under pressure and keeps
their content reachable in SPILLED state:

  device pool ──evict──▶ host tier (numpy, LRU, bounded blocks)
                           │ overflow
                           ▼
                         store tier (object store when a worker context
                         exists — the hostd spill manager then handles
                         memory pressure for free — else spill files on
                         disk; LRU, bounded blocks)
                           │ overflow
                           ▼
                         dropped for real (the only lossy edge)

``match/adopt`` restores spilled chains on hit, so the effective prefix
cache is as large as host memory + the cluster object store instead of
the device pool.  All methods run under the owning engine's lock — the
tier itself is deliberately lock-free.
"""

from __future__ import annotations

import collections
import itertools
import os
import pickle
import tempfile
from typing import Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.util import events
from ray_tpu.util.metrics import Counter

_MET = None


def _metrics() -> dict:
    global _MET
    if _MET is None:
        _MET = {
            "spilled": Counter(
                "kv_tier_spilled_blocks",
                "Sealed KV blocks spilled out of the device pool"),
            "restored": Counter(
                "kv_tier_restored_blocks",
                "Spilled KV blocks restored into the device pool on a "
                "prefix hit"),
            "dropped": Counter(
                "kv_tier_dropped_blocks",
                "Spilled KV blocks dropped off the end of the last tier"),
        }
    return _MET


class KVTierCache:
    """Two LRU tiers keyed by the prefix index's content-addressed chain
    key ``(parent_hash, block_tokens)``.  Values are the block's K/V
    contents ``[n_layers, block_size, kv_heads, head_dim]`` per array —
    bit-exact round-trips are the whole point, so nothing is ever
    quantized or truncated."""

    def __init__(self, host_blocks: int = 256, store_blocks: int = 1024,
                 spill_dir: Optional[str] = None):
        self.host_blocks = max(int(host_blocks), 1)
        self.store_blocks = max(int(store_blocks), 0)
        self._host: "collections.OrderedDict[Tuple, Tuple]" = \
            collections.OrderedDict()          # key -> (k_np, v_np)
        self._store: "collections.OrderedDict[Tuple, Tuple]" = \
            collections.OrderedDict()          # key -> ("ref"|"file", handle)
        self._dir = spill_dir
        self._seq = itertools.count()
        self.counters = {"kv_tier_spilled_blocks": 0,
                         "kv_tier_restored_blocks": 0,
                         "kv_tier_dropped_blocks": 0}

    @classmethod
    def from_config(cls) -> "KVTierCache":
        from ray_tpu._private.config import GLOBAL_CONFIG as cfg
        return cls(host_blocks=cfg.kv_tier_host_blocks,
                   store_blocks=cfg.kv_tier_store_blocks)

    # ---------------- public surface (cache-facing) ----------------

    def __len__(self) -> int:
        return len(self._host) + len(self._store)

    def contains(self, key) -> bool:
        return key in self._host or key in self._store

    def put(self, key, k_np: np.ndarray, v_np: np.ndarray) -> None:
        """Spill one evicted block.  Newest entries win tier capacity;
        the overflow cascades host → store → dropped."""
        if self.contains(key):
            self._touch(key)
            return
        self._host[key] = (np.asarray(k_np), np.asarray(v_np))
        self.counters["kv_tier_spilled_blocks"] += 1
        _metrics()["spilled"].inc()
        events.record("kv", "spilled", host=len(self._host),
                      store=len(self._store))
        while len(self._host) > self.host_blocks:
            old_key, (ko, vo) = self._host.popitem(last=False)
            self._demote(old_key, ko, vo)

    def pop(self, key) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Restore hit: hand the block's contents back (removing them —
        the caller re-indexes a device copy) or None if the key aged
        out since it was matched."""
        pair = self._host.pop(key, None)
        if pair is None:
            pair = self._store_pop(key)
        if pair is None:
            return None
        self.counters["kv_tier_restored_blocks"] += 1
        _metrics()["restored"].inc()
        events.record("kv", "restored", host=len(self._host),
                      store=len(self._store))
        return pair

    def discard(self, key) -> None:
        """The device index re-sealed identical content: the spilled
        copy is stale freight, not a drop worth counting."""
        if self._host.pop(key, None) is not None:
            return
        handle = self._store.pop(key, None)
        if handle is not None:
            self._release(handle)

    def summary_hashes(self) -> List[int]:
        """Cumulative chain hash of every spilled link, oldest first
        (mirrors the device index's seal-order summary)."""
        return [hash(k) for k in
                itertools.chain(self._store, self._host)]

    # ---------------- internals ----------------

    def _touch(self, key) -> None:
        if key in self._host:
            self._host.move_to_end(key)
        elif key in self._store:
            self._store.move_to_end(key)

    def _demote(self, key, k_np, v_np) -> None:
        handle = self._store_put((k_np, v_np)) if self.store_blocks else None
        if handle is None:
            self._drop(1)
            return
        self._store[key] = handle
        while len(self._store) > self.store_blocks:
            _k, h = self._store.popitem(last=False)
            self._release(h)
            self._drop(1)

    def _drop(self, n: int) -> None:
        self.counters["kv_tier_dropped_blocks"] += n
        _metrics()["dropped"].inc(n)
        events.record("kv", "dropped", host=len(self._host),
                      store=len(self._store))

    def _store_put(self, pair) -> Optional[Tuple[str, object]]:
        """Second tier: the object store when this process has a worker
        context (holding the ObjectRef keeps the shm object alive, and
        the hostd spill manager moves it to disk under store pressure —
        exactly the machinery this tier wants to reuse), else a spill
        file on disk.  None means no second tier is available."""
        blob = pickle.dumps(pair, protocol=pickle.HIGHEST_PROTOCOL)
        try:
            import ray_tpu
            if ray_tpu.is_initialized():
                return ("ref", ray_tpu.put(blob))
        except Exception:
            pass
        try:
            if self._dir is None:
                self._dir = tempfile.mkdtemp(prefix="ray_tpu_kv_tier_")
            path = os.path.join(self._dir, f"kv-{next(self._seq)}.bin")
            with open(path, "wb") as f:
                f.write(blob)
            return ("file", path)
        except OSError:
            return None

    def _store_pop(self, key) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        handle = self._store.pop(key, None)
        if handle is None:
            return None
        kind, h = handle
        try:
            if kind == "ref":
                import ray_tpu
                blob = ray_tpu.get(h, timeout=5.0)
            else:
                with open(h, "rb") as f:
                    blob = f.read()
                os.unlink(h)
            return pickle.loads(blob)
        except Exception:
            return None         # store outage == cache miss, never an error

    def _release(self, handle) -> None:
        kind, h = handle
        if kind == "file":
            try:
                os.unlink(h)
            except OSError:
                pass
        # "ref": dropping the ObjectRef releases the store object.
