"""Disaggregated cache-aware serving: the cluster-wide KV tier.

Three coupled planes over the serving engines (see README
"Disaggregated serving & tiered KV cache"):

- ``codec.KVBlockCodec`` — wire format for sealed KV blocks + their
  hash-chain metadata, so a prefill replica's cache contents can be
  adopted bit-exactly by a decode replica's ``PagedKVCache``.
- ``tier.KVTierCache`` — host-memory → object-store/disk spill tiers
  for refcount-0 sealed blocks (the SPILLED prefix-index state), LRU
  pressure eviction across tiers, ``kv_tier_*`` counters.
- ``disagg`` — dedicated prefill / decode deployments and the
  ``DisaggLLMHandle`` front that ships sealed prefixes prefill→decode
  over the object plane and streams tokens with the existing
  mid-stream failover policy.
"""

from ray_tpu.serve.kv_tier.codec import KVBlockCodec, KVCodecError  # noqa: F401
from ray_tpu.serve.kv_tier.tier import KVTierCache  # noqa: F401
from ray_tpu.serve.kv_tier.disagg import (  # noqa: F401
    DisaggLLMHandle,
    PrefillLLMDeployment,
    DecodeLLMDeployment,
    run_disaggregated,
)
