"""Prefill/decode disaggregation over the serve plane.

Two dedicated deployments instead of one monolithic LLM replica set:

- ``PrefillLLMDeployment`` replicas run chunked prefill ONLY (never
  decode, never speculate).  A ``prefill()`` call seals the prompt's KV
  blocks into the replica's prefix index and returns them as one
  ``KVBlockCodec`` frame.
- ``DecodeLLMDeployment`` replicas stream tokens.  ``generate()``
  accepts an optional ``kv_handoff`` frame and adopts it into the local
  ``PagedKVCache`` as sealed prefix blocks before submitting, so decode
  starts from the shipped prefix instead of re-running prefill.
  Speculative decoding (when enabled) runs purely on these replicas.

``DisaggLLMHandle`` fronts both: it runs the prefill hop, ships the
sealed frame decode-ward (bytes over the serve arg path — big frames
automatically ride the native shm object plane), and streams tokens
with the existing ``llm_stream_resume`` mid-stream failover.  Every
failure mode of the handoff degrades to correctness, never an error:

- prefill replica death → ``kv/handoff_lost`` + heal, decode replica
  re-prefills locally (token-exact by construction — the KV contents
  are a pure function of the prompt and the shared weights);
- a corrupt/truncated frame → ``KVBlockCodec.try_decode`` returns None,
  decode re-prefills;
- decode replica death mid-stream → ``llm_stream_resume`` resubmits
  with the produced suffix appended (``kv_handoff`` stays in kwargs:
  adoption is idempotent, so the healed replica imports the same frame
  and re-prefills only the produced tail).
"""

from __future__ import annotations

from typing import List, Optional

import ray_tpu
from ray_tpu.serve.api import deployment, run as serve_run
from ray_tpu.serve.kv_tier.codec import KVBlockCodec
from ray_tpu.serve.llm import llm_stream_resume
from ray_tpu.util import events, spans


@deployment(name="llm-prefill", max_concurrent_queries=64)
class PrefillLLMDeployment:
    """Prefill-only replica: seals prompt KV, exports sealed frames.

    Runs no decode steps for callers — ``max_new_tokens`` is pinned to
    the prefill-only path — so its lanes turn over at prefill latency
    and a burst of long cold prompts never sits behind decode steps."""

    def __init__(self, model="gpt", config="nano", params=None, *,
                 max_lanes: int = 8, block_size: int = 16,
                 num_blocks: Optional[int] = None,
                 max_seq_len: Optional[int] = None,
                 prefill_chunk: int = 32, seed: int = 0,
                 kv_tier: Optional[bool] = None):
        from ray_tpu.inference import InferenceEngine  # jax: replica-only
        self._engine = InferenceEngine(
            model, config, params, max_lanes=max_lanes,
            block_size=block_size, num_blocks=num_blocks,
            max_seq_len=max_seq_len, prefill_chunk=prefill_chunk,
            seed=seed, prefix_cache=True, spec_k=0, kv_tier=kv_tier)

    def prefill(self, prompt, seed: Optional[int] = None,
                _deadline_s: Optional[float] = None) -> Optional[bytes]:
        """Chunked-prefill `prompt`, seal its blocks, return them as one
        encoded KV frame (None when the prompt is too short to seal a
        single full block — the decode side just prefills it all)."""
        prompt = [int(t) for t in prompt]
        handle = self._engine.prefill(prompt, seed=seed,
                                      deadline_s=_deadline_s)
        handle.tokens(timeout=_deadline_s)   # drain: no tokens, by design
        payload = self._engine.export_prefix(prompt)
        if payload is None:
            return None
        return KVBlockCodec.encode(payload)

    def prefix_summary(self) -> dict:
        return self._engine.prefix_summary()

    def stats(self) -> dict:
        return self._engine.stats()


@deployment(name="llm-decode", max_concurrent_queries=64)
class DecodeLLMDeployment:
    """Decode replica: adopts shipped prefixes, streams tokens.

    ``generate`` keeps ``LLMDeployment.generate``'s exact signature
    prefix so ``llm_stream_resume`` works unchanged; ``kv_handoff``
    rides kwargs through a mid-stream resume and re-imports
    idempotently on the healed replica."""

    def __init__(self, model="gpt", config="nano", params=None, *,
                 max_lanes: int = 8, block_size: int = 16,
                 num_blocks: Optional[int] = None,
                 max_seq_len: Optional[int] = None,
                 prefill_chunk: int = 32, seed: int = 0,
                 speculative: bool = False, spec_k: Optional[int] = None,
                 draft_proposer="ngram", kv_tier: Optional[bool] = None):
        from ray_tpu._private.config import GLOBAL_CONFIG
        from ray_tpu.inference import InferenceEngine  # jax: replica-only
        if spec_k is None:
            spec_k = GLOBAL_CONFIG.spec_k if speculative else 0
        self._engine = InferenceEngine(
            model, config, params, max_lanes=max_lanes,
            block_size=block_size, num_blocks=num_blocks,
            max_seq_len=max_seq_len, prefill_chunk=prefill_chunk,
            seed=seed, prefix_cache=True, spec_k=int(spec_k),
            draft_proposer=draft_proposer,
            spec_adaptive=GLOBAL_CONFIG.spec_adaptive, kv_tier=kv_tier)

    def _adopt(self, kv_handoff) -> None:
        if kv_handoff is None:
            return
        payload = KVBlockCodec.try_decode(kv_handoff)
        if payload is None:
            return                       # bad frame == cache miss
        self._engine.import_prefix(payload)

    def generate(self, prompt, max_new_tokens: int = 16,
                 temperature: float = 0.0, eos_id: Optional[int] = None,
                 seed: Optional[int] = None, _produced_offset: int = 0,
                 _deadline_s: Optional[float] = None, kv_handoff=None):
        self._adopt(kv_handoff)
        handle = self._engine.submit(prompt, max_new_tokens,
                                     temperature=temperature,
                                     eos_id=eos_id, seed=seed,
                                     sample_offset=_produced_offset,
                                     deadline_s=_deadline_s)
        try:
            for tok in handle:
                yield int(tok)
        finally:
            handle.cancel()

    def __call__(self, prompt, max_new_tokens: int = 16,
                 temperature: float = 0.0, eos_id: Optional[int] = None,
                 seed: Optional[int] = None,
                 _deadline_s: Optional[float] = None,
                 kv_handoff=None) -> List[int]:
        self._adopt(kv_handoff)
        handle = self._engine.submit(prompt, max_new_tokens,
                                     temperature=temperature,
                                     eos_id=eos_id, seed=seed)
        return handle.tokens(timeout=_deadline_s)

    def prefix_summary(self) -> dict:
        return self._engine.prefix_summary()

    def stats(self) -> dict:
        return self._engine.stats()


class DisaggLLMHandle:
    """Front for a prefill deployment + a decode deployment.

    ``stream()`` is the disaggregated analogue of
    ``handle.options("generate", failover=llm_stream_resume).stream()``:
    prefill hop, KV frame handoff, then a failover-protected decode
    stream.  The handoff is best-effort by contract — any prefill-side
    failure degrades to a decode-side re-prefill.

    ``prefill_retry=False`` turns OFF the prefill hop's replica-death
    retry so a dying prefill replica exercises the degradation path
    instead of healing transparently (chaos gates use this)."""

    def __init__(self, prefill_handle, decode_handle, *,
                 prefill_retry: bool = True,
                 prefill_timeout_s: float = 60.0):
        self._prefill = prefill_handle
        self._decode = decode_handle
        self._prefill_retry = prefill_retry
        self._prefill_timeout_s = prefill_timeout_s

    def _prefill_frame(self, prompt, seed) -> Optional[bytes]:
        tok = spans.begin("kv", "handoff", tokens=len(prompt))
        try:
            if self._prefill_retry:
                frame = self._prefill.prefill.remote(
                    prompt, seed=seed).result(
                        timeout=self._prefill_timeout_s)
            else:
                tr = self._prefill._call("prefill", (prompt,),
                                         {"seed": seed})
                try:
                    frame = ray_tpu.get(tr.ref,
                                        timeout=self._prefill_timeout_s)
                finally:
                    tr._handle._done(tr._idx)
        except BaseException as e:
            # Lost handoff: record it, heal the prefill replica set for
            # the NEXT request, and let decode re-prefill this one.
            events.record("kv", "handoff_lost",
                          error=type(e).__name__, tokens=len(prompt))
            spans.end(tok, ok=False)
            try:
                self._prefill._on_replica_error()
            except Exception:
                pass
            return None
        spans.end(tok, ok=True, frame_bytes=len(frame) if frame else 0)
        return frame

    def stream(self, prompt, max_new_tokens: int = 16, *,
               temperature: float = 0.0, eos_id: Optional[int] = None,
               seed: Optional[int] = None):
        """Yield token ids: prefill→handoff→decode, failover-protected."""
        prompt = [int(t) for t in prompt]
        frame = self._prefill_frame(prompt, seed)
        kwargs = dict(temperature=temperature, eos_id=eos_id, seed=seed)
        if frame is not None:
            kwargs["kv_handoff"] = frame
        stream = self._decode.options(
            "generate", failover=llm_stream_resume).stream(
                prompt, max_new_tokens, **kwargs)
        for tok in stream:
            yield int(tok)

    def generate(self, prompt, max_new_tokens: int = 16, *,
                 temperature: float = 0.0, eos_id: Optional[int] = None,
                 seed: Optional[int] = None) -> List[int]:
        """Non-streaming convenience: drain stream() into a list."""
        return list(self.stream(prompt, max_new_tokens,
                                temperature=temperature, eos_id=eos_id,
                                seed=seed))

    def stats(self) -> dict:
        """Merged prefill/decode replica stats (first replica of each)."""
        out = {}
        for role, handle in (("prefill", self._prefill),
                             ("decode", self._decode)):
            try:
                out[role] = handle.stats.remote().result(timeout=30)
            except Exception:
                out[role] = None
        return out


def run_disaggregated(model="gpt", config="nano", *,
                      prefill_replicas: int = 1, decode_replicas: int = 1,
                      name: str = "llm", prefill_retry: bool = True,
                      **engine_kw) -> DisaggLLMHandle:
    """Deploy a prefill gang + a decode gang and return the front.

    `engine_kw` flows to both deployments; the speculative knobs
    (`speculative`, `spec_k`, `draft_proposer`) only reach the decode
    side — prefill replicas never speculate.  The prefill deployment is
    deployed first (deterministic worker-spawn ordinals for chaos)."""
    spec_keys = ("speculative", "spec_k", "draft_proposer")
    prefill_kw = {k: v for k, v in engine_kw.items() if k not in spec_keys}
    prefill_h = serve_run(
        PrefillLLMDeployment.options(
            name=f"{name}-prefill", num_replicas=prefill_replicas).bind(
                model=model, config=config, **prefill_kw))
    decode_h = serve_run(
        DecodeLLMDeployment.options(
            name=f"{name}-decode", num_replicas=decode_replicas).bind(
                model=model, config=config, **engine_kw))
    return DisaggLLMHandle(prefill_h, decode_h,
                           prefill_retry=prefill_retry)
