"""Serve internals: controller, replica, router/handle, HTTP proxy.

Reference parity: python/ray/serve/_private/ — ServeController
(controller.py:71) reconciles DeploymentState (deployment_state.py:1006);
replicas host user code (replica.py:268); Router picks replicas with
max_concurrent_queries backpressure (router.py:224); HTTPProxy is the
ASGI ingress (http_proxy.py:434).  Config propagation here is pull-based
with revalidation on failure (the reference uses long-poll; same
eventual-consistency contract, no blocked actor threads).

Graceful degradation (reference: serve's replica graceful_shutdown_* +
DeploymentResponseGenerator retry semantics):

- Replica lifecycle STARTING -> RUNNING -> DRAINING -> DEAD.  Downscale,
  redeploy, delete and shutdown move victims to DRAINING: out of the
  routing table immediately, killed only once ``ongoing_requests()``
  quiesces or ``serve_drain_deadline_s`` lapses.
- Mid-stream failover: ``DeploymentHandle.stream``/``stream_async``
  record delivered chunks; on replica loss they heal the replica set and
  resubmit under the handle's failover policy ("replay" skips already-
  delivered chunks; a callable policy rewrites the request — the LLM
  path appends produced tokens to the prompt so the prefix cache makes
  re-prefill cheap and the resumed stream is token-exact).
- Deadline propagation: a per-request deadline bounds admission waits,
  travels to the replica (which aborts not-yet-started work and evicts
  expired streams), and stops retries/failovers.
- Load shedding: a bounded per-deployment admission queue fast-fails
  with ServeOverloadedError (+ retry-after hint) instead of stacking
  unbounded waiters, and ``_pick_replica`` is power-of-two-choices on
  in-flight counts.
"""

from __future__ import annotations

import itertools
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu.exceptions import (
    ActorDiedError, ActorUnavailableError, ReplicaStreamLostError,
    ServeOverloadedError, TaskError)
from ray_tpu.util import events, spans, tracing

CONTROLLER_NAME = "SERVE_CONTROLLER"
SERVE_NAMESPACE = "serve"

# Replica lifecycle states (reference: serve ReplicaState).
REPLICA_STARTING = "STARTING"
REPLICA_RUNNING = "RUNNING"
REPLICA_DRAINING = "DRAINING"
REPLICA_DEAD = "DEAD"

_SERVE_MET = None


# SLO latency buckets for the serve plane (queue wait is often sub-ms;
# end-to-end can run to minutes under backpressure).
_SLO_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0)


def _serve_metrics() -> dict:
    global _SERVE_MET
    if _SERVE_MET is None:
        from ray_tpu.util.metrics import Counter, Gauge, Histogram
        _SERVE_MET = {
            "drained": Counter(
                "serve_replicas_drained",
                "Replicas retired after graceful draining"),
            "drain_deadline_kills": Counter(
                "serve_drain_deadline_kills",
                "Draining replicas force-killed at the drain deadline"),
            "draining": Gauge(
                "serve_draining_replicas",
                "Replicas currently in the DRAINING state"),
            "shed": Counter(
                "serve_requests_shed",
                "Requests fast-failed with ServeOverloadedError at the "
                "admission queue"),
            "failovers": Counter(
                "serve_stream_failovers",
                "Streaming requests resubmitted after replica loss"),
            "retries": Counter(
                "serve_request_retries",
                "Unary requests retried through a healed replica set"),
            "queue_wait": Histogram(
                "serve_queue_wait_s",
                "Admission wait (request arrival -> replica acquired)",
                buckets=_SLO_BUCKETS),
            "e2e": Histogram(
                "serve_e2e_s",
                "Unary request end-to-end latency (call -> result)",
                buckets=_SLO_BUCKETS),
        }
    return _SERVE_MET


def _is_replica_loss(e: BaseException) -> bool:
    """True for errors that mean "the replica (or its stream state) is
    gone" — the triggers for heal + resubmit.  A ReplicaStreamLostError
    raised replica-side crosses the wire wrapped in TaskError, so the
    traceback string is checked too."""
    if isinstance(e, (ActorDiedError, ActorUnavailableError,
                      ReplicaStreamLostError)):
        return True
    if isinstance(e, TaskError):
        return "ReplicaStreamLostError" in (e.traceback_str or "")
    return False


def _chaos_kill_point() -> None:
    """Serve-plane chaos interposition: a replica process draws one
    deterministic kill verdict per serve event (request dispatch or
    stream-chunk pull) — see fault_injection.kill_replica."""
    from ray_tpu._private.fault_injection import get_chaos
    chaos = get_chaos()
    if chaos is not None and chaos.kill_replica():
        import logging
        import os
        logging.getLogger("ray_tpu").warning(
            "chaos: killing serve replica process")
        events.record("serve", "chaos_kill", pid=os.getpid())
        events.dump_crash("chaos_kill_replica")
        os._exit(1)


@dataclass
class AutoscalingConfig:
    """Queue-depth replica autoscaling (reference:
    serve/_private/autoscaling_policy.py + serve/config.py
    AutoscalingConfig): desired = ceil(total_ongoing_requests /
    target_ongoing_requests), clamped to [min, max], applied after the
    respective delay has elapsed continuously."""

    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 0.2
    downscale_delay_s: float = 2.0


@dataclass
class DeploymentConfig:
    name: str
    num_replicas: int = 1
    max_concurrent_queries: int = 100
    ray_actor_options: dict = field(default_factory=dict)
    user_config: Any = None
    autoscaling_config: Optional[AutoscalingConfig] = None
    version: int = 0
    # Bound on requests WAITING for a replica slot (per deployment, per
    # client process) before ServeOverloadedError sheds the excess.
    # None = the serve_queue_length config default; 0 = unbounded.
    queue_limit: Optional[int] = None


@ray_tpu.remote
class ReplicaActor:
    """Hosts one copy of the user's callable (reference: replica.py:268).

    An ASYNC actor: the actor's persistent event loop hosts every
    in-flight request, exactly as the reference replica runs a user event
    loop — so an async deployment overlaps its awaits WITHIN one replica
    (10 concurrent requests that each await 100ms take ~100ms, not ~1s).
    Sync callables run on a thread pool so they can never stall the loop
    (and so blocking helpers like @serve.batch keep working)."""

    def __init__(self, cls_or_fn, init_args, init_kwargs, user_config=None,
                 max_concurrent_queries: int = 100):
        import inspect
        from concurrent.futures import ThreadPoolExecutor
        if inspect.isclass(cls_or_fn):
            self._callable = cls_or_fn(*init_args, **(init_kwargs or {}))
        else:
            self._callable = cls_or_fn
        if user_config is not None and hasattr(self._callable,
                                               "reconfigure"):
            self._callable.reconfigure(user_config)
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, min(max_concurrent_queries, 64)),
            thread_name_prefix="replica-sync")
        self._ongoing = 0
        # In-progress streaming responses: stream id -> async generator
        # (reference: replica-side generator streaming, replica.py's
        # handle_request_streaming).  Chunks are PULLED by the caller
        # (proxy or handle) one next_chunk() at a time — incremental by
        # construction, replica-pinned by the router.
        self._streams: dict = {}
        self._stream_ids = itertools.count(1)
        # sid -> absolute monotonic deadline (or None) for deadline
        # enforcement between chunk pulls.
        self._stream_deadlines: dict = {}
        # Streams cancelled while their sync generator was mid-pull on
        # the thread pool (generators cannot be closed while running);
        # the in-flight next_chunk closes them once the pull returns.
        self._cancelled: set = set()
        # method name -> whether its signature accepts `_deadline_s`
        # (deadline-aware deployments get the remaining budget passed in).
        self._deadline_aware: dict = {}

    async def handle_request(self, method_name, args, kwargs,
                             stream: bool = False,
                             deadline_s: Optional[float] = None):
        import asyncio
        import inspect
        _chaos_kill_point()
        # Traced requests get a serve/replica span around the user-code
        # invocation (child of the task exec span via the contextvar set
        # by the worker); `ongoing` captures concurrent load at entry.
        tok = (spans.begin("serve", "replica",
                           method=method_name or "__call__",
                           ongoing=self._ongoing)
               if tracing.current_context() is not None else None)
        cv = (tracing._ctx.set((tok.trace_id, tok.sid))
              if tok is not None and tok.trace_id else None)
        self._ongoing += 1  # loop-thread only: no lock needed
        try:
            target = self._callable
            if method_name and method_name != "__call__":
                target = getattr(self._callable, method_name)
            elif not callable(target):
                raise TypeError("deployment object is not callable")
            kwargs = kwargs or {}
            deadline = None
            if deadline_s is not None:
                if deadline_s <= 0:
                    # Already past the request deadline before any work
                    # started: abort pre-dispatch instead of burning a
                    # replica slot on a result nobody will wait for.
                    raise TimeoutError(
                        f"request deadline exceeded before "
                        f"{method_name or '__call__'!r} started")
                deadline = time.monotonic() + deadline_s
                mname = method_name or "__call__"
                aware = self._deadline_aware.get(mname)
                if aware is None:
                    try:
                        aware = ("_deadline_s"
                                 in inspect.signature(target).parameters)
                    except (TypeError, ValueError):
                        aware = False
                    self._deadline_aware[mname] = aware
                if aware:
                    kwargs["_deadline_s"] = deadline_s
            if inspect.isasyncgenfunction(target) or inspect.isgeneratorfunction(target):
                if not stream:
                    # Non-streaming caller (handle.remote(), plain HTTP
                    # dispatch): a stream ticket would leak its slot
                    # (no one would pull chunks), and materializing an
                    # unbounded generator would wedge the replica —
                    # reference behavior: require the streaming API.
                    raise TypeError(
                        f"method {method_name or '__call__'!r} is a "
                        f"generator; call it via handle.stream() / "
                        f"stream_async() (or the ASGI route), not "
                        f".remote()")
                # Streaming method: stash the generator and hand back a
                # stream ticket; the in-flight slot stays charged until
                # the consumer drains or cancels (next_chunk below).
                gen = target(*args, **kwargs)
                sid = next(self._stream_ids)
                self._streams[sid] = gen
                self._stream_deadlines[sid] = deadline
                self._ongoing += 1   # held until stream end
                spans.end(tok, stream=True)
                tok = None
                return {"__serve_stream__": sid}
            if inspect.iscoroutinefunction(target) or (
                    not inspect.isfunction(target)
                    and not inspect.ismethod(target)
                    and inspect.iscoroutinefunction(
                        getattr(target, "__call__", None))):
                return await target(*args, **kwargs)
            loop = asyncio.get_running_loop()
            # run_in_executor does not propagate contextvars: carry the
            # request's trace context onto the pool thread so engine
            # events recorded inside sync deployments join the trace.
            import contextvars
            ctx = contextvars.copy_context()
            result = await loop.run_in_executor(
                self._pool, lambda: ctx.run(target, *args, **kwargs))
            if inspect.iscoroutine(result):
                # Sync wrapper handing back a coroutine: finish it here.
                return await result
            return result
        finally:
            self._ongoing -= 1
            spans.end(tok)
            if cv is not None:
                tracing._ctx.reset(cv)

    async def next_chunk(self, sid: int):
        """Pull ONE chunk of stream `sid`: {"chunk": value} or
        {"done": True}.  Sync generators advance on the thread pool so
        they cannot stall the replica loop.  An UNKNOWN sid means this
        replica restarted and lost its in-memory streams — raise
        ReplicaStreamLostError so the handle fails over instead of
        silently truncating the stream with a fake "done"."""
        import asyncio
        import inspect
        _chaos_kill_point()
        gen = self._streams.get(sid)
        if gen is None:
            raise ReplicaStreamLostError(sid)
        deadline = self._stream_deadlines.get(sid)
        if deadline is not None and time.monotonic() > deadline:
            # Past the request deadline: abort replica-side — closing
            # the generator runs its cleanup (the LLM path cancels its
            # GenerationHandle on GeneratorExit, evicting the engine
            # lane) even if the consumer has already given up.
            await self.cancel_stream(sid)
            raise TimeoutError(
                f"stream {sid}: request deadline exceeded")
        try:
            if inspect.isasyncgen(gen):
                chunk = await gen.__anext__()
            else:
                # StopIteration cannot cross a Future: pull behind a
                # sentinel on the thread pool.
                def _pull():
                    try:
                        return True, gen.__next__()
                    except StopIteration:
                        return False, None
                import contextvars
                loop = asyncio.get_running_loop()
                ctx = contextvars.copy_context()
                alive, chunk = await loop.run_in_executor(
                    self._pool, lambda: ctx.run(_pull))
                if sid in self._cancelled:
                    # cancel_stream caught this generator mid-pull and
                    # could not close it; it is suspended now.
                    self._cancelled.discard(sid)
                    try:
                        gen.close()
                    except Exception:
                        pass
                    return {"done": True}
                if not alive:
                    self._finish_stream(sid)
                    return {"done": True}
            return {"chunk": chunk}
        except StopAsyncIteration:
            self._finish_stream(sid)
            return {"done": True}
        except Exception:
            self._finish_stream(sid)
            raise

    async def cancel_stream(self, sid: int):
        gen = self._streams.get(sid)
        if gen is not None:
            try:
                if hasattr(gen, "aclose"):
                    await gen.aclose()
                else:
                    gen.close()
            except ValueError:
                # Sync generator currently executing on the thread pool:
                # close() is illegal mid-frame.  Tombstone the sid; the
                # in-flight next_chunk closes it when the pull returns.
                self._cancelled.add(sid)
            except Exception:
                pass
            self._finish_stream(sid)
        return True

    def _finish_stream(self, sid: int) -> None:
        self._stream_deadlines.pop(sid, None)
        if self._streams.pop(sid, None) is not None:
            self._ongoing -= 1

    async def ongoing_requests(self) -> int:
        """Autoscaling load signal (reference: replicas report queue
        metrics to the controller)."""
        return self._ongoing

    def reconfigure(self, user_config):
        if hasattr(self._callable, "reconfigure"):
            self._callable.reconfigure(user_config)
        return True

    def ping(self):
        return "pong"


@ray_tpu.remote(max_concurrency=64)
class ServeController:
    """Deployment table + reconciliation (reference: controller.py:71,
    DeploymentStateManager deployment_state.py:1864).  Threaded actor:
    the control loop (autoscaling) and long-poll waiters run alongside
    deploy/routing calls; the deployment table is lock-protected."""

    def __init__(self):
        # name -> {"config": DeploymentConfig, "replicas": [handles],
        #          "deployed_def": (cls, args, kwargs)}
        self._deployments: Dict[str, dict] = {}
        self._lock = threading.RLock()
        self._version = 0
        self._version_cv = threading.Condition(self._lock)
        self._loop_started = False
        self._stopped = False
        # name -> (desired_replicas, since_monotonic) scale intent
        self._scale_intent: Dict[str, tuple] = {}
        # Graceful-drain records, appended whenever a replica leaves the
        # routing table with work possibly in flight:
        # {"name", "replica", "since", "deadline", "zero_streak"}
        self._draining: List[dict] = []
        self._drained_total = 0
        self._drain_deadline_kills = 0

    def _bump_version(self):
        with self._version_cv:
            self._version += 1
            self._version_cv.notify_all()

    # ---------------- long-poll config plane ----------------

    def poll_routing(self, name: str, known_version: int,
                     timeout_s: float = 10.0):
        """Block until the config version moves past known_version (or
        timeout), then return the routing table (reference:
        _private/long_poll.py:68 LongPollHost)."""
        deadline = time.monotonic() + timeout_s
        with self._version_cv:
            while self._version == known_version and not self._stopped:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._version_cv.wait(remaining)
        return self.get_routing(name)

    # ---------------- autoscaling control loop ----------------

    def run_control_loop(self, interval_s: float = 0.2):
        """Reference: the controller's run loop (controller.py) driving
        autoscaling_policy decisions.  Runs on one of this threaded
        actor's pool threads forever."""
        with self._lock:
            if self._loop_started:
                return False
            self._loop_started = True
        while not self._stopped:
            try:
                self._autoscale_pass()
            except Exception:
                pass
            try:
                self._drain_pass()
            except Exception:
                pass
            time.sleep(interval_s)
        return True

    # ---------------- graceful draining ----------------

    def _drain_replica(self, name: str, replica) -> None:
        """Move one replica to DRAINING: the caller has already removed
        it from the routing table; it keeps serving its in-flight
        requests and streams, and _drain_pass kills it only once
        ongoing_requests() quiesces (or the drain deadline lapses)."""
        key = replica._actor_id.binary()
        rec = {"name": name, "replica": replica,
               "since": time.monotonic(),
               "deadline": (time.monotonic()
                            + GLOBAL_CONFIG.serve_drain_deadline_s),
               "zero_streak": 0}
        with self._lock:
            if any(r["replica"]._actor_id.binary() == key
                   for r in self._draining):
                return  # already draining (reconcile/delete race)
            self._draining.append(rec)
            n = len(self._draining)
            # Defense-in-depth: if any path leaves the victim visible in
            # a routing snapshot, its state says DRAINING and the router
            # filters it before scoring candidates.
            entry = self._deployments.get(name)
            if entry is not None and key in entry.get("states", {}):
                entry["states"][key] = REPLICA_DRAINING
        _serve_metrics()["draining"].set(n)
        events.record("serve", "drain_start", deployment=name)

    def _drain_pass(self, immediate: bool = False) -> int:
        """One sweep over DRAINING replicas: fan out ongoing_requests()
        probes, kill every replica that has quiesced or whose drain
        deadline lapsed, and return how many are still draining.

        Quiescence needs TWO consecutive zero observations — a single
        zero can race a request dispatched by a router that has not yet
        seen the post-drain routing table.  `immediate` (the shutdown
        path) kills on the first zero."""
        with self._lock:
            records = list(self._draining)
        if not records:
            return 0
        refs = [r["replica"].ongoing_requests.remote() for r in records]
        try:
            ready, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=5)
        except Exception:
            ready = []
        ready_ids = {ref.id for ref in ready}
        now = time.monotonic()
        met = _serve_metrics()
        for rec, ref in zip(records, refs):
            kill = dead = False
            if ref.id in ready_ids:
                try:
                    ongoing = ray_tpu.get(ref, timeout=5)
                except Exception:
                    dead = True  # died on its own: nothing left to drain
                else:
                    if ongoing <= 0:
                        rec["zero_streak"] += 1
                        if immediate or rec["zero_streak"] >= 2:
                            kill = True
                    else:
                        rec["zero_streak"] = 0
            if not dead and not kill and now >= rec["deadline"]:
                kill = True
                met["drain_deadline_kills"].inc()
                self._drain_deadline_kills += 1
                events.record("serve", "drain_deadline_kill",
                              deployment=rec.get("name"))
            if not (kill or dead):
                continue
            if kill:
                try:
                    ray_tpu.kill(rec["replica"])
                except Exception:
                    pass
            with self._lock:
                if rec in self._draining:
                    self._draining.remove(rec)
                    self._drained_total += 1
            met["drained"].inc()
            events.record("serve", "drained", deployment=rec.get("name"))
        with self._lock:
            remaining = len(self._draining)
        met["draining"].set(remaining)
        return remaining

    def drain_stats(self):
        with self._lock:
            return {"draining": len(self._draining),
                    "drained_total": self._drained_total,
                    "deadline_kills": self._drain_deadline_kills}

    def _autoscale_pass(self):
        with self._lock:
            entries = {n: e for n, e in self._deployments.items()
                       if e["config"].autoscaling_config is not None}
        for name, entry in entries.items():
            cfg: DeploymentConfig = entry["config"]
            auto: AutoscalingConfig = cfg.autoscaling_config
            replicas = list(entry["replicas"])
            if not replicas:
                continue
            total = 0
            for r in replicas:
                try:
                    total += ray_tpu.get(r.ongoing_requests.remote(),
                                         timeout=5)
                except Exception:
                    pass
            import math
            desired = max(auto.min_replicas,
                          min(auto.max_replicas,
                              math.ceil(total /
                                        max(auto.target_ongoing_requests,
                                            1e-9))))
            now = time.monotonic()
            current = len(replicas)
            if desired == current:
                self._scale_intent.pop(name, None)
                continue
            intent = self._scale_intent.get(name)
            if intent is None or intent[0] != desired:
                self._scale_intent[name] = (desired, now)
                continue
            delay = (auto.upscale_delay_s if desired > current
                     else auto.downscale_delay_s)
            if now - intent[1] < delay:
                continue
            with self._lock:
                entry = self._deployments.get(name)
                if entry is None:
                    continue
                entry["config"].num_replicas = desired
            self._reconcile(name)
            self._scale_intent.pop(name, None)
            self._bump_version()

    def deploy(self, config: DeploymentConfig, cls_or_fn, init_args,
               init_kwargs):
        with self._lock:
            entry = self._deployments.get(config.name)
            if entry is None:
                entry = {"config": config, "replicas": [],
                         "deployed_def": (cls_or_fn, init_args, init_kwargs)}
                self._deployments[config.name] = entry
            else:
                entry["config"] = config
                entry["deployed_def"] = (cls_or_fn, init_args, init_kwargs)
                # New code/config version: existing replicas are stale and
                # get replaced below (reference: deployment_state.py rolling
                # version replacement).
                entry["def_version"] = entry.get("def_version", 0) + 1
            if config.autoscaling_config is not None:
                config.num_replicas = max(
                    config.autoscaling_config.min_replicas,
                    min(config.num_replicas,
                        config.autoscaling_config.max_replicas))
        self._reconcile(config.name)
        self._bump_version()
        return {"name": config.name, "replicas": len(entry["replicas"])}

    def _reconcile(self, name: str):
        """Converge the replica set.  Blocking actor RPCs (pings, replica
        construction) run WITHOUT the table lock — holding it would stall
        every get_routing/poll_routing for the duration of a replica cold
        start.  A per-deployment lock serializes concurrent reconciles."""
        with self._lock:
            entry = self._deployments.get(name)
            if entry is None:
                return
            rlock = entry.setdefault("_rlock", threading.Lock())
        with rlock:
            with self._lock:
                entry = self._deployments.get(name)
                if entry is None:
                    return
                config: DeploymentConfig = entry["config"]
                cls_or_fn, args, kwargs = entry["deployed_def"]
                replicas = list(entry["replicas"])
                def_version = entry.setdefault("def_version", 0)
                vers = dict(entry.setdefault("replica_vers", {}))
            # ---- unlocked: health checks / drains / constructions ----
            to_drain = []  # leave routing now, die only after quiescing
            candidates = []
            for r in replicas:
                key = r._actor_id.binary()
                if vers.get(key, def_version) != def_version:
                    # Stale code/config version: DRAIN, don't hard-kill —
                    # requests in flight on the old version finish
                    # (reference: rolling version replacement +
                    # graceful_shutdown_wait_loop_s).
                    vers.pop(key, None)
                    to_drain.append(r)
                    continue
                candidates.append(r)
            # Health sweep: fan the pings out and collect them with one
            # bounded wait() instead of serial 10s-timeout gets (N dead
            # replicas used to cost N*10s of controller stall).
            ping_refs = [r.ping.remote() for r in candidates]
            ready_ids = set()
            if ping_refs:
                try:
                    ready, _ = ray_tpu.wait(
                        ping_refs, num_returns=len(ping_refs), timeout=10)
                    ready_ids = {ref.id for ref in ready}
                except Exception:
                    pass
            replicas = []
            for r, ref in zip(candidates, ping_refs):
                ok = False
                if ref.id in ready_ids:
                    try:
                        ray_tpu.get(ref, timeout=10)
                        ok = True
                    except Exception:
                        ok = False
                if ok:
                    replicas.append(r)
                else:
                    vers.pop(r._actor_id.binary(), None)
            opts = dict(config.ray_actor_options)
            started = []
            while len(replicas) + len(started) < config.num_replicas:
                actor = ReplicaActor.options(
                    num_cpus=opts.get("num_cpus", 0.1),
                    num_tpus=opts.get("num_tpus"),
                    resources=opts.get("resources"),
                    max_restarts=2,
                    # Replicas must execute up to max_concurrent_queries
                    # requests at once, or @serve.batch could never
                    # accumulate a batch.
                    max_concurrency=config.max_concurrent_queries,
                ).remote(cls_or_fn, args, kwargs, config.user_config,
                         config.max_concurrent_queries)
                started.append(actor)
                vers[actor._actor_id.binary()] = def_version
            while len(replicas) > config.num_replicas:
                # Downscale: victims drain instead of dropping their
                # in-flight requests on the floor.
                victim = replicas.pop()
                vers.pop(victim._actor_id.binary(), None)
                to_drain.append(victim)
            # Verify new replicas constructed (surface user __init__
            # errors) before committing them to the routing table; fan
            # out first so N cold starts overlap.
            verify = [r.ping.remote() for r in started]
            if verify:
                try:
                    ray_tpu.wait(verify, num_returns=len(verify),
                                 timeout=120)
                except Exception:
                    pass
            for ref in verify:
                ray_tpu.get(ref, timeout=120)
            replicas.extend(started)
            with self._lock:
                entry = self._deployments.get(name)
                if entry is None:
                    # Deployment deleted concurrently: its old replicas
                    # are already draining via delete_deployment; the
                    # freshly-started ones never served and die now.
                    for r in started:
                        try:
                            ray_tpu.kill(r)
                        except Exception:
                            pass
                    return
                entry["replicas"][:] = replicas
                entry["replica_vers"] = vers
                entry["states"] = {r._actor_id.binary(): REPLICA_RUNNING
                                   for r in replicas}
            for victim in to_drain:
                self._drain_replica(name, victim)

    def get_routing(self, name: str):
        with self._lock:
            entry = self._deployments.get(name)
            if entry is None:
                return None
            return {"replicas": list(entry["replicas"]),
                    # Per-replica lifecycle states ride the routing table
                    # so the client-side router can filter non-RUNNING
                    # replicas out of its candidate sample (a DRAINING
                    # victim must never attract new traffic — prefix
                    # affinity included).
                    "states": {k: v
                               for k, v in entry.get("states", {}).items()},
                    "max_concurrent_queries":
                        entry["config"].max_concurrent_queries,
                    "queue_limit": entry["config"].queue_limit,
                    "version": self._version}

    def list_deployments(self):
        with self._lock:
            draining: Dict[str, int] = {}
            for rec in self._draining:
                draining[rec["name"]] = draining.get(rec["name"], 0) + 1
            out = {}
            for name, e in self._deployments.items():
                states: Dict[str, int] = {}
                for s in e.get("states", {}).values():
                    states[s] = states.get(s, 0) + 1
                states[REPLICA_DRAINING] = draining.get(name, 0)
                out[name] = {"num_replicas": len(e["replicas"]),
                             "target": e["config"].num_replicas,
                             "states": states}
            return out

    def delete_deployment(self, name: str):
        with self._lock:
            entry = self._deployments.pop(name, None)
        if entry is None:
            return False
        # Out of the routing table NOW; replicas finish their in-flight
        # work and are reaped by the drain pump (or the drain deadline).
        self._bump_version()
        for r in entry["replicas"]:
            self._drain_replica(name, r)
        return True

    def heal(self, name: str):
        """Router-reported replica failure: reconcile this deployment."""
        self._reconcile(name)
        self._bump_version()
        return True

    def shutdown(self):
        self._stopped = True
        with self._version_cv:
            self._version_cv.notify_all()
        for name in list(self._deployments):
            self.delete_deployment(name)
        # Synchronous graceful drain: in-flight requests get until the
        # drain deadline; whatever remains is force-killed so shutdown
        # always terminates.
        deadline = time.monotonic() + GLOBAL_CONFIG.serve_drain_deadline_s
        while (self._drain_pass(immediate=True)
               and time.monotonic() < deadline):
            time.sleep(0.05)
        with self._lock:
            leftovers = list(self._draining)
            self._draining.clear()
        for rec in leftovers:
            try:
                ray_tpu.kill(rec["replica"])
            except Exception:
                pass
        return True


class _RouterState:
    """Per-deployment routing state SHARED by every handle in the process:
    one replica table, one in-flight map, one long-poll thread — however
    many DeploymentHandle facades exist (reference: handles share the
    Router; r2 review: per-handle pollers leaked a thread per
    handle.options() call)."""

    def __init__(self, name: str):
        self.name = name
        self.lock = threading.Lock()
        self.replicas: List = []
        self.max_q = 100
        self.rr = 0
        # In-flight counts keyed by stable replica identity (actor id).
        self.in_flight: Dict[bytes, int] = {}
        # Requests waiting for a replica slot (the bounded admission
        # queue load shedding is measured against).
        self.pending = 0
        self.queue_limit: Optional[int] = None
        self.fetched_at = 0.0
        self.known_version = -1
        self.poller: Optional[threading.Thread] = None
        # Replica lifecycle states from the routing table (actor id ->
        # "RUNNING"/"DRAINING"); non-RUNNING replicas are filtered out
        # of the candidate sample.
        self.states: Dict[bytes, str] = {}
        # Prefix-cache-aware routing (serve_prefix_routing): the scrape
        # thread fills actor id -> {"hashes": set, "block_size", "ts"};
        # summaries older than serve_prefix_staleness_s never score.
        self.prefix: Dict[bytes, dict] = {}
        self.prefix_thread: Optional[threading.Thread] = None
        self.prefix_disabled = False


_router_states: Dict[str, _RouterState] = {}
_router_states_lock = threading.Lock()


# One small shared executor for orphan-stream reaps: each reap can block
# up to 60s on the abandoned call, and a thread PER abandoned request is
# an unbounded leak under a disconnect storm.  A bounded queue-backed
# pool serializes the excess instead; reaps are cleanup, not latency-
# sensitive.
_reaper_pool = None
_reaper_pool_lock = threading.Lock()


def _get_reaper_pool():
    global _reaper_pool
    if _reaper_pool is None:
        with _reaper_pool_lock:
            if _reaper_pool is None:
                from concurrent.futures import ThreadPoolExecutor
                _reaper_pool = ThreadPoolExecutor(
                    max_workers=4, thread_name_prefix="serve-stream-reaper")
    return _reaper_pool


def _reap_orphan_stream(replica, req_ref) -> None:
    """The caller abandoned a handle_request whose ticket it never saw.
    If that call registered a stream replica-side, its generator and
    in-flight slot would be held forever (no one knows the sid) — wait
    out the call on the shared reaper pool and cancel any stream it
    opened."""
    def _reap():
        try:
            ticket = ray_tpu.get(req_ref, timeout=60)
            if isinstance(ticket, dict) and "__serve_stream__" in ticket:
                ray_tpu.get(replica.cancel_stream.remote(
                    ticket["__serve_stream__"]), timeout=10)
        except Exception:
            pass  # replica died or call failed: nothing leaked
    _get_reaper_pool().submit(_reap)


def _get_router_state(name: str) -> _RouterState:
    with _router_states_lock:
        st = _router_states.get(name)
        if st is None:
            st = _router_states[name] = _RouterState(name)
        return st


_UNSET = object()


def _chain_hashes(tokens, block_size: int):
    """Cumulative prefix-chain hash per block of `tokens` — MUST stay
    identical to inference.kv_cache.chain_hashes (pinned by a test);
    duplicated here so the routing path never imports jax."""
    out = []
    parent = 0
    for i in range((len(tokens) - 1) // block_size):
        parent = hash((parent, tuple(int(t) for t in
                                     tokens[i * block_size:
                                            (i + 1) * block_size])))
        out.append(parent)
    return out


class DeploymentHandle:
    """Client-side handle with power-of-two-choices routing + in-flight
    cap (reference: handle.py over router.py:224-263).  Picklable:
    travels to replicas so deployments can compose.  Routing state is
    shared per deployment.

    Per-handle request options (set via .options()):

    - ``timeout_s``: request deadline.  Bounds admission waits, travels
      to the replica (which aborts not-yet-started work and evicts
      expired streams), and stops retries/failovers.  Defaults to the
      ``serve_request_deadline_s`` config (0 = none).
    - ``failover``: mid-stream failover policy for stream()/
      stream_async().  None (default) surfaces replica loss to the
      caller; ``"replay"`` resubmits the original request and skips
      already-delivered chunks (requires a deterministic stream); a
      callable ``policy(args, kwargs, received) -> (args, kwargs) |
      None`` rewrites the request to resume where the dead replica
      stopped (None = the stream was already complete)."""

    def __init__(self, deployment_name: str, method_name: str = "__call__",
                 timeout_s: Optional[float] = None, failover=None):
        self._name = deployment_name
        self._method = method_name
        self._timeout_s = timeout_s
        self._failover = failover
        self._state = _get_router_state(deployment_name)

    def options(self, method_name: Optional[str] = None, *,
                timeout_s=_UNSET, failover=_UNSET) -> "DeploymentHandle":
        return DeploymentHandle(
            self._name,
            method_name if method_name is not None else self._method,
            self._timeout_s if timeout_s is _UNSET else timeout_s,
            self._failover if failover is _UNSET else failover)

    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        return _MethodCaller(self, item)

    def _apply_routing(self, routing) -> None:
        st = self._state
        with st.lock:
            st.replicas = routing["replicas"]
            st.max_q = routing["max_concurrent_queries"]
            st.queue_limit = routing.get("queue_limit")
            st.known_version = routing.get("version", -1)
            st.states = dict(routing.get("states") or {})
            st.fetched_at = time.monotonic()
            alive = {r._actor_id.binary() for r in st.replicas}
            for key in list(st.in_flight):
                if key not in alive:
                    del st.in_flight[key]
            # A dead/redeployed replica's prefix summary must never
            # attract traffic: drop it with the replica, not at the
            # staleness horizon.
            for key in list(st.prefix):
                if key not in alive:
                    del st.prefix[key]

    def _refresh(self, force=False):
        st = self._state
        with st.lock:
            fresh = (not force and st.replicas
                     and time.monotonic() - st.fetched_at < 2.0)
        if fresh:
            self._ensure_poller()
            return
        try:
            controller = ray_tpu.get_actor(CONTROLLER_NAME, SERVE_NAMESPACE)
            routing = ray_tpu.get(
                controller.get_routing.remote(self._name), timeout=30)
        except Exception:
            # Control-plane outage (GCS restarting, controller lookup
            # timed out).  The replicas themselves are peer-to-peer and
            # very likely still serving — keep routing on the stale
            # table instead of failing the request; the long-poller
            # refreshes the moment the control plane is back.  Only an
            # empty cache (cold start) still surfaces the error.
            with st.lock:
                stale_ok = bool(st.replicas)
                if stale_ok:
                    # Re-arm the freshness window so the next 2s of
                    # requests route on the stale table immediately
                    # instead of each re-paying the failed lookup.
                    st.fetched_at = time.monotonic()
            if not stale_ok:
                raise
            from ray_tpu.util import events
            events.record("serve", "stale_routing", deployment=self._name,
                          replicas=len(st.replicas))
            self._ensure_poller()
            return
        if routing is None:
            raise ValueError(f"deployment {self._name!r} not found")
        self._apply_routing(routing)
        self._ensure_poller()

    # ---------------- prefix-cache-aware routing ----------------

    def _ensure_prefix_scraper(self):
        """One summary-scrape thread per deployment router state (the
        poller pattern), alive only while serve_prefix_routing is on and
        the deployment actually exports summaries."""
        st = self._state
        with st.lock:
            if st.prefix_disabled or (st.prefix_thread is not None
                                      and st.prefix_thread.is_alive()):
                return
            st.prefix_thread = threading.Thread(
                target=self._prefix_scrape_loop, daemon=True,
                name=f"serve-prefix-scrape-{self._name}")
            st.prefix_thread.start()

    def _prefix_scrape_loop(self):
        import ray_tpu.api as _api
        st = self._state
        while (_api._worker is not None and not st.prefix_disabled
               and GLOBAL_CONFIG.serve_prefix_routing):
            with st.lock:
                replicas = list(st.replicas)
            for r in replicas:
                try:
                    summ = ray_tpu.get(
                        r.handle_request.remote("prefix_summary", (), {},
                                                False, 5.0),
                        timeout=5.0)
                    if not isinstance(summ, dict):
                        raise TypeError("not a summary")
                    with st.lock:
                        st.prefix[r._actor_id.binary()] = {
                            "hashes": set(summ.get("hashes") or ()),
                            "block_size": int(summ.get("block_size") or 0),
                            "ts": time.monotonic()}
                except Exception as e:
                    # Deployments without prefix_summary (non-LLM) turn
                    # scraping OFF for this router instead of hammering
                    # every replica forever; dead replicas just age out
                    # (the staleness bound stops their summaries from
                    # scoring long before the table refresh prunes them).
                    msg = f"{type(e).__name__}: {e}"
                    if ("AttributeError" in msg
                            and "prefix_summary" in msg):
                        st.prefix_disabled = True
                        return
            time.sleep(max(GLOBAL_CONFIG.serve_prefix_scrape_s, 0.05))

    def _prefix_order(self, args, kwargs) -> Optional[Dict[bytes, int]]:
        """Score replicas for this request by deepest cached prefix:
        actor id -> matched chain depth, or None when prefix routing is
        off / the request has no token prompt / no fresh summary scores
        (the caller then falls back to pure power-of-two-choices)."""
        if not GLOBAL_CONFIG.serve_prefix_routing:
            return None
        st = self._state
        if st.prefix_disabled:
            return None
        self._ensure_prefix_scraper()
        prompt = args[0] if args else (kwargs or {}).get("prompt")
        if isinstance(prompt, (list, tuple)) and prompt:
            try:
                tokens = [int(t) for t in prompt]
            except (TypeError, ValueError):
                return None
        else:
            return None
        now = time.monotonic()
        stale = GLOBAL_CONFIG.serve_prefix_staleness_s
        with st.lock:
            fresh = [(rid, info) for rid, info in st.prefix.items()
                     if now - info["ts"] <= stale]
        if not fresh:
            return None
        scores: Dict[bytes, int] = {}
        hs_by_bs: Dict[int, list] = {}
        for rid, info in fresh:
            bs = info["block_size"]
            if bs <= 0:
                continue
            hs = hs_by_bs.get(bs)
            if hs is None:
                hs = hs_by_bs[bs] = _chain_hashes(tokens, bs)
            depth = 0
            for h in hs:
                if h not in info["hashes"]:
                    break
                depth += 1
            if depth:
                scores[rid] = depth
        return scores or None

    def _ensure_poller(self):
        """Config changes PUSH to the shared router state via ONE
        controller long-poll thread per deployment (reference:
        _private/long_poll.py:185 config propagation)."""
        st = self._state
        with st.lock:
            if st.poller is not None and st.poller.is_alive():
                return
            st.poller = threading.Thread(
                target=self._poll_loop, daemon=True,
                name=f"serve-longpoll-{self._name}")
            st.poller.start()

    def _poll_loop(self):
        import ray_tpu.api as _api
        st = self._state
        while _api._worker is not None:
            try:
                controller = ray_tpu.get_actor(CONTROLLER_NAME,
                                               SERVE_NAMESPACE)
                routing = ray_tpu.get(
                    controller.poll_routing.remote(
                        self._name, st.known_version, 10.0),
                    timeout=30)
                if routing is None:
                    return  # deployment deleted
                if routing.get("version", -1) != st.known_version:
                    self._apply_routing(routing)
            except Exception:
                time.sleep(1.0)

    def remote(self, *args, **kwargs):
        return self._call(self._method, args, kwargs)

    def _pick_replica(self, prefer: Optional[Dict[bytes, int]] = None):
        """One routing decision under the in-flight cap: power-of-two-
        choices on in-flight counts (reference: router.py's least-loaded
        two-candidate sampling), ties rotated round-robin so idle
        replicas still share traffic.  If both sampled replicas are
        saturated, scan the rest — admission must succeed whenever ANY
        replica is under its cap.

        Replicas whose routing-table state is not RUNNING are filtered
        OUT of the candidate sample up front: a DRAINING victim finishes
        its in-flight work but never attracts new traffic — prefix
        affinity included (this is the draining-victim fix: the old
        sampler only noticed drained replicas at the in-flight probe).

        `prefer` (actor id -> cached-prefix depth, from _prefix_order)
        stable-sorts the candidate order deepest-prefix-first, so the
        p2c/round-robin order is exactly the fallback on ties, unknown
        replicas and stale summaries.  Returns (replica, key) or None
        when every replica is saturated."""
        st = self._state
        with st.lock:
            n = len(st.replicas)
            if n == 0:
                return None
            st.rr += 1
            if st.states:
                elig = [k for k in range(n)
                        if st.states.get(
                            st.replicas[k]._actor_id.binary(),
                            REPLICA_RUNNING) == REPLICA_RUNNING]
                if not elig:
                    # Stale/partial states must not brick routing — the
                    # in-flight probe still backstops a bad pick.
                    elig = list(range(n))
            else:
                elig = list(range(n))
            m = len(elig)
            if m == 1:
                order = list(elig)
            else:
                i = random.randrange(m)
                j = random.randrange(m - 1)
                if j >= i:
                    j += 1
                fi = st.in_flight.get(
                    st.replicas[elig[i]]._actor_id.binary(), 0)
                fj = st.in_flight.get(
                    st.replicas[elig[j]]._actor_id.binary(), 0)
                if fi == fj:
                    # Tie (the common idle case): deterministic round-
                    # robin, so even a short sequential burst provably
                    # spreads across replicas.
                    start = st.rr % m
                    order = [elig[(start + k) % m] for k in range(m)]
                else:
                    if fj < fi:
                        i, j = j, i
                    order = ([elig[i], elig[j]]
                             + [elig[k] for k in range(m)
                                if k not in (i, j)])
            if prefer:
                order.sort(key=lambda idx: -prefer.get(
                    st.replicas[idx]._actor_id.binary(), 0))
            for idx in order:
                key = st.replicas[idx]._actor_id.binary()
                if st.in_flight.get(key, 0) < st.max_q:
                    st.in_flight[key] = st.in_flight.get(key, 0) + 1
                    depth = prefer.get(key, 0) if prefer else 0
                    if depth > 0:
                        events.record("serve", "prefix_route",
                                      deployment=self._name, depth=depth)
                    return st.replicas[idx], key
        return None

    # ---------------- admission: bounded queue + shedding ----------------

    def _request_deadline(self) -> Optional[float]:
        t = self._timeout_s
        if t is None:
            cfg = GLOBAL_CONFIG.serve_request_deadline_s
            t = cfg if cfg and cfg > 0 else None
        return None if t is None else time.monotonic() + t

    def _admission_enter(self) -> None:
        """Count this request as queued; shed it with
        ServeOverloadedError if the bounded per-deployment queue is
        already full (graceful overload degradation: a fast, actionable
        failure instead of an unbounded pile-up of waiters)."""
        st = self._state
        with st.lock:
            limit = st.queue_limit
            if limit is None:
                limit = GLOBAL_CONFIG.serve_queue_length
            if limit and st.pending >= limit:
                _serve_metrics()["shed"].inc()
                events.record("serve", "shed", deployment=self._name,
                              pending=st.pending, limit=limit)
                raise ServeOverloadedError(
                    self._name, GLOBAL_CONFIG.serve_retry_after_hint_s,
                    st.pending, limit)
            st.pending += 1

    def _admission_exit(self) -> None:
        st = self._state
        with st.lock:
            st.pending = max(0, st.pending - 1)

    def _wait_deadline(self, deadline: Optional[float]) -> float:
        limit = time.monotonic() + GLOBAL_CONFIG.serve_backpressure_timeout_s
        return limit if deadline is None else min(limit, deadline)

    def _acquire_replica(self, deadline: Optional[float], prefer=None):
        """Admit one request: pick a replica under its cap (preferring
        `prefer`'s deepest-cached-prefix order when set), else wait in
        the bounded queue until one frees up, the backpressure window
        closes, or the request deadline passes."""
        t0 = time.perf_counter()
        # Traced requests get an explicit admit span (queue wait is the
        # classic serve bottleneck); untraced ones keep the instant event.
        tok = (spans.begin("serve", "admit", deployment=self._name)
               if tracing.current_context() is not None else None)
        pick = self._pick_replica(prefer)
        if pick is not None:
            self._observe_admit(t0)
            spans.end(tok, queued=False)
            return pick
        self._admission_enter()
        try:
            limit = self._wait_deadline(deadline)
            while True:
                pick = self._pick_replica(prefer)
                if pick is not None:
                    self._observe_admit(t0)
                    spans.end(tok, queued=True)
                    return pick
                if time.monotonic() > limit:
                    spans.end(tok, granted=False)
                    raise TimeoutError(
                        f"no replica of {self._name!r} under its "
                        f"max_concurrent_queries cap before the deadline")
                time.sleep(0.01)  # every replica saturated: backpressure
        finally:
            self._admission_exit()

    def _observe_admit(self, t0: float) -> None:
        wait = time.perf_counter() - t0
        _serve_metrics()["queue_wait"].observe(wait)
        events.record("serve", "admit", deployment=self._name,
                      wait_s=round(wait, 6))

    async def _acquire_replica_async(self, deadline: Optional[float],
                                     prefer=None):
        import asyncio
        t0 = time.perf_counter()
        tok = (spans.begin("serve", "admit", deployment=self._name)
               if tracing.current_context() is not None else None)
        pick = self._pick_replica(prefer)
        if pick is not None:
            self._observe_admit(t0)
            spans.end(tok, queued=False)
            return pick
        self._admission_enter()
        try:
            limit = self._wait_deadline(deadline)
            while True:
                pick = self._pick_replica(prefer)
                if pick is not None:
                    self._observe_admit(t0)
                    spans.end(tok, queued=True)
                    return pick
                if time.monotonic() > limit:
                    spans.end(tok, granted=False)
                    raise TimeoutError(
                        f"no replica of {self._name!r} under its "
                        f"max_concurrent_queries cap before the deadline")
                await asyncio.sleep(0.005)
        finally:
            self._admission_exit()

    @staticmethod
    def _remaining(deadline: Optional[float]) -> Optional[float]:
        """Deadline budget left, as handle_request's deadline_s arg."""
        return None if deadline is None else deadline - time.monotonic()

    @staticmethod
    def _step_timeout(deadline: Optional[float]) -> float:
        """Per-RPC timeout for one stream step, clipped to the request
        deadline so an expired request stops waiting promptly."""
        if deadline is None:
            return 60.0
        return max(0.1, min(60.0, deadline - time.monotonic()))

    def _call(self, method, args, kwargs):
        t0 = time.time()
        # Traced requests open a serve/request span covering submit ->
        # result(); routing, admission and the task-lifecycle subtree all
        # parent under it (the contextvar is scoped to this call so the
        # span closes from _TrackedRef on whatever thread collects it).
        tok = (spans.begin("serve", "request", deployment=self._name,
                           method=method or "__call__")
               if tracing.current_context() is not None else None)
        cv = (tracing._ctx.set((tok.trace_id, tok.sid))
              if tok is not None and tok.trace_id else None)
        try:
            self._refresh()
            deadline = self._request_deadline()
            replica, key = self._acquire_replica(
                deadline, self._prefix_order(args, kwargs))
            ref = replica.handle_request.remote(
                method, args, kwargs, False, self._remaining(deadline))
        except BaseException:
            spans.end(tok, ok=False)
            raise
        finally:
            if cv is not None:
                tracing._ctx.reset(cv)
        return _TrackedRef(ref, self, key, method, args, kwargs,
                           deadline=deadline, t0=t0, tok=tok)

    def stream(self, *args, **kwargs):
        """Synchronous streaming call: yields the chunks of a generator
        (or async-generator) deployment method INCREMENTALLY — each
        chunk is pulled from the replica on demand (reference: streaming
        DeploymentResponseGenerator over handle_request_streaming).
        Each attempt is replica-pinned; if the replica dies mid-stream
        and this handle has a failover policy, the replica set is healed
        and the request resubmitted (see the class docstring)."""
        policy = self._failover
        deadline = self._request_deadline()
        received: List[Any] = []
        cur_args, cur_kwargs = args, dict(kwargs)
        skip = 0
        attempts = 0
        while True:
            try:
                for chunk in self._stream_once(cur_args, cur_kwargs,
                                               skip, deadline):
                    received.append(chunk)
                    yield chunk
                return
            except BaseException as e:
                if policy is None or not _is_replica_loss(e):
                    raise
                attempts += 1
                if attempts > GLOBAL_CONFIG.serve_failover_attempts:
                    raise
                if deadline is not None and time.monotonic() > deadline:
                    raise
                _serve_metrics()["failovers"].inc()
                events.record("serve", "failover", deployment=self._name,
                              attempt=attempts, received=len(received))
                self._on_replica_error()
                if callable(policy):
                    resumed = policy(args, dict(kwargs), list(received))
                    if resumed is None:
                        return  # policy says the stream was complete
                    cur_args, cur_kwargs = resumed
                    skip = 0
                else:  # "replay": rerun, swallow already-seen chunks
                    cur_args, cur_kwargs = args, dict(kwargs)
                    skip = len(received)

    def _stream_once(self, args, kwargs, skip: int,
                     deadline: Optional[float]):
        """One replica-pinned streaming attempt; the first `skip` chunks
        are swallowed (already delivered by a previous attempt)."""
        self._refresh()
        replica, key = self._acquire_replica(
            deadline, self._prefix_order(args, kwargs))
        try:
            req_ref = replica.handle_request.remote(
                self._method, args, kwargs, True, self._remaining(deadline))
            try:
                ticket = ray_tpu.get(req_ref,
                                     timeout=self._step_timeout(deadline))
            except BaseException:
                # The replica may still complete the call and register a
                # stream whose sid we never learned — reap it so the
                # in-flight slot isn't held forever.
                _reap_orphan_stream(replica, req_ref)
                raise
            if not (isinstance(ticket, dict)
                    and "__serve_stream__" in ticket):
                # Non-generator method: degrade to a one-item stream.
                if skip <= 0:
                    yield ticket
                return
            sid = ticket["__serve_stream__"]
            try:
                while True:
                    out = ray_tpu.get(replica.next_chunk.remote(sid),
                                      timeout=self._step_timeout(deadline))
                    if out.get("done"):
                        return
                    if skip > 0:
                        skip -= 1
                        continue
                    yield out["chunk"]
            except BaseException:
                # Any abandonment (consumer close, get timeout, worker
                # error) must release the replica's stream slot.
                try:
                    ray_tpu.get(replica.cancel_stream.remote(sid),
                                timeout=10)
                except Exception:
                    pass
                raise
        finally:
            self._done(key)

    async def stream_async(self, method, args, kwargs, *,
                           timeout: float = 60.0):
        """Async streaming variant (the proxy's path): an async
        generator over the method's chunks, with the same failover
        semantics as stream()."""
        policy = self._failover
        deadline = self._request_deadline()
        received: List[Any] = []
        cur_args, cur_kwargs = args, dict(kwargs or {})
        skip = 0
        attempts = 0
        while True:
            try:
                agen = self._stream_once_async(
                    method, cur_args, cur_kwargs, skip, deadline, timeout)
                async for chunk in agen:
                    received.append(chunk)
                    yield chunk
                return
            except BaseException as e:
                if policy is None or not _is_replica_loss(e):
                    raise
                attempts += 1
                if attempts > GLOBAL_CONFIG.serve_failover_attempts:
                    raise
                if deadline is not None and time.monotonic() > deadline:
                    raise
                _serve_metrics()["failovers"].inc()
                events.record("serve", "failover", deployment=self._name,
                              attempt=attempts, received=len(received))
                self._on_replica_error()
                if callable(policy):
                    resumed = policy(args, dict(kwargs or {}),
                                     list(received))
                    if resumed is None:
                        return
                    cur_args, cur_kwargs = resumed
                    skip = 0
                else:
                    cur_args, cur_kwargs = args, dict(kwargs or {})
                    skip = len(received)

    async def _stream_once_async(self, method, args, kwargs, skip: int,
                                 deadline: Optional[float],
                                 timeout: float):
        import asyncio

        def _step(base):
            return (base if deadline is None
                    else max(0.1, min(base, deadline - time.monotonic())))

        self._refresh()
        replica, key = await self._acquire_replica_async(
            deadline, self._prefix_order(args, kwargs))
        try:
            # Per-step timeout: a wedged generator must not hold this
            # coroutine (and the in-flight slot) forever — mirror the
            # sync stream()'s bounded gets.
            req_ref = replica.handle_request.remote(
                method, args, kwargs, True, self._remaining(deadline))
            try:
                ticket = await asyncio.wait_for(
                    asyncio.wrap_future(req_ref.future()), _step(timeout))
            except BaseException:
                # Unknown-sid orphan (see stream()): reap off-loop.
                _reap_orphan_stream(replica, req_ref)
                raise
            if not (isinstance(ticket, dict)
                    and "__serve_stream__" in ticket):
                if skip <= 0:
                    yield ticket
                return
            sid = ticket["__serve_stream__"]
            try:
                while True:
                    out = await asyncio.wait_for(asyncio.wrap_future(
                        replica.next_chunk.remote(sid).future()),
                        _step(timeout))
                    if out.get("done"):
                        return
                    if skip > 0:
                        skip -= 1
                        continue
                    yield out["chunk"]
            except BaseException:
                # Same slot-release contract as the sync stream().
                try:
                    await asyncio.wait_for(asyncio.wrap_future(
                        replica.cancel_stream.remote(sid).future()), 10)
                except Exception:
                    pass
                raise
        finally:
            self._done(key)

    async def call_async(self, method, args, kwargs, *,
                         timeout: float = 60.0, _retried=False):
        """Async-native request path (reference: the ASGI proxy awaits the
        router/replica without burning a thread per request)."""
        import asyncio

        req_deadline = self._request_deadline()
        deadline = time.monotonic() + timeout
        if req_deadline is not None:
            deadline = min(deadline, req_deadline)
        self._refresh()
        replica, key = await self._acquire_replica_async(
            deadline, self._prefix_order(args, kwargs))
        ref = replica.handle_request.remote(
            method, args, kwargs, False, deadline - time.monotonic())
        released = False

        def release(_=None):
            nonlocal released
            if not released:
                released = True
                self._done(key)

        try:
            fut = asyncio.wrap_future(ref.future())
            try:
                result = await asyncio.wait_for(
                    fut, max(0.1, deadline - time.monotonic()))
            except asyncio.TimeoutError:
                # The request is STILL running on the replica — keep its
                # in-flight slot charged until the underlying call
                # completes, or the admission cap would over-admit.
                fut.add_done_callback(release)
                raise TimeoutError(
                    f"request to {self._name!r} timed out")
            release()
            return result
        except ActorDiedError:
            release()
            if _retried or (req_deadline is not None
                            and time.monotonic() > req_deadline):
                raise
            _serve_metrics()["retries"].inc()
            self._on_replica_error()
            return await self.call_async(
                method, args, kwargs,
                timeout=max(0.1, deadline - time.monotonic()),
                _retried=True)
        except TimeoutError:
            raise
        except BaseException:
            release()
            raise

    def _done(self, key: bytes):
        st = self._state
        with st.lock:
            if key in st.in_flight:
                st.in_flight[key] = max(0, st.in_flight[key] - 1)

    def _on_replica_error(self):
        try:
            controller = ray_tpu.get_actor(CONTROLLER_NAME, SERVE_NAMESPACE)
            ray_tpu.get(controller.heal.remote(self._name), timeout=60)
        except Exception:
            pass
        self._refresh(force=True)

    def __reduce__(self):
        # failover callables must be module-level (picklable) to travel.
        return (DeploymentHandle, (self._name, self._method,
                                   self._timeout_s, self._failover))


class _MethodCaller:
    def __init__(self, handle: DeploymentHandle, method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs):
        return self._handle._call(self._method, args, kwargs)


class _TrackedRef:
    """Wraps the reply ref to release the in-flight slot on result() and
    retry once through a healed replica set on replica death (never past
    the request deadline)."""

    def __init__(self, ref, handle: DeploymentHandle, key: bytes,
                 method: str, args, kwargs, retried: bool = False,
                 deadline: Optional[float] = None,
                 t0: Optional[float] = None, tok=None):
        self._ref = ref
        self._handle = handle
        self._idx = key
        self._request = (method, args, kwargs)
        self._retried = retried
        self._deadline = deadline
        self._t0 = t0 if t0 is not None else time.time()
        self._tok = tok          # open serve/request span (traced only)

    def result(self, timeout: Optional[float] = None):
        from ray_tpu.exceptions import ActorDiedError, RayTpuTimeoutError
        try:
            value = ray_tpu.get(self._ref, timeout=timeout)
        except ActorDiedError:
            self._handle._done(self._idx)
            if self._retried or (self._deadline is not None
                                 and time.monotonic() > self._deadline):
                spans.end(self._tok, ok=False)
                self._tok = None
                raise
            _serve_metrics()["retries"].inc()
            events.record("serve", "retry",
                          deployment=self._handle._name,
                          method=self._request[0])
            spans.end(self._tok, retried=True)
            self._tok = None
            self._handle._on_replica_error()
            method, args, kwargs = self._request
            retry = self._handle._call(method, args, kwargs)
            retry._retried = True
            retry._t0 = self._t0
            return retry.result(timeout)
        except RayTpuTimeoutError:
            # Still executing on the replica: keep the slot charged until
            # it actually finishes (admission-cap correctness).  The span
            # stays open; a later result() (or the crash horizon) ends it.
            handle, key = self._handle, self._idx
            self._ref.future().add_done_callback(
                lambda _: handle._done(key))
            raise
        except BaseException:
            self._handle._done(self._idx)
            spans.end(self._tok, ok=False)
            self._tok = None
            raise
        self._handle._done(self._idx)
        _serve_metrics()["e2e"].observe(time.time() - self._t0)
        spans.end(self._tok)
        self._tok = None
        return value

    @property
    def ref(self):
        return self._ref
