"""Serve internals: controller, replica, router/handle, HTTP proxy.

Reference parity: python/ray/serve/_private/ — ServeController
(controller.py:71) reconciles DeploymentState (deployment_state.py:1006);
replicas host user code (replica.py:268); Router round-robins with
max_concurrent_queries backpressure (router.py:224); HTTPProxy is the
ASGI ingress (http_proxy.py:434).  Config propagation here is pull-based
with revalidation on failure (the reference uses long-poll; same
eventual-consistency contract, no blocked actor threads).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import ray_tpu

CONTROLLER_NAME = "SERVE_CONTROLLER"
SERVE_NAMESPACE = "serve"


@dataclass
class DeploymentConfig:
    name: str
    num_replicas: int = 1
    max_concurrent_queries: int = 100
    ray_actor_options: dict = field(default_factory=dict)
    user_config: Any = None
    version: int = 0


@ray_tpu.remote
class ReplicaActor:
    """Hosts one copy of the user's callable (reference: replica.py:268)."""

    def __init__(self, cls_or_fn, init_args, init_kwargs, user_config=None):
        import inspect
        if inspect.isclass(cls_or_fn):
            self._callable = cls_or_fn(*init_args, **(init_kwargs or {}))
        else:
            self._callable = cls_or_fn
        if user_config is not None and hasattr(self._callable,
                                               "reconfigure"):
            self._callable.reconfigure(user_config)

    def handle_request(self, method_name, args, kwargs):
        target = self._callable
        if method_name and method_name != "__call__":
            target = getattr(self._callable, method_name)
        elif not callable(target):
            raise TypeError("deployment object is not callable")
        import asyncio
        import inspect
        result = target(*args, **(kwargs or {}))
        if inspect.iscoroutine(result):
            result = asyncio.run(result)
        return result

    def reconfigure(self, user_config):
        if hasattr(self._callable, "reconfigure"):
            self._callable.reconfigure(user_config)
        return True

    def ping(self):
        return "pong"


@ray_tpu.remote
class ServeController:
    """Deployment table + reconciliation (reference: controller.py:71,
    DeploymentStateManager deployment_state.py:1864)."""

    def __init__(self):
        # name -> {"config": DeploymentConfig, "replicas": [handles],
        #          "deployed_def": (cls, args, kwargs)}
        self._deployments: Dict[str, dict] = {}
        self._version = 0

    def deploy(self, config: DeploymentConfig, cls_or_fn, init_args,
               init_kwargs):
        entry = self._deployments.get(config.name)
        if entry is None:
            entry = {"config": config, "replicas": [],
                     "deployed_def": (cls_or_fn, init_args, init_kwargs)}
            self._deployments[config.name] = entry
        else:
            entry["config"] = config
            entry["deployed_def"] = (cls_or_fn, init_args, init_kwargs)
        self._reconcile(config.name)
        self._version += 1
        return {"name": config.name, "replicas": len(entry["replicas"])}

    def _reconcile(self, name: str):
        entry = self._deployments[name]
        config: DeploymentConfig = entry["config"]
        cls_or_fn, args, kwargs = entry["deployed_def"]
        replicas: List = entry["replicas"]
        # Health-check existing replicas; drop the dead.
        alive = []
        for r in replicas:
            try:
                ray_tpu.get(r.ping.remote(), timeout=10)
                alive.append(r)
            except Exception:
                pass
        replicas[:] = alive
        opts = dict(config.ray_actor_options)
        while len(replicas) < config.num_replicas:
            actor = ReplicaActor.options(
                num_cpus=opts.get("num_cpus", 0.1),
                num_tpus=opts.get("num_tpus"),
                resources=opts.get("resources"),
                max_restarts=2,
                # Replicas must execute up to max_concurrent_queries requests
                # at once, or @serve.batch could never accumulate a batch.
                max_concurrency=config.max_concurrent_queries,
            ).remote(cls_or_fn, args, kwargs, config.user_config)
            replicas.append(actor)
        while len(replicas) > config.num_replicas:
            victim = replicas.pop()
            try:
                ray_tpu.kill(victim)
            except Exception:
                pass
        # Verify new replicas constructed (surface user __init__ errors).
        for r in replicas:
            ray_tpu.get(r.ping.remote(), timeout=120)

    def get_routing(self, name: str):
        entry = self._deployments.get(name)
        if entry is None:
            return None
        return {"replicas": list(entry["replicas"]),
                "max_concurrent_queries":
                    entry["config"].max_concurrent_queries,
                "version": self._version}

    def list_deployments(self):
        return {name: {"num_replicas": len(e["replicas"]),
                       "target": e["config"].num_replicas}
                for name, e in self._deployments.items()}

    def delete_deployment(self, name: str):
        entry = self._deployments.pop(name, None)
        if entry is None:
            return False
        for r in entry["replicas"]:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        self._version += 1
        return True

    def heal(self, name: str):
        """Router-reported replica failure: reconcile this deployment."""
        if name in self._deployments:
            self._reconcile(name)
            self._version += 1
        return True

    def shutdown(self):
        for name in list(self._deployments):
            self.delete_deployment(name)
        return True


class DeploymentHandle:
    """Client-side handle with round-robin + in-flight cap (reference:
    handle.py over router.py:224-263).  Picklable: travels to replicas so
    deployments can compose."""

    def __init__(self, deployment_name: str, method_name: str = "__call__"):
        self._name = deployment_name
        self._method = method_name
        self._lock = threading.Lock()
        self._replicas: List = []
        self._max_q = 100
        self._rr = 0
        # In-flight counts keyed by stable replica identity (actor id) —
        # index keys would mis-attribute counts after _refresh/heal
        # replaces the replica list.
        self._in_flight: Dict[bytes, int] = {}
        self._fetched_at = 0.0

    def options(self, method_name: str) -> "DeploymentHandle":
        return DeploymentHandle(self._name, method_name)

    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        return _MethodCaller(self, item)

    def _refresh(self, force=False):
        with self._lock:
            if not force and self._replicas \
                    and time.monotonic() - self._fetched_at < 2.0:
                return
            controller = ray_tpu.get_actor(CONTROLLER_NAME, SERVE_NAMESPACE)
            routing = ray_tpu.get(
                controller.get_routing.remote(self._name), timeout=30)
            if routing is None:
                raise ValueError(f"deployment {self._name!r} not found")
            self._replicas = routing["replicas"]
            self._max_q = routing["max_concurrent_queries"]
            self._fetched_at = time.monotonic()
            alive = {r._actor_id.binary() for r in self._replicas}
            for key in list(self._in_flight):
                if key not in alive:
                    del self._in_flight[key]

    def remote(self, *args, **kwargs):
        return self._call(self._method, args, kwargs)

    def _call(self, method, args, kwargs):
        self._refresh()
        deadline = time.monotonic() + 60
        while True:
            with self._lock:
                n = len(self._replicas)
                order = [(self._rr + i) % n for i in range(n)] if n else []
                self._rr += 1
                pick = None
                for idx in order:
                    key = self._replicas[idx]._actor_id.binary()
                    if self._in_flight.get(key, 0) < self._max_q:
                        pick = idx
                        break
            if pick is not None:
                replica = self._replicas[pick]
                key = replica._actor_id.binary()
                with self._lock:
                    self._in_flight[key] = self._in_flight.get(key, 0) + 1
                ref = replica.handle_request.remote(method, args, kwargs)
                return _TrackedRef(ref, self, key, method, args, kwargs)
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"no replica of {self._name!r} under its "
                    f"max_concurrent_queries cap within 60s")
            time.sleep(0.01)  # every replica saturated: backpressure

    def _done(self, key: bytes):
        with self._lock:
            if key in self._in_flight:
                self._in_flight[key] = max(0, self._in_flight[key] - 1)

    def _on_replica_error(self):
        try:
            controller = ray_tpu.get_actor(CONTROLLER_NAME, SERVE_NAMESPACE)
            ray_tpu.get(controller.heal.remote(self._name), timeout=60)
        except Exception:
            pass
        self._refresh(force=True)

    def __reduce__(self):
        return (DeploymentHandle, (self._name, self._method))


class _MethodCaller:
    def __init__(self, handle: DeploymentHandle, method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs):
        return self._handle._call(self._method, args, kwargs)


class _TrackedRef:
    """Wraps the reply ref to release the in-flight slot on result() and
    retry once through a healed replica set on replica death."""

    def __init__(self, ref, handle: DeploymentHandle, key: bytes,
                 method: str, args, kwargs, retried: bool = False):
        self._ref = ref
        self._handle = handle
        self._idx = key
        self._request = (method, args, kwargs)
        self._retried = retried

    def result(self, timeout: Optional[float] = None):
        from ray_tpu.exceptions import ActorDiedError
        try:
            value = ray_tpu.get(self._ref, timeout=timeout)
        except ActorDiedError:
            self._handle._done(self._idx)
            if self._retried:
                raise
            self._handle._on_replica_error()
            method, args, kwargs = self._request
            retry = self._handle._call(method, args, kwargs)
            retry._retried = True
            return retry.result(timeout)
        except BaseException:
            self._handle._done(self._idx)
            raise
        self._handle._done(self._idx)
        return value

    @property
    def ref(self):
        return self._ref
