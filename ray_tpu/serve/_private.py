"""Serve internals: controller, replica, router/handle, HTTP proxy.

Reference parity: python/ray/serve/_private/ — ServeController
(controller.py:71) reconciles DeploymentState (deployment_state.py:1006);
replicas host user code (replica.py:268); Router round-robins with
max_concurrent_queries backpressure (router.py:224); HTTPProxy is the
ASGI ingress (http_proxy.py:434).  Config propagation here is pull-based
with revalidation on failure (the reference uses long-poll; same
eventual-consistency contract, no blocked actor threads).
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu._private.config import GLOBAL_CONFIG

CONTROLLER_NAME = "SERVE_CONTROLLER"
SERVE_NAMESPACE = "serve"


@dataclass
class AutoscalingConfig:
    """Queue-depth replica autoscaling (reference:
    serve/_private/autoscaling_policy.py + serve/config.py
    AutoscalingConfig): desired = ceil(total_ongoing_requests /
    target_ongoing_requests), clamped to [min, max], applied after the
    respective delay has elapsed continuously."""

    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 0.2
    downscale_delay_s: float = 2.0


@dataclass
class DeploymentConfig:
    name: str
    num_replicas: int = 1
    max_concurrent_queries: int = 100
    ray_actor_options: dict = field(default_factory=dict)
    user_config: Any = None
    autoscaling_config: Optional[AutoscalingConfig] = None
    version: int = 0


@ray_tpu.remote
class ReplicaActor:
    """Hosts one copy of the user's callable (reference: replica.py:268).

    An ASYNC actor: the actor's persistent event loop hosts every
    in-flight request, exactly as the reference replica runs a user event
    loop — so an async deployment overlaps its awaits WITHIN one replica
    (10 concurrent requests that each await 100ms take ~100ms, not ~1s).
    Sync callables run on a thread pool so they can never stall the loop
    (and so blocking helpers like @serve.batch keep working)."""

    def __init__(self, cls_or_fn, init_args, init_kwargs, user_config=None,
                 max_concurrent_queries: int = 100):
        import inspect
        from concurrent.futures import ThreadPoolExecutor
        if inspect.isclass(cls_or_fn):
            self._callable = cls_or_fn(*init_args, **(init_kwargs or {}))
        else:
            self._callable = cls_or_fn
        if user_config is not None and hasattr(self._callable,
                                               "reconfigure"):
            self._callable.reconfigure(user_config)
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, min(max_concurrent_queries, 64)),
            thread_name_prefix="replica-sync")
        self._ongoing = 0
        # In-progress streaming responses: stream id -> async generator
        # (reference: replica-side generator streaming, replica.py's
        # handle_request_streaming).  Chunks are PULLED by the caller
        # (proxy or handle) one next_chunk() at a time — incremental by
        # construction, replica-pinned by the router.
        self._streams: dict = {}
        self._stream_ids = itertools.count(1)

    async def handle_request(self, method_name, args, kwargs,
                             stream: bool = False):
        import asyncio
        import inspect
        self._ongoing += 1  # loop-thread only: no lock needed
        try:
            target = self._callable
            if method_name and method_name != "__call__":
                target = getattr(self._callable, method_name)
            elif not callable(target):
                raise TypeError("deployment object is not callable")
            kwargs = kwargs or {}
            if inspect.isasyncgenfunction(target) or inspect.isgeneratorfunction(target):
                if not stream:
                    # Non-streaming caller (handle.remote(), plain HTTP
                    # dispatch): a stream ticket would leak its slot
                    # (no one would pull chunks), and materializing an
                    # unbounded generator would wedge the replica —
                    # reference behavior: require the streaming API.
                    raise TypeError(
                        f"method {method_name or '__call__'!r} is a "
                        f"generator; call it via handle.stream() / "
                        f"stream_async() (or the ASGI route), not "
                        f".remote()")
                # Streaming method: stash the generator and hand back a
                # stream ticket; the in-flight slot stays charged until
                # the consumer drains or cancels (next_chunk below).
                gen = target(*args, **kwargs)
                sid = next(self._stream_ids)
                self._streams[sid] = gen
                self._ongoing += 1   # held until stream end
                return {"__serve_stream__": sid}
            if inspect.iscoroutinefunction(target) or (
                    not inspect.isfunction(target)
                    and not inspect.ismethod(target)
                    and inspect.iscoroutinefunction(
                        getattr(target, "__call__", None))):
                return await target(*args, **kwargs)
            loop = asyncio.get_running_loop()
            result = await loop.run_in_executor(
                self._pool, lambda: target(*args, **kwargs))
            if inspect.iscoroutine(result):
                # Sync wrapper handing back a coroutine: finish it here.
                return await result
            return result
        finally:
            self._ongoing -= 1

    async def next_chunk(self, sid: int):
        """Pull ONE chunk of stream `sid`: {"chunk": value} or
        {"done": True}.  Sync generators advance on the thread pool so
        they cannot stall the replica loop."""
        import asyncio
        import inspect
        gen = self._streams.get(sid)
        if gen is None:
            return {"done": True}
        try:
            if inspect.isasyncgen(gen):
                chunk = await gen.__anext__()
            else:
                # StopIteration cannot cross a Future: pull behind a
                # sentinel on the thread pool.
                def _pull():
                    try:
                        return True, gen.__next__()
                    except StopIteration:
                        return False, None
                loop = asyncio.get_running_loop()
                alive, chunk = await loop.run_in_executor(self._pool,
                                                          _pull)
                if not alive:
                    self._finish_stream(sid)
                    return {"done": True}
            return {"chunk": chunk}
        except StopAsyncIteration:
            self._finish_stream(sid)
            return {"done": True}
        except Exception:
            self._finish_stream(sid)
            raise

    async def cancel_stream(self, sid: int):
        gen = self._streams.get(sid)
        if gen is not None:
            try:
                if hasattr(gen, "aclose"):
                    await gen.aclose()
                else:
                    gen.close()
            except Exception:
                pass
            self._finish_stream(sid)
        return True

    def _finish_stream(self, sid: int) -> None:
        if self._streams.pop(sid, None) is not None:
            self._ongoing -= 1

    async def ongoing_requests(self) -> int:
        """Autoscaling load signal (reference: replicas report queue
        metrics to the controller)."""
        return self._ongoing

    def reconfigure(self, user_config):
        if hasattr(self._callable, "reconfigure"):
            self._callable.reconfigure(user_config)
        return True

    def ping(self):
        return "pong"


@ray_tpu.remote(max_concurrency=64)
class ServeController:
    """Deployment table + reconciliation (reference: controller.py:71,
    DeploymentStateManager deployment_state.py:1864).  Threaded actor:
    the control loop (autoscaling) and long-poll waiters run alongside
    deploy/routing calls; the deployment table is lock-protected."""

    def __init__(self):
        # name -> {"config": DeploymentConfig, "replicas": [handles],
        #          "deployed_def": (cls, args, kwargs)}
        self._deployments: Dict[str, dict] = {}
        self._lock = threading.RLock()
        self._version = 0
        self._version_cv = threading.Condition(self._lock)
        self._loop_started = False
        self._stopped = False
        # name -> (desired_replicas, since_monotonic) scale intent
        self._scale_intent: Dict[str, tuple] = {}

    def _bump_version(self):
        with self._version_cv:
            self._version += 1
            self._version_cv.notify_all()

    # ---------------- long-poll config plane ----------------

    def poll_routing(self, name: str, known_version: int,
                     timeout_s: float = 10.0):
        """Block until the config version moves past known_version (or
        timeout), then return the routing table (reference:
        _private/long_poll.py:68 LongPollHost)."""
        deadline = time.monotonic() + timeout_s
        with self._version_cv:
            while self._version == known_version and not self._stopped:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._version_cv.wait(remaining)
        return self.get_routing(name)

    # ---------------- autoscaling control loop ----------------

    def run_control_loop(self, interval_s: float = 0.2):
        """Reference: the controller's run loop (controller.py) driving
        autoscaling_policy decisions.  Runs on one of this threaded
        actor's pool threads forever."""
        with self._lock:
            if self._loop_started:
                return False
            self._loop_started = True
        while not self._stopped:
            try:
                self._autoscale_pass()
            except Exception:
                pass
            time.sleep(interval_s)
        return True

    def _autoscale_pass(self):
        with self._lock:
            entries = {n: e for n, e in self._deployments.items()
                       if e["config"].autoscaling_config is not None}
        for name, entry in entries.items():
            cfg: DeploymentConfig = entry["config"]
            auto: AutoscalingConfig = cfg.autoscaling_config
            replicas = list(entry["replicas"])
            if not replicas:
                continue
            total = 0
            for r in replicas:
                try:
                    total += ray_tpu.get(r.ongoing_requests.remote(),
                                         timeout=5)
                except Exception:
                    pass
            import math
            desired = max(auto.min_replicas,
                          min(auto.max_replicas,
                              math.ceil(total /
                                        max(auto.target_ongoing_requests,
                                            1e-9))))
            now = time.monotonic()
            current = len(replicas)
            if desired == current:
                self._scale_intent.pop(name, None)
                continue
            intent = self._scale_intent.get(name)
            if intent is None or intent[0] != desired:
                self._scale_intent[name] = (desired, now)
                continue
            delay = (auto.upscale_delay_s if desired > current
                     else auto.downscale_delay_s)
            if now - intent[1] < delay:
                continue
            with self._lock:
                entry = self._deployments.get(name)
                if entry is None:
                    continue
                entry["config"].num_replicas = desired
            self._reconcile(name)
            self._scale_intent.pop(name, None)
            self._bump_version()

    def deploy(self, config: DeploymentConfig, cls_or_fn, init_args,
               init_kwargs):
        with self._lock:
            entry = self._deployments.get(config.name)
            if entry is None:
                entry = {"config": config, "replicas": [],
                         "deployed_def": (cls_or_fn, init_args, init_kwargs)}
                self._deployments[config.name] = entry
            else:
                entry["config"] = config
                entry["deployed_def"] = (cls_or_fn, init_args, init_kwargs)
                # New code/config version: existing replicas are stale and
                # get replaced below (reference: deployment_state.py rolling
                # version replacement).
                entry["def_version"] = entry.get("def_version", 0) + 1
            if config.autoscaling_config is not None:
                config.num_replicas = max(
                    config.autoscaling_config.min_replicas,
                    min(config.num_replicas,
                        config.autoscaling_config.max_replicas))
        self._reconcile(config.name)
        self._bump_version()
        return {"name": config.name, "replicas": len(entry["replicas"])}

    def _reconcile(self, name: str):
        """Converge the replica set.  Blocking actor RPCs (pings, replica
        construction) run WITHOUT the table lock — holding it would stall
        every get_routing/poll_routing for the duration of a replica cold
        start.  A per-deployment lock serializes concurrent reconciles."""
        with self._lock:
            entry = self._deployments.get(name)
            if entry is None:
                return
            rlock = entry.setdefault("_rlock", threading.Lock())
        with rlock:
            with self._lock:
                entry = self._deployments.get(name)
                if entry is None:
                    return
                config: DeploymentConfig = entry["config"]
                cls_or_fn, args, kwargs = entry["deployed_def"]
                replicas = list(entry["replicas"])
                def_version = entry.setdefault("def_version", 0)
                vers = dict(entry.setdefault("replica_vers", {}))
            # ---- unlocked: health checks / kills / constructions ----
            alive = []
            for r in replicas:
                key = r._actor_id.binary()
                if vers.get(key, def_version) != def_version:
                    try:
                        ray_tpu.kill(r)
                    except Exception:
                        pass
                    vers.pop(key, None)
                    continue
                try:
                    ray_tpu.get(r.ping.remote(), timeout=10)
                    alive.append(r)
                except Exception:
                    vers.pop(key, None)
            replicas = alive
            opts = dict(config.ray_actor_options)
            while len(replicas) < config.num_replicas:
                actor = ReplicaActor.options(
                    num_cpus=opts.get("num_cpus", 0.1),
                    num_tpus=opts.get("num_tpus"),
                    resources=opts.get("resources"),
                    max_restarts=2,
                    # Replicas must execute up to max_concurrent_queries
                    # requests at once, or @serve.batch could never
                    # accumulate a batch.
                    max_concurrency=config.max_concurrent_queries,
                ).remote(cls_or_fn, args, kwargs, config.user_config,
                         config.max_concurrent_queries)
                replicas.append(actor)
                vers[actor._actor_id.binary()] = def_version
            while len(replicas) > config.num_replicas:
                victim = replicas.pop()
                vers.pop(victim._actor_id.binary(), None)
                try:
                    ray_tpu.kill(victim)
                except Exception:
                    pass
            # Verify new replicas constructed (surface user __init__
            # errors) before committing them to the routing table.
            for r in replicas:
                ray_tpu.get(r.ping.remote(), timeout=120)
            with self._lock:
                entry = self._deployments.get(name)
                if entry is None:
                    for r in replicas:
                        try:
                            ray_tpu.kill(r)
                        except Exception:
                            pass
                    return
                entry["replicas"][:] = replicas
                entry["replica_vers"] = vers

    def get_routing(self, name: str):
        with self._lock:
            entry = self._deployments.get(name)
            if entry is None:
                return None
            return {"replicas": list(entry["replicas"]),
                    "max_concurrent_queries":
                        entry["config"].max_concurrent_queries,
                    "version": self._version}

    def list_deployments(self):
        with self._lock:
            return {name: {"num_replicas": len(e["replicas"]),
                           "target": e["config"].num_replicas}
                    for name, e in self._deployments.items()}

    def delete_deployment(self, name: str):
        with self._lock:
            entry = self._deployments.pop(name, None)
        if entry is None:
            return False
        for r in entry["replicas"]:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        self._bump_version()
        return True

    def heal(self, name: str):
        """Router-reported replica failure: reconcile this deployment."""
        self._reconcile(name)
        self._bump_version()
        return True

    def shutdown(self):
        self._stopped = True
        with self._version_cv:
            self._version_cv.notify_all()
        for name in list(self._deployments):
            self.delete_deployment(name)
        return True


class _RouterState:
    """Per-deployment routing state SHARED by every handle in the process:
    one replica table, one in-flight map, one long-poll thread — however
    many DeploymentHandle facades exist (reference: handles share the
    Router; r2 review: per-handle pollers leaked a thread per
    handle.options() call)."""

    def __init__(self, name: str):
        self.name = name
        self.lock = threading.Lock()
        self.replicas: List = []
        self.max_q = 100
        self.rr = 0
        # In-flight counts keyed by stable replica identity (actor id).
        self.in_flight: Dict[bytes, int] = {}
        self.fetched_at = 0.0
        self.known_version = -1
        self.poller: Optional[threading.Thread] = None


_router_states: Dict[str, _RouterState] = {}
_router_states_lock = threading.Lock()


# One small shared executor for orphan-stream reaps: each reap can block
# up to 60s on the abandoned call, and a thread PER abandoned request is
# an unbounded leak under a disconnect storm.  A bounded queue-backed
# pool serializes the excess instead; reaps are cleanup, not latency-
# sensitive.
_reaper_pool = None
_reaper_pool_lock = threading.Lock()


def _get_reaper_pool():
    global _reaper_pool
    if _reaper_pool is None:
        with _reaper_pool_lock:
            if _reaper_pool is None:
                from concurrent.futures import ThreadPoolExecutor
                _reaper_pool = ThreadPoolExecutor(
                    max_workers=4, thread_name_prefix="serve-stream-reaper")
    return _reaper_pool


def _reap_orphan_stream(replica, req_ref) -> None:
    """The caller abandoned a handle_request whose ticket it never saw.
    If that call registered a stream replica-side, its generator and
    in-flight slot would be held forever (no one knows the sid) — wait
    out the call on the shared reaper pool and cancel any stream it
    opened."""
    def _reap():
        try:
            ticket = ray_tpu.get(req_ref, timeout=60)
            if isinstance(ticket, dict) and "__serve_stream__" in ticket:
                ray_tpu.get(replica.cancel_stream.remote(
                    ticket["__serve_stream__"]), timeout=10)
        except Exception:
            pass  # replica died or call failed: nothing leaked
    _get_reaper_pool().submit(_reap)


def _get_router_state(name: str) -> _RouterState:
    with _router_states_lock:
        st = _router_states.get(name)
        if st is None:
            st = _router_states[name] = _RouterState(name)
        return st


class DeploymentHandle:
    """Client-side handle with round-robin + in-flight cap (reference:
    handle.py over router.py:224-263).  Picklable: travels to replicas so
    deployments can compose.  Routing state is shared per deployment."""

    def __init__(self, deployment_name: str, method_name: str = "__call__"):
        self._name = deployment_name
        self._method = method_name
        self._state = _get_router_state(deployment_name)

    def options(self, method_name: str) -> "DeploymentHandle":
        return DeploymentHandle(self._name, method_name)

    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        return _MethodCaller(self, item)

    def _apply_routing(self, routing) -> None:
        st = self._state
        with st.lock:
            st.replicas = routing["replicas"]
            st.max_q = routing["max_concurrent_queries"]
            st.known_version = routing.get("version", -1)
            st.fetched_at = time.monotonic()
            alive = {r._actor_id.binary() for r in st.replicas}
            for key in list(st.in_flight):
                if key not in alive:
                    del st.in_flight[key]

    def _refresh(self, force=False):
        st = self._state
        with st.lock:
            fresh = (not force and st.replicas
                     and time.monotonic() - st.fetched_at < 2.0)
        if fresh:
            self._ensure_poller()
            return
        controller = ray_tpu.get_actor(CONTROLLER_NAME, SERVE_NAMESPACE)
        routing = ray_tpu.get(
            controller.get_routing.remote(self._name), timeout=30)
        if routing is None:
            raise ValueError(f"deployment {self._name!r} not found")
        self._apply_routing(routing)
        self._ensure_poller()

    def _ensure_poller(self):
        """Config changes PUSH to the shared router state via ONE
        controller long-poll thread per deployment (reference:
        _private/long_poll.py:185 config propagation)."""
        st = self._state
        with st.lock:
            if st.poller is not None and st.poller.is_alive():
                return
            st.poller = threading.Thread(
                target=self._poll_loop, daemon=True,
                name=f"serve-longpoll-{self._name}")
            st.poller.start()

    def _poll_loop(self):
        import ray_tpu.api as _api
        st = self._state
        while _api._worker is not None:
            try:
                controller = ray_tpu.get_actor(CONTROLLER_NAME,
                                               SERVE_NAMESPACE)
                routing = ray_tpu.get(
                    controller.poll_routing.remote(
                        self._name, st.known_version, 10.0),
                    timeout=30)
                if routing is None:
                    return  # deployment deleted
                if routing.get("version", -1) != st.known_version:
                    self._apply_routing(routing)
            except Exception:
                time.sleep(1.0)

    def remote(self, *args, **kwargs):
        return self._call(self._method, args, kwargs)

    def _pick_replica(self):
        """One routing decision under the in-flight cap; returns
        (replica, key) or None when every replica is saturated."""
        st = self._state
        with st.lock:
            n = len(st.replicas)
            order = [(st.rr + i) % n for i in range(n)] if n else []
            st.rr += 1
            for idx in order:
                key = st.replicas[idx]._actor_id.binary()
                if st.in_flight.get(key, 0) < st.max_q:
                    st.in_flight[key] = st.in_flight.get(key, 0) + 1
                    return st.replicas[idx], key
        return None

    def _call(self, method, args, kwargs):
        self._refresh()
        wait_s = GLOBAL_CONFIG.serve_backpressure_timeout_s
        deadline = time.monotonic() + wait_s
        while True:
            pick = self._pick_replica()
            if pick is not None:
                replica, key = pick
                ref = replica.handle_request.remote(method, args, kwargs)
                return _TrackedRef(ref, self, key, method, args, kwargs)
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"no replica of {self._name!r} under its "
                    f"max_concurrent_queries cap within {wait_s:g}s")
            time.sleep(0.01)  # every replica saturated: backpressure

    def stream(self, *args, **kwargs):
        """Synchronous streaming call: yields the chunks of a generator
        (or async-generator) deployment method INCREMENTALLY — each
        chunk is pulled from the replica on demand (reference: streaming
        DeploymentResponseGenerator over handle_request_streaming).
        Replica-pinned: every chunk comes from the replica that started
        the stream."""
        self._refresh()
        wait_s = GLOBAL_CONFIG.serve_backpressure_timeout_s
        deadline = time.monotonic() + wait_s
        while True:
            pick = self._pick_replica()
            if pick is not None:
                break
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"no replica of {self._name!r} under its "
                    f"max_concurrent_queries cap within {wait_s:g}s")
            time.sleep(0.01)
        replica, key = pick
        try:
            req_ref = replica.handle_request.remote(self._method, args,
                                                    kwargs, True)
            try:
                ticket = ray_tpu.get(req_ref, timeout=60)
            except BaseException:
                # The replica may still complete the call and register a
                # stream whose sid we never learned — reap it so the
                # in-flight slot isn't held forever.
                _reap_orphan_stream(replica, req_ref)
                raise
            if not (isinstance(ticket, dict)
                    and "__serve_stream__" in ticket):
                # Non-generator method: degrade to a one-item stream.
                yield ticket
                return
            sid = ticket["__serve_stream__"]
            try:
                while True:
                    out = ray_tpu.get(replica.next_chunk.remote(sid),
                                      timeout=60)
                    if out.get("done"):
                        return
                    yield out["chunk"]
            except BaseException:
                # Any abandonment (consumer close, get timeout, worker
                # error) must release the replica's stream slot.
                try:
                    ray_tpu.get(replica.cancel_stream.remote(sid),
                                timeout=10)
                except Exception:
                    pass
                raise
        finally:
            self._done(key)

    async def stream_async(self, method, args, kwargs, *,
                           timeout: float = 60.0):
        """Async streaming variant (the proxy's path): an async
        generator over the method's chunks."""
        import asyncio
        self._refresh()
        deadline = time.monotonic() + timeout
        while True:
            pick = self._pick_replica()
            if pick is not None:
                break
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"no replica of {self._name!r} under its "
                    f"max_concurrent_queries cap within {timeout}s")
            await asyncio.sleep(0.005)
        replica, key = pick
        try:
            # Per-step timeout: a wedged generator must not hold this
            # coroutine (and the in-flight slot) forever — mirror the
            # sync stream()'s bounded gets.
            req_ref = replica.handle_request.remote(method, args, kwargs,
                                                    True)
            try:
                ticket = await asyncio.wait_for(
                    asyncio.wrap_future(req_ref.future()), timeout)
            except BaseException:
                # Unknown-sid orphan (see stream()): reap off-loop.
                _reap_orphan_stream(replica, req_ref)
                raise
            if not (isinstance(ticket, dict)
                    and "__serve_stream__" in ticket):
                yield ticket
                return
            sid = ticket["__serve_stream__"]
            try:
                while True:
                    out = await asyncio.wait_for(asyncio.wrap_future(
                        replica.next_chunk.remote(sid).future()), timeout)
                    if out.get("done"):
                        return
                    yield out["chunk"]
            except BaseException:
                # Same slot-release contract as the sync stream().
                try:
                    await asyncio.wait_for(asyncio.wrap_future(
                        replica.cancel_stream.remote(sid).future()), 10)
                except Exception:
                    pass
                raise
        finally:
            self._done(key)

    async def call_async(self, method, args, kwargs, *,
                         timeout: float = 60.0, _retried=False):
        """Async-native request path (reference: the ASGI proxy awaits the
        router/replica without burning a thread per request)."""
        import asyncio

        from ray_tpu.exceptions import ActorDiedError

        self._refresh()
        deadline = time.monotonic() + timeout
        while True:
            pick = self._pick_replica()
            if pick is not None:
                break
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"no replica of {self._name!r} under its "
                    f"max_concurrent_queries cap within {timeout}s")
            await asyncio.sleep(0.005)
        replica, key = pick
        ref = replica.handle_request.remote(method, args, kwargs)
        released = False

        def release(_=None):
            nonlocal released
            if not released:
                released = True
                self._done(key)

        try:
            fut = asyncio.wrap_future(ref.future())
            try:
                result = await asyncio.wait_for(
                    fut, max(0.1, deadline - time.monotonic()))
            except asyncio.TimeoutError:
                # The request is STILL running on the replica — keep its
                # in-flight slot charged until the underlying call
                # completes, or the admission cap would over-admit.
                fut.add_done_callback(release)
                raise TimeoutError(
                    f"request to {self._name!r} timed out")
            release()
            return result
        except ActorDiedError:
            release()
            if _retried:
                raise
            self._on_replica_error()
            return await self.call_async(
                method, args, kwargs,
                timeout=max(0.1, deadline - time.monotonic()),
                _retried=True)
        except TimeoutError:
            raise
        except BaseException:
            release()
            raise

    def _done(self, key: bytes):
        st = self._state
        with st.lock:
            if key in st.in_flight:
                st.in_flight[key] = max(0, st.in_flight[key] - 1)

    def _on_replica_error(self):
        try:
            controller = ray_tpu.get_actor(CONTROLLER_NAME, SERVE_NAMESPACE)
            ray_tpu.get(controller.heal.remote(self._name), timeout=60)
        except Exception:
            pass
        self._refresh(force=True)

    def __reduce__(self):
        return (DeploymentHandle, (self._name, self._method))


class _MethodCaller:
    def __init__(self, handle: DeploymentHandle, method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs):
        return self._handle._call(self._method, args, kwargs)


class _TrackedRef:
    """Wraps the reply ref to release the in-flight slot on result() and
    retry once through a healed replica set on replica death."""

    def __init__(self, ref, handle: DeploymentHandle, key: bytes,
                 method: str, args, kwargs, retried: bool = False):
        self._ref = ref
        self._handle = handle
        self._idx = key
        self._request = (method, args, kwargs)
        self._retried = retried

    def result(self, timeout: Optional[float] = None):
        from ray_tpu.exceptions import ActorDiedError, RayTpuTimeoutError
        try:
            value = ray_tpu.get(self._ref, timeout=timeout)
        except ActorDiedError:
            self._handle._done(self._idx)
            if self._retried:
                raise
            self._handle._on_replica_error()
            method, args, kwargs = self._request
            retry = self._handle._call(method, args, kwargs)
            retry._retried = True
            return retry.result(timeout)
        except RayTpuTimeoutError:
            # Still executing on the replica: keep the slot charged until
            # it actually finishes (admission-cap correctness).
            handle, key = self._handle, self._idx
            self._ref.future().add_done_callback(
                lambda _: handle._done(key))
            raise
        except BaseException:
            self._handle._done(self._idx)
            raise
        self._handle._done(self._idx)
        return value

    @property
    def ref(self):
        return self._ref
