"""ASGI ingress for Serve deployments.

Reference parity: serve/api.py `@serve.ingress(app)` (mount a
FastAPI/Starlette/any-ASGI app on a deployment) + the proxy's ASGI host
(serve/_private/http_proxy.py:250).  Here the replica RUNS the ASGI
protocol itself and streams response events back through the generic
replica streaming plane (_private.ReplicaActor.next_chunk), so chunked/
SSE responses flow to the HTTP client incrementally and replica-pinned.

Usage — any ASGI callable works (no framework dependency):

    async def app(scope, receive, send): ...
    @serve.deployment
    @serve.ingress(app)
    class Api:
        pass

Requests to /{deployment}/{path} reach the app with `path` as its route
(root_path = /{deployment}).
"""

from __future__ import annotations

import asyncio
from typing import Any, Callable, Dict


def ingress(asgi_app: Callable):
    """Class decorator mounting an ASGI app on the deployment.  The
    wrapped class gains `__asgi_call__`, an async generator the proxy
    consumes: first item = {"status", "headers"}, then body chunks."""

    def wrap(cls):
        class AsgiWrapped(cls):
            __serve_asgi__ = True

            async def __asgi_call__(self, request: Dict[str, Any]):
                app = getattr(self, "__asgi_app__", None)
                if app is None:
                    app = asgi_app
                    # Factory support (@ingress(lambda: build_app())):
                    # build ONCE PER REPLICA — per-request construction
                    # would reset in-app state and re-pay route setup.
                    if not _looks_asgi(app):
                        app = app()
                    self.__asgi_app__ = app
                async for event in _run_asgi(app, request):
                    yield event

        AsgiWrapped.__name__ = cls.__name__
        AsgiWrapped.__qualname__ = getattr(cls, "__qualname__",
                                           cls.__name__)
        return AsgiWrapped

    return wrap


def _looks_asgi(app) -> bool:
    import inspect
    if inspect.iscoroutinefunction(app):
        return True
    call = getattr(app, "__call__", None)
    return call is not None and inspect.iscoroutinefunction(call)


async def _run_asgi(app, request: Dict[str, Any]):
    """Drive one ASGI http request/response cycle, yielding the response
    start followed by each body chunk AS THE APP PRODUCES THEM (a
    bounded queue hands events from the app task to this generator, so
    a slow consumer backpressures the app)."""
    body = request.get("body") or b""
    scope = {
        "type": "http",
        "asgi": {"version": "3.0", "spec_version": "2.3"},
        "http_version": "1.1",
        "method": request.get("method", "GET"),
        "scheme": "http",
        "path": request.get("path", "/"),
        "raw_path": request.get("path", "/").encode(),
        "query_string": request.get("query_string", "").encode(),
        "root_path": request.get("root_path", ""),
        "headers": [(k.encode().lower(), v.encode())
                    for k, v in request.get("headers", [])],
        "client": ("127.0.0.1", 0),
        "server": ("127.0.0.1", 0),
    }
    consumed = False

    async def receive():
        nonlocal consumed
        if consumed:
            # Block until the cycle ends: frameworks (Starlette's
            # listen_for_disconnect) await receive() concurrently to
            # detect client disconnects — fabricating one here would
            # make every StreamingResponse cancel itself immediately.
            # The app task is cancelled at stream end, which is the
            # only "disconnect" this replica-side shim can observe.
            await asyncio.Event().wait()
        consumed = True
        return {"type": "http.request", "body": body, "more_body": False}

    events: asyncio.Queue = asyncio.Queue(maxsize=4)

    async def send(event):
        await events.put(event)

    async def run():
        try:
            await app(scope, receive, send)
        except Exception as e:  # surfaces as a 500 with the error text
            await events.put({"type": "__error__", "error": repr(e)})
        finally:
            await events.put(None)

    task = asyncio.ensure_future(run())
    started = False
    try:
        while True:
            ev = await events.get()
            if ev is None:
                return
            kind = ev.get("type")
            if kind == "__error__":
                if not started:
                    yield {"status": 500,
                           "headers": [("content-type", "text/plain")]}
                    yield ev["error"].encode()
                    return
                # Mid-stream failure: abort the stream so the client sees
                # a broken response, not a clean (truncated) end-of-body.
                raise RuntimeError(
                    f"ASGI app failed mid-stream: {ev['error']}")
            if kind == "http.response.start":
                started = True
                yield {"status": ev.get("status", 200),
                       "headers": [(k.decode(), v.decode())
                                   for k, v in ev.get("headers", [])]}
            elif kind == "http.response.body":
                chunk = ev.get("body", b"")
                if chunk:
                    yield bytes(chunk)
                if not ev.get("more_body", False):
                    return
    finally:
        task.cancel()
