"""Serve public API (reference: python/ray/serve/api.py — serve.start,
@serve.deployment, serve.run, serve.delete, serve.status, serve.shutdown,
deployment .bind() graphs, get_deployment_handle)."""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import ray_tpu
from ray_tpu.serve._private import (
    CONTROLLER_NAME, SERVE_NAMESPACE, AutoscalingConfig, DeploymentConfig,
    DeploymentHandle, ServeController)

_http_proxy = None


def _get_or_start_controller():
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME, SERVE_NAMESPACE)
    except ValueError:
        controller = ServeController.options(
            name=CONTROLLER_NAME, namespace=SERVE_NAMESPACE,
            lifetime="detached", num_cpus=0.1,
            get_if_exists=True).remote()
        # Fire-and-forget: the autoscaling/reconciliation loop runs on one
        # of the threaded controller's pool threads (idempotent).
        controller.run_control_loop.remote()
    return controller


def start(http_host: str = "127.0.0.1", http_port: int = 0,
          with_proxy: bool = False) -> Optional[int]:
    """Start the Serve control plane (+ optionally the HTTP ingress).
    Returns the proxy port when a proxy was started."""
    global _http_proxy
    _get_or_start_controller()
    if with_proxy:
        if _http_proxy is None:
            from ray_tpu.serve._proxy import HTTPProxyActor
            _http_proxy = HTTPProxyActor.options(num_cpus=0.1).remote(
                http_host, http_port)
        return ray_tpu.get(_http_proxy.address.remote(), timeout=60)
    return None


class Application:
    """A bound deployment (graph node) ready for serve.run
    (reference: serve/dag.py + deployment .bind())."""

    def __init__(self, deployment: "Deployment", args, kwargs):
        self.deployment = deployment
        self.args = args
        self.kwargs = kwargs


class Deployment:
    """Reference: serve/deployment.py — the @serve.deployment object."""

    def __init__(self, cls_or_fn, name: str, config: DeploymentConfig):
        self._cls_or_fn = cls_or_fn
        self.name = name
        self._config = config

    def options(self, *, name: Optional[str] = None,
                num_replicas: Optional[int] = None,
                max_concurrent_queries: Optional[int] = None,
                ray_actor_options: Optional[dict] = None,
                user_config: Any = None,
                autoscaling_config: Optional[dict] = None,
                queue_limit: Optional[int] = None) -> "Deployment":
        import copy
        cfg = copy.deepcopy(self._config)
        if num_replicas is not None:
            cfg.num_replicas = num_replicas
        if max_concurrent_queries is not None:
            cfg.max_concurrent_queries = max_concurrent_queries
        if queue_limit is not None:
            cfg.queue_limit = queue_limit
        if ray_actor_options is not None:
            cfg.ray_actor_options = dict(ray_actor_options)
        if user_config is not None:
            cfg.user_config = user_config
        if autoscaling_config is not None:
            cfg.autoscaling_config = (
                autoscaling_config
                if isinstance(autoscaling_config, AutoscalingConfig)
                else AutoscalingConfig(**autoscaling_config))
        new_name = name or self.name
        cfg.name = new_name
        return Deployment(self._cls_or_fn, new_name, cfg)

    def bind(self, *args, **kwargs) -> Application:
        return Application(self, args, kwargs)


def deployment(_cls_or_fn=None, *, name: Optional[str] = None,
               num_replicas: int = 1, max_concurrent_queries: int = 100,
               ray_actor_options: Optional[dict] = None,
               user_config: Any = None,
               autoscaling_config: Optional[dict] = None,
               queue_limit: Optional[int] = None):
    """@serve.deployment decorator.

    `queue_limit` bounds how many requests may WAIT for a replica slot
    (per deployment, per client process) before the router sheds new
    arrivals with ServeOverloadedError; None uses the
    ``serve_queue_length`` config default, 0 disables shedding."""

    def wrap(cls_or_fn):
        dep_name = name or getattr(cls_or_fn, "__name__", "deployment")
        auto = None
        if autoscaling_config is not None:
            auto = (autoscaling_config
                    if isinstance(autoscaling_config, AutoscalingConfig)
                    else AutoscalingConfig(**autoscaling_config))
        cfg = DeploymentConfig(
            name=dep_name, num_replicas=num_replicas,
            max_concurrent_queries=max_concurrent_queries,
            ray_actor_options=dict(ray_actor_options or {}),
            user_config=user_config,
            autoscaling_config=auto,
            queue_limit=queue_limit)
        return Deployment(cls_or_fn, dep_name, cfg)

    return wrap(_cls_or_fn) if _cls_or_fn is not None else wrap


def run(target: Application, *, _blocking: bool = False) -> DeploymentHandle:
    """Deploy an application graph; returns the ingress handle
    (reference: serve/api.py serve.run).  Bound arguments that are
    themselves Applications deploy first and are passed as handles —
    the deployment-graph composition path."""
    controller = _get_or_start_controller()

    def deploy_app(app: Application) -> DeploymentHandle:
        resolved_args = tuple(
            deploy_app(a) if isinstance(a, Application) else a
            for a in app.args)
        resolved_kwargs = {
            k: deploy_app(v) if isinstance(v, Application) else v
            for k, v in app.kwargs.items()}
        dep = app.deployment
        ray_tpu.get(controller.deploy.remote(
            dep._config, dep._cls_or_fn, resolved_args, resolved_kwargs),
            timeout=300)
        return DeploymentHandle(dep.name)

    return deploy_app(target)


def get_deployment_handle(name: str) -> DeploymentHandle:
    return DeploymentHandle(name)


def status() -> dict:
    controller = ray_tpu.get_actor(CONTROLLER_NAME, SERVE_NAMESPACE)
    return ray_tpu.get(controller.list_deployments.remote(), timeout=30)


def delete(name: str) -> bool:
    controller = ray_tpu.get_actor(CONTROLLER_NAME, SERVE_NAMESPACE)
    return ray_tpu.get(controller.delete_deployment.remote(name),
                       timeout=60)


def shutdown():
    global _http_proxy
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME, SERVE_NAMESPACE)
    except ValueError:
        return
    try:
        ray_tpu.get(controller.shutdown.remote(), timeout=60)
    finally:
        ray_tpu.kill(controller)
        if _http_proxy is not None:
            try:
                ray_tpu.kill(_http_proxy)
            except Exception:
                pass
            _http_proxy = None
