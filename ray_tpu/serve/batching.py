"""@serve.batch — dynamic request batching (reference: serve/batching.py).

Calls made within `batch_wait_timeout_s` (or until `max_batch_size`
accumulates) are combined into ONE invocation of the wrapped function with
a list argument; each caller gets its own element of the returned list.
TPU rationale: batching is how a replica keeps the MXU fed — many 1-item
requests become one batched forward pass.
"""

from __future__ import annotations

import functools
import threading
from typing import Callable, List, Optional


class _BatchQueue:
    def __init__(self, fn: Callable, max_batch_size: int, wait_s: float):
        self._orig_fn = fn
        self._fn = fn
        self._max = max_batch_size
        self._wait = wait_s
        self._lock = threading.Lock()
        self._items: List = []
        self._events: List[threading.Event] = []
        self._results: dict = {}
        self._timer: Optional[threading.Timer] = None

    def __reduce__(self):
        # Locks/timers are process-local; a pickled queue restarts empty.
        return (_BatchQueue, (self._orig_fn, self._max, self._wait))

    def submit(self, item):
        event = threading.Event()
        with self._lock:
            self._items.append(item)
            self._events.append(event)
            my_index = len(self._items) - 1
            flush = len(self._items) >= self._max
            if not flush and self._timer is None:
                self._timer = threading.Timer(self._wait, self._flush)
                self._timer.daemon = True
                self._timer.start()
        if flush:
            self._flush()
        event.wait()
        with self._lock:
            outcome = self._results.pop(event)
        if isinstance(outcome, BaseException):
            raise outcome
        return outcome

    def _flush(self):
        with self._lock:
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
            items, events = self._items, self._events
            self._items, self._events = [], []
        if not items:
            return
        try:
            outputs = self._fn(items)
            if len(outputs) != len(items):
                raise ValueError(
                    f"@serve.batch function returned {len(outputs)} results "
                    f"for {len(items)} inputs")
            outcomes = list(outputs)
        except BaseException as e:  # noqa: BLE001
            outcomes = [e] * len(items)
        with self._lock:
            for ev, out in zip(events, outcomes):
                self._results[ev] = out
        for ev in events:
            ev.set()


def batch(_fn=None, *, max_batch_size: int = 10,
          batch_wait_timeout_s: float = 0.01):
    """Decorator: fn(list_of_items) -> list_of_results becomes callable as
    fn(item) -> result with automatic batching."""

    def wrap(fn):
        # The queue is created lazily per process (it holds locks/timers,
        # which must never travel inside a pickled deployment class).
        holder: dict = {"queue": None}

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            if kwargs:
                # The batch function receives ONE list argument; there is
                # no sound way to batch per-call keyword arguments, and
                # silently dropping them corrupts results.
                raise TypeError(
                    f"@serve.batch function {fn.__name__!r} called with "
                    f"keyword arguments {sorted(kwargs)} — batched calls "
                    f"accept a single positional item")
            if len(args) not in (1, 2):
                raise TypeError(
                    f"@serve.batch function {fn.__name__!r} takes one "
                    f"positional item (plus self for methods), got "
                    f"{len(args)} positional arguments")
            if holder["queue"] is None:
                holder["queue"] = _BatchQueue(fn, max_batch_size,
                                              batch_wait_timeout_s)
            queue = holder["queue"]
            # Support both free functions fn(items) and methods
            # self.fn(items): the batched element is the LAST positional.
            item = args[-1]
            if len(args) == 2:
                # Bound method: bind fn to self ONCE, under the queue lock
                # — a concurrent _flush must never observe a half-swapped
                # callable, and rebinding every call would race submit().
                with queue._lock:
                    if queue._fn is queue._orig_fn:
                        queue._fn = fn.__get__(args[0], type(args[0]))
            return queue.submit(item)

        return wrapped

    return wrap(_fn) if _fn is not None else wrap
