"""ray_tpu.serve — model serving on actor replicas.

Reference parity: python/ray/serve/ (SURVEY.md §2.3): controller actor with
deployment reconciliation, replica actors hosting user callables, handle
router with power-of-two-choices routing, max_concurrent_queries
backpressure, bounded-queue load shedding + failure healing, graceful
replica draining, mid-stream failover, per-request deadlines, HTTP
ingress proxy, deployment-graph composition via .bind(), @serve.batch
dynamic batching.
"""

from ray_tpu.exceptions import (  # noqa: F401
    ReplicaStreamLostError,
    ServeOverloadedError,
)
from ray_tpu.serve.api import (  # noqa: F401
    Application,
    Deployment,
    delete,
    deployment,
    get_deployment_handle,
    run,
    shutdown,
    start,
    status,
)
from ray_tpu.serve.asgi import ingress  # noqa: F401
from ray_tpu.serve.batching import batch  # noqa: F401
from ray_tpu.serve.llm import LLMDeployment, llm_stream_resume  # noqa: F401
from ray_tpu.serve.kv_tier import (  # noqa: F401
    DecodeLLMDeployment,
    DisaggLLMHandle,
    KVBlockCodec,
    KVCodecError,
    KVTierCache,
    PrefillLLMDeployment,
    run_disaggregated,
)
from ray_tpu.serve._private import DeploymentHandle  # noqa: F401
