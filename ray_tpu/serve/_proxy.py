"""HTTP ingress proxy (reference: serve/_private/http_proxy.py:434 —
one proxy actor per node running an HTTP server; here aiohttp replaces
uvicorn/ASGI).

Routing: POST/GET /{deployment} — a JSON body becomes the callable's
single argument; the JSON-encoded return value is the response.
"""

from __future__ import annotations

import json
import threading

import ray_tpu


@ray_tpu.remote
class HTTPProxyActor:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        import asyncio

        from aiohttp import web

        from ray_tpu.serve._private import DeploymentHandle

        self._handles: dict[str, DeploymentHandle] = {}
        self._ready = threading.Event()
        self._port = None

        handles = self._handles

        async def dispatch(request: web.Request):
            name = request.match_info["deployment"]
            handle = handles.get(name)
            if handle is None:
                handle = DeploymentHandle(name)
                handles[name] = handle
            if request.can_read_body:
                try:
                    payload = await request.json()
                except json.JSONDecodeError:
                    payload = (await request.read()).decode()
            else:
                payload = dict(request.query) or None
            # Async-native path: the routing decision and the reply await
            # run on this event loop — no thread per in-flight request
            # (reference: fully-async ASGI proxy, http_proxy.py:250).
            try:
                result = await handle.call_async(
                    handle._method, (payload,), {}, timeout=60)
            except ValueError as e:
                return web.json_response({"error": str(e)}, status=404)
            except Exception as e:  # noqa: BLE001
                return web.json_response(
                    {"error": f"{type(e).__name__}: {e}"}, status=500)
            return web.json_response({"result": result})

        async def healthz(_):
            return web.Response(text="ok")

        async def dispatch_asgi(request: web.Request):
            """ASGI path: /{deployment}/{tail} — the replica runs the
            mounted app and streams response events back; chunked bodies
            flow to the HTTP client as they are produced (reference:
            http_proxy.py ASGI host + streaming responses)."""
            name = request.match_info["deployment"]
            handle = handles.get(name)
            if handle is None:
                handle = DeploymentHandle(name)
                handles[name] = handle
            req = {
                "method": request.method,
                "path": "/" + request.match_info.get("tail", ""),
                "query_string": request.query_string,
                "root_path": "/" + name,
                "headers": [(k, v) for k, v in request.headers.items()],
                "body": await request.read(),
            }
            try:
                agen = handle.stream_async("__asgi_call__", (req,), {})
                first = await agen.__anext__()
            except ValueError as e:
                return web.json_response({"error": str(e)}, status=404)
            except StopAsyncIteration:
                return web.Response(status=500, text="empty ASGI response")
            except Exception as e:  # noqa: BLE001 — incl. non-ASGI targets
                if "__asgi_call__" in str(e) or isinstance(e,
                                                           AttributeError):
                    return web.json_response(
                        {"error": f"deployment {name!r} does not mount "
                                  f"an ASGI app"}, status=404)
                return web.json_response(
                    {"error": f"{type(e).__name__}: {e}"}, status=500)
            from multidict import CIMultiDict
            hdrs = CIMultiDict()
            for k, v in first.get("headers", []):
                # Duplicate names are legitimate (Set-Cookie); only the
                # framing headers are ours to manage.
                if k.lower() not in ("content-length",
                                     "transfer-encoding"):
                    hdrs.add(k, v)
            resp = web.StreamResponse(status=first.get("status", 200),
                                      headers=hdrs)
            await resp.prepare(request)
            async for chunk in agen:
                await resp.write(chunk)
            await resp.write_eof()
            return resp

        def serve_forever():
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            app = web.Application()
            app.router.add_get("/-/healthz", healthz)
            app.router.add_route("*", "/{deployment}/{tail:.*}",
                                 dispatch_asgi)
            app.router.add_route("*", "/{deployment}", dispatch)
            runner = web.AppRunner(app)
            loop.run_until_complete(runner.setup())
            site = web.TCPSite(runner, host, port)
            loop.run_until_complete(site.start())
            self._port = site._server.sockets[0].getsockname()[1]
            self._ready.set()
            loop.run_forever()

        threading.Thread(target=serve_forever, daemon=True).start()
        self._ready.wait(30)

    def address(self):
        return self._port
