"""HTTP ingress proxy (reference: serve/_private/http_proxy.py:434 —
one proxy actor per node running an HTTP server; here aiohttp replaces
uvicorn/ASGI).

Routing: POST/GET /{deployment} — a JSON body becomes the callable's
single argument; the JSON-encoded return value is the response.
"""

from __future__ import annotations

import json
import threading

import ray_tpu


@ray_tpu.remote
class HTTPProxyActor:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        import asyncio

        from aiohttp import web

        from ray_tpu.serve._private import DeploymentHandle

        self._handles: dict[str, DeploymentHandle] = {}
        self._ready = threading.Event()
        self._port = None

        handles = self._handles

        async def dispatch(request: web.Request):
            name = request.match_info["deployment"]
            handle = handles.get(name)
            if handle is None:
                handle = DeploymentHandle(name)
                handles[name] = handle
            if request.can_read_body:
                try:
                    payload = await request.json()
                except json.JSONDecodeError:
                    payload = (await request.read()).decode()
            else:
                payload = dict(request.query) or None
            # Async-native path: the routing decision and the reply await
            # run on this event loop — no thread per in-flight request
            # (reference: fully-async ASGI proxy, http_proxy.py:250).
            try:
                result = await handle.call_async(
                    handle._method, (payload,), {}, timeout=60)
            except ValueError as e:
                return web.json_response({"error": str(e)}, status=404)
            except Exception as e:  # noqa: BLE001
                return web.json_response(
                    {"error": f"{type(e).__name__}: {e}"}, status=500)
            return web.json_response({"result": result})

        async def healthz(_):
            return web.Response(text="ok")

        def serve_forever():
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            app = web.Application()
            app.router.add_get("/-/healthz", healthz)
            app.router.add_route("*", "/{deployment}", dispatch)
            runner = web.AppRunner(app)
            loop.run_until_complete(runner.setup())
            site = web.TCPSite(runner, host, port)
            loop.run_until_complete(site.start())
            self._port = site._server.sockets[0].getsockname()[1]
            self._ready.set()
            loop.run_forever()

        threading.Thread(target=serve_forever, daemon=True).start()
        self._ready.wait(30)

    def address(self):
        return self._port
