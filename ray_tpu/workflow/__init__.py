"""ray_tpu.workflow — durable DAG execution with persisted step results.

Reference parity: python/ray/workflow/ (workflow_executor.py,
workflow_storage.py, task_executor.py): a DAG runs step by step, every
step's result is checkpointed to storage keyed by a deterministic step
id, and `resume` re-runs only steps without a stored result — crash and
driver-restart safe.
"""

from ray_tpu.workflow.api import (  # noqa: F401
    get_output,
    get_status,
    init,
    list_all,
    resume,
    run,
    run_async,
)

__all__ = ["get_output", "get_status", "init", "list_all", "resume",
           "run", "run_async"]
from ray_tpu.workflow import events  # noqa: F401,E402
