"""Workflow execution + storage.

Reference parity: python/ray/workflow/ — workflow_storage.py (per-step
persisted results under the workflow's storage prefix),
workflow_executor.py (resume skips completed steps), api.py (run/resume/
get_output/get_status/list_all).

Step identity: a deterministic id derived from the DAG structure
(function name + position), so the same DAG resumes against its stored
results.  Storage is a filesystem directory (set with workflow.init;
defaults to ~/.ray_tpu_workflows).
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.dag import ClassMethodNode, ClassNode, DAGNode, FunctionNode, InputNode

_storage_dir: Optional[str] = None


def init(storage: Optional[str] = None) -> None:
    """Set the workflow storage root (reference: workflow.init)."""
    global _storage_dir
    _storage_dir = storage or os.path.join(
        os.path.expanduser("~"), ".ray_tpu_workflows")
    os.makedirs(_storage_dir, exist_ok=True)


def _storage() -> str:
    if _storage_dir is None:
        init()
    return _storage_dir


def _wf_dir(workflow_id: str) -> str:
    d = os.path.join(_storage(), workflow_id)
    os.makedirs(os.path.join(d, "steps"), exist_ok=True)
    return d


def _step_id(node: DAGNode, path: str) -> str:
    """Deterministic id: structural path + callable name."""
    if isinstance(node, FunctionNode):
        name = getattr(node._remote_fn, "__name__", "fn")
    elif isinstance(node, ClassMethodNode):
        name = node._method
    elif isinstance(node, ClassNode):
        name = node._actor_cls._cls.__name__
    else:
        name = type(node).__name__
    return hashlib.sha1(f"{path}:{name}".encode()).hexdigest()[:16]


class _StepStore:
    def __init__(self, workflow_id: str):
        self.dir = _wf_dir(workflow_id)

    def has(self, step_id: str) -> bool:
        return os.path.exists(os.path.join(self.dir, "steps", step_id))

    def load(self, step_id: str):
        with open(os.path.join(self.dir, "steps", step_id), "rb") as f:
            return pickle.load(f)

    def save(self, step_id: str, value) -> None:
        path = os.path.join(self.dir, "steps", step_id)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(value, f)
        os.replace(tmp, path)

    def meta(self, **updates) -> dict:
        path = os.path.join(self.dir, "meta.json")
        meta = {}
        if os.path.exists(path):
            with open(path) as f:
                meta = json.load(f)
        if updates:
            meta.update(updates)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(meta, f)
            os.replace(tmp, path)
        return meta


def _execute_durable(node: DAGNode, store: _StepStore, input_value,
                     path: str = "r", seen: Optional[dict] = None):
    """Post-order durable execution: each step's RESULT (not ref) persists
    before its parent runs (reference: task_executor.py checkpointing).
    `seen` (node uuid -> value) makes a node SHARED by multiple parents
    execute exactly once per run, matching DAGNode.execute — its result is
    still checkpointed under every structural path so resume finds it."""
    if seen is None:
        seen = {}
    if isinstance(node, InputNode):
        return input_value
    if node._uuid in seen:
        return seen[node._uuid]
    if isinstance(node, ClassNode):
        # Actors are not durable steps; reconstruct (once) each run.
        args, kwargs = _resolve_bound(node, store, input_value, path, seen)
        actor = node._actor_cls.remote(*args, **kwargs)
        seen[node._uuid] = actor
        return actor
    sid = _step_id(node, path)
    if store.has(sid):
        value = store.load(sid)
        seen[node._uuid] = value
        return value
    if isinstance(node, ClassMethodNode):
        actor = _execute_durable(node._class_node, store, input_value,
                                 path + ".actor", seen)
        args, kwargs = _resolve_bound(node, store, input_value, path, seen)
        value = ray_tpu.get(getattr(actor, node._method)
                            .remote(*args, **kwargs))
    elif isinstance(node, FunctionNode):
        args, kwargs = _resolve_bound(node, store, input_value, path, seen)
        value = ray_tpu.get(node._remote_fn.remote(*args, **kwargs))
    else:
        raise TypeError(f"cannot execute {type(node).__name__} durably")
    store.save(sid, value)
    seen[node._uuid] = value
    return value


def _resolve_bound(node: DAGNode, store, input_value, path, seen):
    args = []
    for i, a in enumerate(node._bound_args):
        args.append(
            _execute_durable(a, store, input_value, f"{path}.a{i}", seen)
            if isinstance(a, DAGNode) else a)
    kwargs = {}
    for k, v in node._bound_kwargs.items():
        kwargs[k] = (
            _execute_durable(v, store, input_value, f"{path}.k{k}", seen)
            if isinstance(v, DAGNode) else v)
    return tuple(args), kwargs


def run(dag: DAGNode, *, workflow_id: Optional[str] = None,
        input_value: Any = None):
    """Execute a DAG durably; returns the final result.  Re-running (or
    resuming) the same workflow_id skips steps whose results are stored."""
    import uuid as _uuid
    workflow_id = workflow_id or (
        f"wf-{int(time.time())}-{os.getpid()}-{_uuid.uuid4().hex[:8]}")
    store = _StepStore(workflow_id)
    store.meta(status="RUNNING", started_at=time.time())
    # The DAG structure is persisted so resume() works without the
    # original python objects in scope.
    dag_path = os.path.join(store.dir, "dag.pkl")
    if not os.path.exists(dag_path):
        import cloudpickle
        with open(dag_path + ".tmp", "wb") as f:
            cloudpickle.dump((dag, input_value), f)
        os.replace(dag_path + ".tmp", dag_path)
    try:
        result = _execute_durable(dag, store, input_value)
    except BaseException as e:
        store.meta(status="FAILED", error=repr(e))
        raise
    store.save("__output__", result)
    store.meta(status="SUCCESSFUL", finished_at=time.time())
    return result


def run_async(dag: DAGNode, *, workflow_id: Optional[str] = None,
              input_value: Any = None):
    """Run in a background task; returns an ObjectRef of the result."""
    import cloudpickle
    blob = cloudpickle.dumps((dag, input_value))
    storage = _storage()

    @ray_tpu.remote
    def _driver(blob, workflow_id, storage):
        import cloudpickle as cp

        from ray_tpu import workflow as wf
        wf.init(storage)
        dag, input_value = cp.loads(blob)
        return wf.run(dag, workflow_id=workflow_id, input_value=input_value)

    return _driver.remote(blob, workflow_id, storage)


def resume(workflow_id: str):
    """Resume from storage: completed steps load, missing ones re-run
    (reference: workflow_executor resume path)."""
    store = _StepStore(workflow_id)
    dag_path = os.path.join(store.dir, "dag.pkl")
    if not os.path.exists(dag_path):
        raise ValueError(f"workflow {workflow_id!r} has no stored DAG")
    import cloudpickle
    with open(dag_path, "rb") as f:
        dag, input_value = cloudpickle.load(f)
    return run(dag, workflow_id=workflow_id, input_value=input_value)


def get_output(workflow_id: str):
    store = _StepStore(workflow_id)
    if not store.has("__output__"):
        raise ValueError(f"workflow {workflow_id!r} has no stored output")
    return store.load("__output__")


def get_status(workflow_id: str) -> str:
    return _StepStore(workflow_id).meta().get("status", "UNKNOWN")


def list_all() -> List[Dict[str, Any]]:
    root = _storage()
    out = []
    for name in sorted(os.listdir(root)):
        meta_path = os.path.join(root, name, "meta.json")
        if os.path.isfile(meta_path):
            with open(meta_path) as f:
                meta = json.load(f)
            out.append({"workflow_id": name, **meta})
    return out
