"""Workflow event providers: durable waits on external events.

Reference parity: python/ray/workflow/ — `workflow.wait_for_event` with
pluggable `EventListener`s and the HTTP event provider
(http_event_provider.py): a workflow step blocks until an external
system delivers an event, the event's payload is CHECKPOINTED with the
step, and a resumed workflow replays the recorded payload instead of
waiting again (exactly-once event consumption).

Built-ins:
  - EventListener: the plugin interface (async poll_for_event).
  - TimerListener: fires after a duration (reference: workflow timers).
  - HTTPEventProvider: a tiny HTTP endpoint; an external POST to
    /event/<key> delivers the payload to any step waiting on that key.

Usage:
    from ray_tpu import workflow
    from ray_tpu.workflow.events import HTTPEventProvider, wait_for_event

    provider = HTTPEventProvider(port=0)   # share provider.address
    dag = step2.bind(wait_for_event.bind(provider.listener("approval")))
    workflow.run(dag, workflow_id="w1")    # blocks at the event step
    # elsewhere: POST {"ok": true} to http://host:port/event/approval
"""

from __future__ import annotations

import asyncio
import json
import threading
from typing import Any, Dict

import ray_tpu


class EventListener:
    """Plugin interface (reference: workflow/event_listener.py)."""

    async def poll_for_event(self) -> Any:
        """Block until the event arrives; the return value is the event
        payload (checkpointed by the event step)."""
        raise NotImplementedError


class TimerListener(EventListener):
    def __init__(self, seconds: float):
        self.seconds = seconds

    async def poll_for_event(self) -> Any:
        await asyncio.sleep(self.seconds)
        return {"fired_after_s": self.seconds}


@ray_tpu.remote(num_cpus=0)
class _EventMailbox:
    """Named actor holding delivered events per key (the durable
    rendezvous between external posters and waiting steps)."""

    def __init__(self):
        self._events: Dict[str, Any] = {}
        self._waiters: Dict[str, asyncio.Event] = {}

    async def deliver(self, key: str, payload) -> bool:
        self._events[key] = payload
        ev = self._waiters.pop(key, None)
        if ev is not None:
            ev.set()
        return True

    async def wait(self, key: str):
        while key not in self._events:
            ev = self._waiters.get(key)
            if ev is None:
                ev = self._waiters[key] = asyncio.Event()
            try:
                await asyncio.wait_for(ev.wait(), 5.0)
            except asyncio.TimeoutError:
                pass
        return self._events[key]

    async def peek(self, key: str):
        return self._events.get(key)


class HTTPEventProvider:
    """HTTP ingress for events (reference: http_event_provider.py):
    POST /event/<key> with a JSON body delivers that payload to waiting
    workflow steps; GET /event/<key> shows whether it was delivered."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 name: str = "wf_event_mailbox"):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        self._mailbox = _EventMailbox.options(
            name=name, get_if_exists=True, lifetime="detached").remote()
        mailbox = self._mailbox

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _reply(self, code: int, body: dict):
                data = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_POST(self):
                if not self.path.startswith("/event/"):
                    return self._reply(404, {"error": "unknown path"})
                key = self.path[len("/event/"):]
                n = int(self.headers.get("Content-Length", 0))
                try:
                    payload = json.loads(self.rfile.read(n) or b"null")
                except ValueError:
                    return self._reply(400, {"error": "bad json"})
                ray_tpu.get(mailbox.deliver.remote(key, payload))
                self._reply(200, {"delivered": key})

            def do_GET(self):
                if not self.path.startswith("/event/"):
                    return self._reply(404, {"error": "unknown path"})
                key = self.path[len("/event/"):]
                got = ray_tpu.get(mailbox.peek.remote(key))
                self._reply(200, {"key": key, "delivered": got is not None})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self.address = f"http://{host}:{self.port}"
        threading.Thread(target=self._httpd.serve_forever, daemon=True,
                         name="wf-events").start()

    def listener(self, key: str) -> "MailboxListener":
        return MailboxListener(key,
                               mailbox_name="wf_event_mailbox")

    def stop(self):
        self._httpd.shutdown()


class MailboxListener(EventListener):
    """Waits on a key in the named mailbox actor (picklable: steps
    resolve the actor by name wherever they execute)."""

    def __init__(self, key: str, mailbox_name: str = "wf_event_mailbox"):
        self.key = key
        self.mailbox_name = mailbox_name

    async def poll_for_event(self) -> Any:
        mailbox = ray_tpu.get_actor(self.mailbox_name)
        ref = mailbox.wait.remote(self.key)
        # Drive the blocking get off the loop.
        return await asyncio.get_event_loop().run_in_executor(
            None, lambda: ray_tpu.get(ref, timeout=None))


def wait_for_event(listener: EventListener) -> Any:
    """The event STEP body: bind `event_step` into a workflow DAG; the
    return value (the event payload) checkpoints like any step result,
    so a resumed workflow replays it instead of waiting again
    (reference: workflow.wait_for_event exactly-once semantics)."""
    return asyncio.run(listener.poll_for_event())


# Bindable step: dag = consumer.bind(events.event_step.bind(listener))
event_step = ray_tpu.remote(wait_for_event)
