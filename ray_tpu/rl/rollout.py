"""Rollout actors: versioned trajectory generation for the RL substrate.

Two gang members, one contract — `adopt(version, weights)` swaps the
policy in place and `rollout()`/`sample_versioned()` emits trajectories
TAGGED with the policy version that produced them:

- `EngineRolloutActor` generates through the serving `InferenceEngine`:
  continuous batching across concurrent episodes, prefix-cache reuse of
  the shared prompt template, and speculative decoding as a pure
  rollout-throughput multiplier (token-exact, so the behavior policy is
  unchanged).  The engine runs with `capture_logp=True`, so every
  emitted token carries the behavior log-prob V-trace needs.
- `EnvRolloutActor` is the classic vectorized-env `RolloutWorker` in
  time-major V-trace layout (`postprocess=False`), version-tagged the
  same way — the CartPole parity path.

Weight adoption on the engine path is BETWEEN scheduler steps: in-flight
lanes keep their paged-KV state and continue under the new weights, so
a publish never drops rollout work.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ray_tpu.rllib.rollout_worker import (RolloutWorker,
                                          _force_cpu_platform_if_worker)
from ray_tpu.rllib.sample_batch import SampleBatch
from ray_tpu.util import spans


class EngineRolloutActor:
    """Trajectory generation through the serving engine.

    Usable in-process or as a `ray_tpu` actor (one per CPU slot — the
    worker process pins jax to CPU so rollout gangs never fight the
    learner for the chip).
    """

    def __init__(self, model="gpt", config="nano", *, params=None,
                 max_lanes: int = 4, spec_k: int = 0,
                 temperature: float = 1.0, seed: int = 0,
                 prefix_cache: bool = True,
                 reward_fn: Optional[Callable[[List[int], List[int]],
                                              float]] = None,
                 **engine_kwargs):
        _force_cpu_platform_if_worker()
        from ray_tpu.inference.engine import InferenceEngine
        self.engine = InferenceEngine(
            model, config, params, max_lanes=max_lanes, spec_k=spec_k,
            seed=seed, prefix_cache=prefix_cache, auto_start=False,
            capture_logp=True, **engine_kwargs)
        self.temperature = float(temperature)
        self.version = 0
        self._reward_fn = reward_fn
        self._total_tokens = 0

    # -- weights -----------------------------------------------------------
    def adopt(self, version: int, weights: Any) -> int:
        """In-place weight swap: live lanes keep generating."""
        with spans.span("rl", "adopt", version=int(version),
                        live_lanes=self.engine.num_active):
            self.engine.update_params(weights, int(version))
        self.version = int(version)
        return self.version

    def get_version(self) -> int:
        return self.version

    # -- sampling ----------------------------------------------------------
    def rollout(self, prompts: Sequence[Sequence[int]],
                max_new_tokens: int = 32,
                seed: Optional[int] = None
                ) -> Tuple[SampleBatch, int, Dict]:
        """Generate one trajectory per prompt; all prompts ride the
        lane scheduler concurrently (continuous batching — finished
        lanes are refilled from the queue mid-flight).

        Returns (batch, version, metrics): `batch` is a time-major
        [T, B] SampleBatch of token trajectories (right-padded to the
        longest episode, `valid` masks the padding) and `version` is the
        policy version EVERY token in it was sampled under — rollout()
        drains the gang between adoptions, so a batch is never
        version-mixed."""
        import time
        t0 = time.monotonic()
        version = self.version
        with spans.span("rl", "rollout", version=version,
                        prompts=len(prompts)):
            handles = [
                self.engine.submit(
                    list(p), max_new_tokens, temperature=self.temperature,
                    seed=None if seed is None else seed + i)
                for i, p in enumerate(prompts)]
            while self.engine.step():
                pass
            episodes = [(h.tokens(), h.logps) for h in handles]
        B = len(episodes)
        T = max(1, max(len(toks) for toks, _ in episodes))
        actions = np.zeros((T, B), np.int32)
        logp = np.zeros((T, B), np.float32)
        rewards = np.zeros((T, B), np.float32)
        terminateds = np.zeros((T, B), np.bool_)
        valid = np.zeros((T, B), np.bool_)
        tokens_out = 0
        for b, ((toks, lps), prompt) in enumerate(zip(episodes, prompts)):
            n = len(toks)
            tokens_out += n
            actions[:n, b] = toks
            logp[:n, b] = lps
            valid[:n, b] = True
            if n:
                terminateds[n - 1, b] = True
                if self._reward_fn is not None:
                    rewards[n - 1, b] = float(
                        self._reward_fn(list(prompt), toks))
        self._total_tokens += tokens_out
        batch = SampleBatch({
            SampleBatch.ACTIONS: actions,
            SampleBatch.ACTION_LOGP: logp,
            SampleBatch.REWARDS: rewards,
            SampleBatch.TERMINATEDS: terminateds,
            SampleBatch.TRUNCATEDS: np.zeros((T, B), np.bool_),
            "valid": valid,
            "policy_version": np.full((T, B), version, np.int32),
        })
        wall = time.monotonic() - t0
        st = self.engine.stats()
        metrics = {"tokens": tokens_out, "wall_s": wall,
                   "tokens_per_s": tokens_out / wall if wall > 0 else 0.0,
                   "total_tokens": self._total_tokens,
                   "prefix_hit_tokens": st["prefix_hit_tokens"],
                   "spec_accepted_per_step": st["spec_accepted_per_step"]}
        return batch, version, metrics

    def stats(self) -> dict:
        return self.engine.stats()

    def ping(self) -> bool:
        return True


class EnvRolloutActor(RolloutWorker):
    """Vectorized-env rollout worker with version tagging.

    Always collects in the time-major V-trace layout (postprocess is
    forced off); `sample_versioned()` is `sample()` plus the policy
    version the fragment was collected under.
    """

    def __init__(self, *args, **kwargs):
        kwargs["postprocess"] = False
        super().__init__(*args, **kwargs)
        self.version = 0

    def adopt(self, version: int, weights: Any) -> int:
        with spans.span("rl", "adopt", version=int(version)):
            self.set_weights(weights)
        self.version = int(version)
        return self.version

    def get_version(self) -> int:
        return self.version

    def sample_versioned(self) -> Tuple[SampleBatch, int, Dict]:
        version = self.version
        with spans.span("rl", "rollout", version=version):
            batch, metrics = self.sample()
        T, B = batch[SampleBatch.ACTIONS].shape[:2]
        batch["policy_version"] = np.full((T, B), version, np.int32)
        return batch, version, metrics
