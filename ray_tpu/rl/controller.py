"""Podracer: the async actor/learner control loop.

Composition of the substrate (Podracer/Sebulba shape, PAPERS.md): a
gang of versioned rollout actors runs ahead asynchronously; delivered
fragments enter the bounded `TrajectoryQueue` (stale-by->k batches are
dropped at the door, a full queue backpressures the producer instead of
growing a staleness ramp); the stale-tolerant V-trace learner drains
whatever is admissible; and every `publish_interval` updates the new
weights cross the object plane ONCE and the gang adopts by reference —
engine-backed actors swap between scheduler steps without dropping
in-flight lanes.

Fault tolerance is part of the loop, not a wrapper: a dead rollout
worker is detected at delivery, replaced, and re-adopts the CURRENT
published weights (`rl/worker_replaced`); a dead learner is rebuilt
from the newest COMMITTED checkpoint (`recover_learner()` ->
`rl/learner_resume`) and the queue — which the controller owns, not the
learner — survives with its entries re-screened against the restored
version, so resume never trains on trajectories from beyond its
horizon.

Driver surface matches `rllib`: `PodracerConfig().environment(...)
.training(...).build()`, then `.train()` per iteration.
"""

from __future__ import annotations

from typing import Any, Dict, List

import ray_tpu
from ray_tpu.rl.learner import StaleTolerantLearner
from ray_tpu.rl.rollout import EnvRolloutActor
from ray_tpu.rl.trajectory import TrajectoryQueue
from ray_tpu.rl.weights import WeightPublisher
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.worker_set import WorkerSet
from ray_tpu.util import events
from ray_tpu.util.metrics import Counter

_MET = None


def _metrics() -> dict:
    global _MET
    if _MET is None:
        _MET = {
            "replaced": Counter(
                "rl_workers_replaced",
                "Rollout workers replaced after death (re-formed + "
                "re-adopted the current weights)"),
        }
    return _MET


class PodracerConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=Podracer)
        self.lr = 6e-4
        self.grad_clip = 40.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.clip_rho_threshold = 1.0
        self.clip_c_threshold = 1.0
        # Async-loop knobs: k=0 forces on-policy (every batch must be at
        # the learner's version — the PPO-parity configuration).
        self.staleness_bound = 1
        self.queue_capacity = 8
        self.publish_interval = 1     # learner updates between publishes
        self.min_updates_per_step = 1
        # Durability: ckpt_dir=None disables checkpointing.
        self.ckpt_dir = None
        self.ckpt_interval = 20


class Podracer(Algorithm):
    def setup(self) -> None:
        cfg = self.config
        self.workers = WorkerSet(
            num_workers=max(cfg.num_rollout_workers, 1),
            num_cpus_per_worker=cfg.num_cpus_per_worker,
            worker_cls=EnvRolloutActor,
            worker_kwargs=dict(
                env=cfg.env, num_envs=cfg.num_envs_per_worker,
                rollout_fragment_length=cfg.rollout_fragment_length,
                gamma=cfg.gamma, lam=cfg.lambda_,
                hidden=cfg.model_hidden, seed=cfg.seed))
        self.learner = self._make_learner()
        self.queue = TrajectoryQueue(cfg.queue_capacity,
                                     cfg.staleness_bound)
        self.publisher = WeightPublisher()
        self.publisher.publish(self.learner.get_weights(),
                               self.workers.remote_workers,
                               version=self.learner.version)
        self._inflight: Dict[Any, Any] = {}   # sample ref -> worker
        self._idle: List[Any] = []            # backpressured workers

    def _make_learner(self) -> StaleTolerantLearner:
        cfg = self.config
        return StaleTolerantLearner(
            self.obs_dim, self.num_actions, hidden=cfg.model_hidden,
            gamma=cfg.gamma, lr=cfg.lr, grad_clip=cfg.grad_clip,
            vf_loss_coeff=cfg.vf_loss_coeff,
            entropy_coeff=cfg.entropy_coeff,
            clip_rho_threshold=cfg.clip_rho_threshold,
            clip_c_threshold=cfg.clip_c_threshold, seed=cfg.seed,
            ckpt_dir=cfg.ckpt_dir, ckpt_interval=cfg.ckpt_interval)

    # -- gang management ---------------------------------------------------
    def _launch(self, worker) -> None:
        self._inflight[worker.sample_versioned.remote()] = worker

    def _launch_all_idle(self) -> None:
        # Backpressured workers restart only once the queue has room.
        while self._idle and not self.queue.full:
            self._launch(self._idle.pop())
        busy = set(map(id, self._inflight.values()))
        busy |= set(map(id, self._idle))
        for w in self.workers.remote_workers:
            if id(w) not in busy:
                self._launch(w)

    def _replace(self, worker) -> None:
        replacement = self.workers.replace_worker(worker)
        _metrics()["replaced"].inc()
        events.record("rl", "worker_replaced",
                      version=self.publisher.version)
        try:
            # Re-formed worker re-adopts the CURRENT published weights —
            # no new put, the reference is still live in the object plane.
            self.publisher.re_adopt(replacement)
        except Exception:
            pass  # surfaces at its next delivery if it is truly gone
        self._launch(replacement)

    def _publish_boundary(self) -> None:
        version, weights = self.learner.publish_boundary()
        # wait=False: adoption lands per-actor behind whatever fragment
        # is in flight (the version boundary IS the fragment boundary);
        # blocking the driver here would serialize publish behind the
        # slowest rollout.
        self.publisher.publish(weights, self.workers.remote_workers,
                               version=version, wait=False)

    def _drain_learner(self) -> int:
        cfg = self.config
        updates = 0
        while True:
            item = self.queue.get(self.learner.version, timeout=0.0)
            if item is None:
                return updates
            batch, bversion = item
            self._last_learner_metrics = self.learner.update(batch,
                                                             bversion)
            updates += 1
            if self.learner.num_updates % cfg.publish_interval == 0:
                self._publish_boundary()

    def _process_deliveries(self, block: bool) -> tuple:
        """Harvest completed sample refs: queue the batches (or hold the
        worker under backpressure) and replace workers whose refs
        surface a death.  block=False sweeps everything already done
        without waiting — the end-of-step pass that keeps dead-worker
        detection latency at one iteration even when the learner's
        update quota was met early."""
        if not self._inflight:
            return 0, 0
        refs = list(self._inflight)
        ready, _ = ray_tpu.wait(
            refs, num_returns=1 if block else len(refs),
            timeout=10.0 if block else 0.0)
        fragments = 0
        episodes = 0
        for ref in ready:
            worker = self._inflight.pop(ref)
            try:
                batch, bversion, metrics = ray_tpu.get(ref)
            except Exception:
                self._replace(worker)
                continue
            episodes += self._record_metrics([metrics])
            fragments += 1
            accepted = self.queue.put(batch, bversion,
                                      self.learner.version)
            if accepted or bversion < self.learner.version:
                # Delivered (or too stale to queue — either way the
                # worker should go sample under fresher weights).
                self._launch(worker)
            else:
                self._idle.append(worker)   # backpressure
        return fragments, episodes

    # -- training ----------------------------------------------------------
    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        updates_before = self.learner.num_updates
        fragments = 0
        episodes = 0
        self._last_learner_metrics = getattr(self, "_last_learner_metrics",
                                             {})
        while (self.learner.num_updates - updates_before
               < cfg.min_updates_per_step):
            self._drain_learner()
            self._launch_all_idle()
            if (self.learner.num_updates - updates_before
                    >= cfg.min_updates_per_step):
                break
            if not self._inflight:
                continue   # everything backpressured: drain again
            f, e = self._process_deliveries(block=True)
            fragments += f
            episodes += e
        f, e = self._process_deliveries(block=False)
        fragments += f
        episodes += e
        self._launch_all_idle()
        self.workers.local_worker.set_weights(self.learner.get_weights())
        return {"fragments_this_iter": fragments,
                "episodes_this_iter": episodes,
                "learner_updates_total": self.learner.num_updates,
                "policy_version": self.learner.version,
                "queue": self.queue.stats(),
                **{f"learner/{k}": v
                   for k, v in self._last_learner_metrics.items()}}

    # -- fault tolerance ---------------------------------------------------
    def recover_learner(self):
        """The killed-learner path: throw away the in-memory learner,
        rebuild from the newest COMMITTED checkpoint (fresh optimizer +
        step 0 when none exists), re-screen the surviving queue against
        the restored version, and republish so the gang converges onto
        the restored weights.  Returns the restored update count (None
        for a from-scratch rebuild)."""
        self.learner = self._make_learner()
        restored = self.learner.restore_latest()
        self.queue.evict_stale(self.learner.version)
        self.publisher.publish(self.learner.get_weights(),
                               self.workers.remote_workers,
                               version=self.learner.version, wait=False)
        return restored

    # -- persistence -------------------------------------------------------
    def save_to_dict(self) -> Dict[str, Any]:
        return {"learner_state": self.learner.state_tree(),
                "config": self.config.to_dict()}

    def restore_from_dict(self, state: Dict[str, Any]) -> None:
        import numpy as np
        tree = state["learner_state"]
        self.learner._core.set_state({"params": tree["params"],
                                      "opt_state": tree["opt_state"]})
        self.learner.version = int(np.asarray(tree["version"]))
        self.learner.num_updates = int(np.asarray(tree["num_updates"]))
        self.publisher.publish(self.learner.get_weights(),
                               self.workers.remote_workers,
                               version=self.learner.version)
