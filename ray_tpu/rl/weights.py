"""In-place weight publication: put once, adopt by reference.

The learner's weights cross the process boundary exactly once per
version boundary — one `ray_tpu.put` into the object plane — and every
rollout actor receives the REFERENCE (`actor.adopt.remote(version,
ref)`), pulling the payload zero-copy from the object store instead of
having the driver pickle the tree into each actor call.  The publisher
remembers the current (version, ref) pair so a re-formed rollout worker
can re-adopt the live weights without a fresh put (`re_adopt`).

Spans: the driver-side put + fan-out is one `rl/publish` span; each
actor records its own `rl/adopt` span around the in-place engine swap,
so `scale_attrib.py rl` can separate publish wall from rollout wall.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import ray_tpu
from ray_tpu.util import spans
from ray_tpu.util.metrics import Counter, Histogram

_MET = None


def _metrics() -> dict:
    global _MET
    if _MET is None:
        _MET = {
            "publishes": Counter(
                "rl_weight_publishes",
                "Weight versions published through the object plane"),
            "adoptions": Counter(
                "rl_weight_adoptions",
                "Per-actor adoptions of a published weight reference"),
            "publish_s": Histogram(
                "rl_weight_publish_s",
                "Wall seconds per publish (one put + gang-wide adopt)",
                buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                         1.0, 2.5, 5.0)),
        }
    return _MET


class WeightPublisher:
    """Driver-side fan-out of learner weights to a rollout gang."""

    def __init__(self):
        self.version = 0
        self._ref: Any = None

    def publish(self, weights: Any, actors: Sequence[Any], *,
                version: Optional[int] = None,
                wait: bool = True) -> Tuple[int, List[Any]]:
        """Put `weights` once and fan the reference to `actors`.

        Returns (version, failed_actors): adoption failures (dead
        actors) are collected, not raised, so the controller can replace
        the worker and `re_adopt` the replacement.  With wait=False the
        adopt calls are left in flight (the engine swap is between-steps
        safe, so nothing downstream needs the barrier)."""
        import time
        t0 = time.monotonic()
        self.version = (int(version) if version is not None
                        else self.version + 1)
        failed: List[Any] = []
        with spans.span("rl", "publish", version=self.version,
                        actors=len(actors)):
            self._ref = ray_tpu.put(weights)
            refs = [(a, a.adopt.remote(self.version, self._ref))
                    for a in actors]
            if wait:
                for a, ref in refs:
                    try:
                        ray_tpu.get(ref)
                        _metrics()["adoptions"].inc()
                    except Exception:
                        failed.append(a)
        met = _metrics()
        met["publishes"].inc()
        met["publish_s"].observe(time.monotonic() - t0)
        return self.version, failed

    def re_adopt(self, actor: Any) -> int:
        """Hand the CURRENT (version, ref) to one actor — the re-formed
        rollout worker path.  No new put: the payload is already in the
        object plane."""
        if self._ref is None:
            raise RuntimeError("nothing published yet")
        ray_tpu.get(actor.adopt.remote(self.version, self._ref))
        _metrics()["adoptions"].inc()
        return self.version

    @property
    def current_ref(self) -> Any:
        return self._ref
