"""Stale-tolerant V-trace learner with COMMITTED checkpoints.

Wraps the jitted `rllib` V-trace SGD core (`impala._VTraceLearner`, the
`rllib/vtrace.py` importance correction) with the three things the
async actor/learner loop needs on top of plain IMPALA:

- an explicit POLICY VERSION that advances only at publish boundaries
  (`publish_boundary()` — the controller puts the returned weights
  through the `WeightPublisher`), so trajectory staleness is a
  well-defined `learner.version - behavior_version`;
- per-update staleness accounting (histogram + the `rl/learn` span
  carries the staleness it trained on);
- durable state through `CheckpointManager`: periodic COMMITTED
  checkpoints of (params, opt_state, version, num_updates), and
  `restore_latest()` for the killed-learner chaos path — torn saves are
  invisible by construction, so a resume never reads a half-written
  tree.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from ray_tpu.rllib.impala import IMPALAConfig, _VTraceLearner
from ray_tpu.util import events, spans
from ray_tpu.util.metrics import Counter, Histogram

_MET = None


def _metrics() -> dict:
    global _MET
    if _MET is None:
        _MET = {
            "updates": Counter(
                "rl_learner_updates", "V-trace SGD updates applied"),
            "staleness": Histogram(
                "rl_update_staleness",
                "Policy-version staleness of each trained batch",
                buckets=(0.0, 1.0, 2.0, 3.0, 4.0, 8.0)),
        }
    return _MET


class StaleTolerantLearner:
    def __init__(self, obs_dim: int, num_actions: int, *,
                 hidden=(64, 64), gamma: float = 0.99, lr: float = 6e-4,
                 grad_clip: float = 40.0, vf_loss_coeff: float = 0.5,
                 entropy_coeff: float = 0.01,
                 clip_rho_threshold: float = 1.0,
                 clip_c_threshold: float = 1.0, seed: int = 0,
                 ckpt_dir: Optional[str] = None, ckpt_interval: int = 20,
                 keep_last_k: int = 3):
        cfg = IMPALAConfig()
        cfg.gamma = gamma
        cfg.lr = lr
        cfg.grad_clip = grad_clip
        cfg.vf_loss_coeff = vf_loss_coeff
        cfg.entropy_coeff = entropy_coeff
        cfg.clip_rho_threshold = clip_rho_threshold
        cfg.clip_c_threshold = clip_c_threshold
        self._core = _VTraceLearner(obs_dim, num_actions, cfg, hidden, seed)
        self.version = 1          # the initial weights ARE version 1
        self.num_updates = 0
        self.ckpt_interval = int(ckpt_interval)
        self._ckpt = None
        if ckpt_dir is not None:
            from ray_tpu.checkpoint.manager import CheckpointManager
            self._ckpt = CheckpointManager(ckpt_dir, keep_last_k=keep_last_k)

    # -- training ----------------------------------------------------------
    def update(self, batch, behavior_version: int) -> Dict[str, float]:
        """One V-trace SGD step on a batch collected under
        `behavior_version`.  The importance correction in the loss is
        what licenses staleness > 0; bounding it is the queue's job."""
        staleness = self.version - int(behavior_version)
        met = _metrics()
        met["staleness"].observe(float(max(0, staleness)))
        train = {k: v for k, v in batch.items()
                 if k not in ("policy_version", "valid")}
        with spans.span("rl", "learn", version=self.version,
                        staleness=staleness):
            metrics = self._core.update(train)
        self.num_updates += 1
        met["updates"].inc()
        if (self._ckpt is not None and self.ckpt_interval > 0
                and self.num_updates % self.ckpt_interval == 0):
            self.checkpoint()
        metrics["staleness"] = float(staleness)
        return metrics

    def publish_boundary(self) -> Tuple[int, Any]:
        """Advance the policy version and hand out the weights to
        publish under it."""
        self.version += 1
        return self.version, self._core.get_weights()

    def get_weights(self):
        return self._core.get_weights()

    # -- durability --------------------------------------------------------
    def state_tree(self) -> Dict[str, Any]:
        state = self._core.get_state()
        return {"params": state["params"], "opt_state": state["opt_state"],
                "version": np.asarray(self.version, np.int64),
                "num_updates": np.asarray(self.num_updates, np.int64)}

    def checkpoint(self, *, sync: bool = True) -> None:
        """COMMITTED save at the current update count (sync by default:
        the chaos gate's contract is that a checkpoint the learner
        reported is one it can resume from)."""
        if self._ckpt is None:
            raise RuntimeError("learner built without ckpt_dir")
        self._ckpt.save(self.num_updates, self.state_tree(), sync=sync)

    def restore_latest(self) -> Optional[int]:
        """Resume from the newest COMMITTED checkpoint; None when there
        is none.  Returns the restored update count."""
        if self._ckpt is None or self._ckpt.latest_step() is None:
            return None
        tree = self._ckpt.restore()
        self._core.set_state({"params": tree["params"],
                              "opt_state": tree["opt_state"]})
        self.version = int(np.asarray(tree["version"]))
        self.num_updates = int(np.asarray(tree["num_updates"]))
        events.record("rl", "learner_resume", version=self.version,
                      num_updates=self.num_updates)
        return self.num_updates
