"""Bounded, staleness-aware trajectory queue between rollouts and learner.

The queue is driver-local (it lives in the controller, NOT in the
learner), which is what lets a killed learner resume from a checkpoint
without poisoning it: entries are (batch, behavior_version) pairs, and
staleness is always evaluated against the CURRENT learner version at
admission and again at consumption — a batch that was fresh when queued
but went stale while the learner was down is evicted, never trained on.

Two protections, both observable on the `rl` plane:

- staleness bound: a batch whose behavior version trails the learner by
  more than `staleness_bound` versions is rejected (`rl/stale_drop`) —
  V-trace corrects off-policyness, but only usefully within a bound.
- capacity: when the queue is full the producer is backpressured
  (`rl/backpressure`) instead of growing an unbounded staleness ramp.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Optional, Tuple

from ray_tpu.util import events
from ray_tpu.util.metrics import Counter

_MET = None


def _metrics() -> dict:
    global _MET
    if _MET is None:
        _MET = {
            "accepted": Counter(
                "rl_trajectories_accepted",
                "Trajectory batches admitted to the learner queue"),
            "stale_dropped": Counter(
                "rl_trajectories_stale_dropped",
                "Trajectory batches dropped for exceeding the staleness "
                "bound (at admission or consumption)"),
            "backpressured": Counter(
                "rl_trajectory_backpressure",
                "Producer offers rejected because the queue was full"),
        }
    return _MET


class TrajectoryQueue:
    """Thread-safe bounded FIFO of (batch, behavior_version) entries."""

    def __init__(self, capacity: int = 8, staleness_bound: int = 1):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if staleness_bound < 0:
            raise ValueError(
                f"staleness_bound must be >= 0, got {staleness_bound}")
        self.capacity = int(capacity)
        self.staleness_bound = int(staleness_bound)
        self._dq: "collections.deque[Tuple[Any, int]]" = collections.deque()
        self._cv = threading.Condition()
        self.accepted = 0
        self.stale_dropped = 0
        self.backpressured = 0

    def __len__(self) -> int:
        with self._cv:
            return len(self._dq)

    @property
    def full(self) -> bool:
        with self._cv:
            return len(self._dq) >= self.capacity

    def put(self, batch: Any, version: int, learner_version: int,
            timeout: float = 0.0) -> bool:
        """Offer one batch produced by policy `version`.  Returns False
        (and records why) when the batch is already staler than the
        bound or the queue stays full past `timeout` — the caller
        should treat False-with-full as backpressure and hold the
        producer instead of re-offering in a spin."""
        staleness = int(learner_version) - int(version)
        if staleness > self.staleness_bound:
            self.stale_dropped += 1
            _metrics()["stale_dropped"].inc()
            events.record("rl", "stale_drop", version=int(version),
                          learner_version=int(learner_version),
                          staleness=staleness, where="put")
            return False
        with self._cv:
            if not self._cv.wait_for(
                    lambda: len(self._dq) < self.capacity,
                    timeout=timeout):
                self.backpressured += 1
                _metrics()["backpressured"].inc()
                events.record("rl", "backpressure", depth=len(self._dq),
                              capacity=self.capacity)
                return False
            self._dq.append((batch, int(version)))
            self.accepted += 1
            _metrics()["accepted"].inc()
            self._cv.notify_all()
            return True

    def get(self, learner_version: int,
            timeout: float = 0.0) -> Optional[Tuple[Any, int]]:
        """Pop the oldest batch still within the staleness bound for the
        CURRENT learner version; entries that went stale while queued
        are evicted in passing.  None when nothing consumable arrives
        within `timeout`."""
        import time as _time
        deadline = _time.monotonic() + max(0.0, timeout)
        with self._cv:
            while True:
                while self._dq:
                    batch, version = self._dq.popleft()
                    staleness = int(learner_version) - version
                    if staleness <= self.staleness_bound:
                        self._cv.notify_all()
                        return batch, version
                    self.stale_dropped += 1
                    _metrics()["stale_dropped"].inc()
                    events.record("rl", "stale_drop", version=version,
                                  learner_version=int(learner_version),
                                  staleness=staleness, where="get")
                remaining = deadline - _time.monotonic()
                if remaining <= 0 or not self._cv.wait_for(
                        lambda: bool(self._dq), timeout=remaining):
                    return None

    def evict_stale(self, learner_version: int) -> int:
        """Drop every queued entry beyond the staleness bound (the
        learner-resume path calls this so a restored learner never
        consumes trajectories from before its checkpoint horizon)."""
        dropped = 0
        with self._cv:
            keep = collections.deque()
            for batch, version in self._dq:
                if int(learner_version) - version <= self.staleness_bound:
                    keep.append((batch, version))
                else:
                    dropped += 1
                    self.stale_dropped += 1
                    _metrics()["stale_dropped"].inc()
                    events.record(
                        "rl", "stale_drop", version=version,
                        learner_version=int(learner_version),
                        staleness=int(learner_version) - version,
                        where="evict")
            self._dq = keep
            if dropped:
                self._cv.notify_all()
        return dropped

    def stats(self) -> dict:
        with self._cv:
            return {"depth": len(self._dq), "capacity": self.capacity,
                    "staleness_bound": self.staleness_bound,
                    "accepted": self.accepted,
                    "stale_dropped": self.stale_dropped,
                    "backpressured": self.backpressured}
