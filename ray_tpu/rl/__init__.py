"""Podracer-style split actor/learner RL substrate.

Three composable parts (Podracer / RLAX lineage, see PAPERS.md):

- Rollout gangs (`rollout.py`): actors that generate versioned,
  SampleBatch-compatible trajectories — either through the serving
  `InferenceEngine` (continuous batching + prefix cache + speculative
  decoding as a rollout-throughput multiplier) or through the classic
  vectorized-env `RolloutWorker`.
- In-place weight publication (`weights.py`): the learner's weights go
  through the object plane ONCE per version boundary and every rollout
  actor adopts the reference; engine actors swap weights between
  scheduler steps without dropping in-flight lanes.
- A stale-tolerant V-trace learner (`learner.py`) consuming stale-by-≤k
  trajectories from a bounded `TrajectoryQueue` (`trajectory.py`), with
  COMMITTED checkpoints through `CheckpointManager`.

`controller.py` wires them into the async actor/learner loop
(`PodracerConfig().build()` — same driver surface as `rllib`
algorithms).  Everything records on the `rl` event plane so
`scale_attrib.py rl` can attribute rollout vs publish vs learn wall.
"""

from ray_tpu.rl.controller import Podracer, PodracerConfig
from ray_tpu.rl.learner import StaleTolerantLearner
from ray_tpu.rl.rollout import EngineRolloutActor, EnvRolloutActor
from ray_tpu.rl.trajectory import TrajectoryQueue
from ray_tpu.rl.weights import WeightPublisher

__all__ = [
    "EngineRolloutActor",
    "EnvRolloutActor",
    "Podracer",
    "PodracerConfig",
    "StaleTolerantLearner",
    "TrajectoryQueue",
    "WeightPublisher",
]
