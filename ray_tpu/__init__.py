"""ray_tpu — a TPU-native distributed AI runtime.

A from-scratch framework with the capability surface of Ray (tasks, actors,
objects, placement groups, Train/Tune/Data/Serve/RL libraries), architected
for TPUs: JAX/XLA is the compute plane (pjit/GSPMD sharding over ICI meshes,
Pallas kernels), a shared-memory object store + per-host daemons + a global
control service form the host-side runtime.
"""

__version__ = "0.1.0"

from ray_tpu.api import (  # noqa: F401
    ActorClass,
    ActorHandle,
    available_resources,
    cancel,
    cluster_resources,
    get,
    get_actor,
    init,
    is_initialized,
    kill,
    method,
    nodes,
    put,
    remote,
    shutdown,
    wait,
)
from ray_tpu.object_ref import ObjectRef  # noqa: F401
from ray_tpu import exceptions  # noqa: F401
