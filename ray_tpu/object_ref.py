"""ObjectRef: a distributed future naming an object and its owner.

Reference parity: python/ray/_raylet.pyx ObjectRef + ownership model from
src/ray/core_worker/reference_count.h — every ref carries the owner's RPC
address so any holder can (a) resolve the value, (b) report borrows back to
the owner, which runs the distributed refcount.
"""

from __future__ import annotations

from typing import Any

from ray_tpu._private.ids import ObjectID

# Set by the core worker when a process connects; used for GC callbacks.
_ref_hooks = None


def _install_hooks(hooks):
    global _ref_hooks
    _ref_hooks = hooks


class ObjectRef:
    __slots__ = ("id", "owner_address", "_skip_gc", "__weakref__")

    def __init__(self, object_id: ObjectID, owner_address: str = "",
                 _register: bool = True):
        self.id = object_id
        self.owner_address = owner_address
        self._skip_gc = not _register
        if _register and _ref_hooks is not None:
            _ref_hooks.on_ref_created(self)

    def binary(self) -> bytes:
        return self.id.binary()

    def hex(self) -> str:
        return self.id.hex()

    def task_id(self):
        return self.id.task_id()

    def future(self):
        """Return a concurrent.futures.Future resolving to the value."""
        if _ref_hooks is None:
            raise RuntimeError("ray_tpu not initialized")
        return _ref_hooks.as_future(self)

    def __await__(self):
        """Allow `await ref` inside async actors."""
        if _ref_hooks is None:
            raise RuntimeError("ray_tpu not initialized")
        return _ref_hooks.await_ref(self).__await__()

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ObjectRef({self.id.hex()})"

    def __reduce__(self):
        # Serialization of a ref is a *borrow*: the serializer's thread-local
        # collector records it so the owner learns about the new holder.
        from ray_tpu._private import serialization as _ser
        collector = _ser.current_ref_collector()
        if collector is not None:
            collector.append(self)
        return (_deserialize_ref, (self.id.binary(), self.owner_address))

    def __del__(self):
        if not self._skip_gc and _ref_hooks is not None:
            try:
                _ref_hooks.on_ref_deleted(self)
            except Exception:
                pass


def _deserialize_ref(id_binary: bytes, owner_address: str) -> "ObjectRef":
    ref = ObjectRef(ObjectID(id_binary), owner_address, _register=False)
    if _ref_hooks is not None:
        _ref_hooks.on_ref_deserialized(ref)
        ref._skip_gc = False
    return ref


Any  # silence linters about unused import in docs builds
