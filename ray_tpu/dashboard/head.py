"""Dashboard head: REST API + web UI over the state API and job manager.

Reference parity: dashboard/head.py + http_server_head.py (aiohttp REST
routes over the state aggregator) and dashboard/modules/job/job_head.py
(the /api/jobs/ REST surface the job SDK/CLI talks to).  The reference
ships a React client; here a single embedded page polls the same JSON
endpoints — the API surface, not the pixels, is the parity target.

Run: python -m ray_tpu.dashboard.head --address GCS_ADDR --port 8265
"""

from __future__ import annotations

import asyncio
import functools
import json
import logging
from typing import Optional

logger = logging.getLogger("ray_tpu.dashboard")

DEFAULT_PORT = 8265


def _json(data, status: int = 200):
    from aiohttp import web
    return web.Response(text=json.dumps(data, default=str),
                        content_type="application/json", status=status)


class DashboardHead:
    """Serves /api/* (cluster state + jobs) and the UI page.

    Blocking state-API calls run in a thread executor so the aiohttp loop
    stays responsive (same split as the client server's handler pool).
    """

    def __init__(self, host: str = "127.0.0.1", port: int = DEFAULT_PORT):
        from concurrent.futures import ThreadPoolExecutor
        from ray_tpu.dashboard.job_manager import JobManager
        self.host = host
        self.port = port
        self.bound_port: Optional[int] = None
        self._pool = ThreadPoolExecutor(max_workers=16,
                                        thread_name_prefix="dash")
        self._jobs = JobManager()
        self._runner = None

    async def _call(self, fn, *args, **kwargs):
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            self._pool, functools.partial(fn, *args, **kwargs))

    # ---- state endpoints ----

    async def _h_state(self, fn, request):
        try:
            return _json({"result": await self._call(fn)})
        except Exception as e:
            return _json({"error": repr(e)}, status=500)

    async def _h_overview(self, request):
        from ray_tpu import state
        try:
            summary = await self._call(state.summarize_cluster)
            jobs = await self._call(self._jobs.list_jobs)
            return _json({"result": {"cluster": summary, "jobs": jobs}})
        except Exception as e:
            return _json({"error": repr(e)}, status=500)

    # ---- job endpoints (reference: job_head.py REST surface) ----

    async def _h_jobs_list(self, request):
        try:
            return _json({"result": await self._call(self._jobs.list_jobs)})
        except Exception as e:
            return _json({"error": repr(e)}, status=500)

    async def _h_jobs_submit(self, request):
        try:
            body = await request.json()
            entrypoint = body["entrypoint"]
        except Exception as e:
            return _json({"error": f"bad request: {e!r}"}, status=400)
        try:
            sub_id = await self._call(
                self._jobs.submit_job, entrypoint,
                runtime_env=body.get("runtime_env"),
                metadata=body.get("metadata"),
                submission_id=body.get("submission_id"))
            return _json({"result": {"submission_id": sub_id}})
        except ValueError as e:
            return _json({"error": str(e)}, status=400)
        except Exception as e:
            return _json({"error": repr(e)}, status=500)

    async def _h_job_status(self, request):
        sub_id = request.match_info["sub_id"]
        try:
            rec = await self._call(self._jobs.get_job_status, sub_id)
        except Exception as e:
            return _json({"error": repr(e)}, status=500)
        if rec is None:
            return _json({"error": f"no job {sub_id}"}, status=404)
        return _json({"result": rec})

    async def _h_job_logs(self, request):
        from aiohttp import web
        sub_id = request.match_info["sub_id"]
        try:
            text = await self._call(self._jobs.get_job_logs, sub_id)
        except KeyError:
            return _json({"error": f"no job {sub_id}"}, status=404)
        return web.Response(text=text, content_type="text/plain")

    async def _h_job_stop(self, request):
        sub_id = request.match_info["sub_id"]
        try:
            stopped = await self._call(self._jobs.stop_job, sub_id)
            return _json({"result": {"stopped": stopped}})
        except KeyError:
            return _json({"error": f"no job {sub_id}"}, status=404)

    async def _h_job_delete(self, request):
        sub_id = request.match_info["sub_id"]
        try:
            deleted = await self._call(self._jobs.delete_job, sub_id)
            return _json({"result": {"deleted": deleted}})
        except RuntimeError as e:
            return _json({"error": str(e)}, status=400)
        except Exception as e:
            return _json({"error": repr(e)}, status=500)

    async def _h_index(self, request):
        from aiohttp import web
        return web.Response(text=_INDEX_HTML, content_type="text/html")

    async def _h_metrics(self, request):
        from aiohttp import web
        from ray_tpu import state
        try:
            text = await self._call(state.prometheus_metrics)
        except Exception as e:
            return _json({"error": repr(e)}, status=500)
        return web.Response(text=text, content_type="text/plain")

    # ---- lifecycle ----

    async def start(self) -> int:
        from aiohttp import web
        from ray_tpu import state
        app = web.Application()
        st = [
            ("nodes", state.list_nodes), ("actors", state.list_actors),
            ("placement_groups", state.list_placement_groups),
            ("workers", state.list_workers), ("objects", state.list_objects),
            ("tasks", state.list_tasks), ("timeline", state.timeline),
            ("cluster_metrics", state.cluster_metrics),
        ]
        for name, fn in st:
            app.router.add_get(f"/api/{name}",
                               functools.partial(self._h_state, fn))
        app.router.add_get("/api/overview", self._h_overview)
        app.router.add_get("/api/jobs", self._h_jobs_list)
        app.router.add_post("/api/jobs", self._h_jobs_submit)
        app.router.add_get("/api/jobs/{sub_id}", self._h_job_status)
        app.router.add_get("/api/jobs/{sub_id}/logs", self._h_job_logs)
        app.router.add_post("/api/jobs/{sub_id}/stop", self._h_job_stop)
        app.router.add_delete("/api/jobs/{sub_id}", self._h_job_delete)
        app.router.add_get("/metrics", self._h_metrics)
        app.router.add_get("/", self._h_index)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        self.bound_port = self._runner.addresses[0][1]
        return self.bound_port

    async def stop(self):
        if self._runner is not None:
            await self._runner.cleanup()


_INDEX_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>ray_tpu dashboard</title>
<style>
 body{font-family:system-ui,sans-serif;margin:0;background:#f6f7f9;color:#1a1a2e}
 header{background:#1a1a2e;color:#fff;padding:10px 20px;display:flex;gap:16px;align-items:baseline}
 header h1{font-size:16px;margin:0}
 header span{font-size:12px;opacity:.7}
 main{padding:16px 20px;max-width:1200px}
 .cards{display:flex;gap:12px;flex-wrap:wrap;margin-bottom:16px}
 .card{background:#fff;border:1px solid #e3e5ea;border-radius:8px;padding:10px 16px;min-width:110px}
 .card b{display:block;font-size:22px}
 .card small{color:#667}
 h2{font-size:14px;margin:18px 0 6px}
 table{border-collapse:collapse;width:100%;background:#fff;border:1px solid #e3e5ea;border-radius:8px;font-size:12px}
 th,td{text-align:left;padding:5px 10px;border-bottom:1px solid #eef0f3;font-variant-numeric:tabular-nums}
 th{background:#fafbfc;color:#556}
 .ok{color:#0a7d33}.bad{color:#c0392b}
</style></head><body>
<header><h1>ray_tpu dashboard</h1><span id="ts"></span></header>
<main>
 <div class="cards" id="cards"></div>
 <h2>Nodes</h2><table id="nodes"></table>
 <h2>Jobs</h2><table id="jobs"></table>
 <h2>Actors</h2><table id="actors"></table>
 <h2>Placement groups</h2><table id="pgs"></table>
</main>
<script>
async function j(u){const r=await fetch(u);const d=await r.json();
  if(d.error)throw new Error(d.error);return d.result}
function esc(v){return String(v).replace(/[&<>"']/g,
  c=>({'&':'&amp;','<':'&lt;','>':'&gt;','"':'&quot;',"'":'&#39;'}[c]))}
// values are user-controlled (entrypoints, actor names) — escape them;
// cells that need markup (status dots) pass {html:...} explicitly.
function cell(v){return (v&&v.html!==undefined)?v.html:esc(v)}
function tab(el,cols,rows){el.innerHTML='<tr>'+cols.map(c=>'<th>'+esc(c)+'</th>').join('')
  +'</tr>'+rows.map(r=>'<tr>'+r.map(v=>'<td>'+cell(v)+'</td>').join('')+'</tr>').join('')}
function card(label,val){return '<div class="card"><b>'+esc(val)+'</b><small>'+esc(label)+'</small></div>'}
async function tick(){
 try{
  const [nodes,actors,pgs,jobs]=await Promise.all([
    j('/api/nodes'),j('/api/actors'),j('/api/placement_groups'),j('/api/jobs')]);
  document.getElementById('cards').innerHTML=
    card('nodes',nodes.filter(n=>n.alive).length+'/'+nodes.length)
    +card('actors',actors.filter(a=>a.state=='ALIVE').length)
    +card('placement groups',pgs.length)
    +card('jobs running',jobs.filter(x=>x.status=='RUNNING').length)
    +card('jobs total',jobs.length);
  tab(document.getElementById('nodes'),['node','address','alive','head','resources'],
    nodes.map(n=>[n.node_id.slice(0,12),n.address,
      n.alive?{html:'<span class=ok>yes</span>'}:{html:'<span class=bad>no</span>'},
      n.is_head?'yes':'',JSON.stringify(n.resources_available)]));
  tab(document.getElementById('jobs'),['id','status','entrypoint','message'],
    jobs.map(x=>[x.submission_id,x.status,(x.entrypoint||'').slice(0,80),x.message||'']));
  tab(document.getElementById('actors'),['actor','class','state','name','node'],
    actors.slice(0,200).map(a=>[a.actor_id.slice(0,12),a.class_name,a.state,
      a.name||'',(a.node_id||'').slice(0,12)]));
  tab(document.getElementById('pgs'),['pg','state','strategy','bundles'],
    pgs.map(p=>[p.placement_group_id.slice(0,12),p.state,p.strategy,
      JSON.stringify(p.bundles)]));
  document.getElementById('ts').textContent='updated '+new Date().toLocaleTimeString();
 }catch(e){document.getElementById('ts').textContent='error: '+e.message}
}
tick();setInterval(tick,2000);
</script></body></html>
"""


def main(argv=None) -> int:
    import argparse

    import ray_tpu

    parser = argparse.ArgumentParser(prog="ray_tpu-dashboard")
    parser.add_argument("--address", required=True, help="GCS address")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=DEFAULT_PORT)
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    ray_tpu.init(address=args.address, log_to_driver=False)
    head = DashboardHead(host=args.host, port=args.port)
    loop = asyncio.new_event_loop()
    port = loop.run_until_complete(head.start())
    print(f"dashboard listening on {args.host}:{port}", flush=True)
    try:
        loop.run_forever()
    except KeyboardInterrupt:
        pass
    finally:
        loop.run_until_complete(head.stop())
        ray_tpu.shutdown()
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
