"""Job submission: run shell entrypoints as supervised cluster jobs.

Reference parity: dashboard/modules/job/job_manager.py (JobManager:490
submit_job/stop_job/get_job_status, JobSupervisor:136 — a detached actor
that runs the entrypoint as a subprocess, streams its logs, and records a
terminal JobStatus) and common.py (JobStatus lifecycle PENDING -> RUNNING
-> SUCCEEDED/FAILED/STOPPED).

Differences from the reference, driven by the TPU runtime's shape:
- Job records and final logs live in GCS KV (ns "job_sub" / "job_logs")
  instead of head-node files, so any driver/REST head can read them even
  when the supervisor ran on another host.
- The supervisor self-exits after persisting terminal state; readers fall
  back from the actor call to the KV record when it is gone.
"""

from __future__ import annotations

import os
import time
import uuid
from typing import Any, Dict, List, Optional

_KV_NS = "job_sub"
_LOG_NS = "job_logs"
_LOG_CAP = 4 << 20          # keep the tail of very chatty jobs
_SUPERVISOR_PREFIX = "_job_supervisor:"


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    TERMINAL = (SUCCEEDED, FAILED, STOPPED)


def _kv_call(method: str, req: dict):
    from ray_tpu import api
    w = api._worker
    return w.io.run(w.gcs.call("Kv", method, req))


def _kv_put(ns: str, key: str, value: bytes) -> None:
    _kv_call("kv_put", {"ns": ns, "key": key, "value": value,
                        "overwrite": True})


def _kv_get(ns: str, key: str) -> Optional[bytes]:
    reply = _kv_call("kv_get", {"ns": ns, "key": key})
    return reply.get("value")


def _put_record(rec: Dict[str, Any]) -> None:
    import cloudpickle
    _kv_put(_KV_NS, rec["submission_id"], cloudpickle.dumps(rec))


def _get_record(submission_id: str) -> Optional[Dict[str, Any]]:
    import pickle
    blob = _kv_get(_KV_NS, submission_id)
    return pickle.loads(blob) if blob is not None else None


class JobSupervisor:
    """Detached actor hosting one job's entrypoint subprocess.

    Runs with the job's runtime_env (so working_dir/env_vars apply to the
    subprocess through plain inheritance), mirrors the reference's
    JobSupervisor.run (job_manager.py:214): spawn with a process group,
    drain output, write terminal status.
    """

    def __init__(self, submission_id: str, entrypoint: str,
                 metadata: Dict[str, str], gcs_address: str):
        self.submission_id = submission_id
        self.entrypoint = entrypoint
        self.metadata = metadata
        self.gcs_address = gcs_address
        self.proc = None
        self.lines: List[bytes] = []
        self.nbytes = 0
        self.stop_requested = False
        self.done = False

    def start(self) -> str:
        import subprocess
        import threading

        # A stop_job issued while we were still PENDING persisted STOPPED;
        # honor it instead of launching the entrypoint.
        rec = _get_record(self.submission_id)
        if rec is not None and rec["status"] == JobStatus.STOPPED:
            self._finish_without_run()
            return JobStatus.STOPPED

        env = dict(os.environ)
        # The entrypoint's ray_tpu.init() joins this cluster (reference
        # sets RAY_ADDRESS for the job driver the same way).
        env["RAY_TPU_ADDRESS"] = self.gcs_address
        env["RAY_TPU_JOB_SUBMISSION_ID"] = self.submission_id
        self.proc = subprocess.Popen(
            self.entrypoint, shell=True, cwd=os.getcwd(), env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            start_new_session=True)
        rec = _get_record(self.submission_id)
        if rec is not None and rec["status"] == JobStatus.STOPPED:
            # stop raced the spawn: tear the process group down again.
            self.stop()
            try:
                self.proc.wait(timeout=10)
            except Exception:
                pass
            self._finish_without_run()
            return JobStatus.STOPPED
        rec["status"] = JobStatus.RUNNING
        rec["start_time"] = time.time()
        _put_record(rec)
        # Close the lost-update window: stop_job's PENDING path may persist
        # STOPPED between our re-read above and the RUNNING write, which the
        # write just clobbered.  stop_job also sets an append-only stop-intent
        # key that nothing overwrites; honor it after the RUNNING write.
        if _kv_get(_KV_NS, self.submission_id + ".stop_intent") is not None:
            self.stop()
            try:
                self.proc.wait(timeout=10)
            except Exception:
                pass
            rec["status"] = JobStatus.STOPPED
            rec["message"] = "stopped before start"
            rec["end_time"] = time.time()
            _put_record(rec)
            self._finish_without_run()
            return JobStatus.STOPPED
        threading.Thread(target=self._drain, daemon=True,
                         name="job-drain").start()
        return JobStatus.RUNNING

    def _finish_without_run(self) -> None:
        """Terminal without ever running the entrypoint (stopped before
        start): mark done and self-clean the detached actor — the usual
        self-exit lives at the end of _drain, which never runs here."""
        import threading
        self.done = True
        threading.Timer(1.0, os._exit, args=(0,)).start()

    def _drain(self) -> None:
        for line in self.proc.stdout:
            self.lines.append(line)
            self.nbytes += len(line)
            while self.nbytes > _LOG_CAP and len(self.lines) > 1:
                self.nbytes -= len(self.lines.pop(0))
        rc = self.proc.wait()
        if self.stop_requested:
            status, message = JobStatus.STOPPED, "stopped by user"
        elif rc == 0:
            status, message = JobStatus.SUCCEEDED, None
        else:
            status, message = JobStatus.FAILED, f"exit code {rc}"
        # Logs must be durable BEFORE the terminal status: a client that
        # sees SUCCEEDED immediately reads the KV log blob.
        persisted = False
        for _ in range(5):
            try:
                _kv_put(_LOG_NS, self.submission_id, b"".join(self.lines))
                rec = _get_record(self.submission_id)
                rec["status"] = status
                rec["message"] = message
                rec["end_time"] = time.time()
                _put_record(rec)
                persisted = True
                break
            except Exception:
                time.sleep(1.0)
        self.done = True
        if persisted:
            # Self-clean the detached actor once state is durable; readers
            # fall back to KV (reference: JobSupervisor ray.actor.exit_actor).
            # If persistence failed (GCS unreachable) stay alive so status/
            # logs remain servable via actor calls and stop_job still works.
            import threading
            threading.Timer(1.0, os._exit, args=(0,)).start()

    def logs(self) -> bytes:
        return b"".join(self.lines)

    def running(self) -> bool:
        return not self.done

    def stop(self) -> bool:
        import signal
        if self.proc is not None and self.proc.poll() is None:
            # Flag only when actually interrupting a live process — a stop
            # racing normal exit must not relabel a finished job STOPPED.
            self.stop_requested = True
            # Kill the whole process group: entrypoints are shell commands.
            try:
                os.killpg(os.getpgid(self.proc.pid), signal.SIGTERM)
            except ProcessLookupError:
                pass
            import threading

            def escalate():
                if self.proc.poll() is None:
                    try:
                        os.killpg(os.getpgid(self.proc.pid), signal.SIGKILL)
                    except ProcessLookupError:
                        pass
            threading.Timer(3.0, escalate).start()
            return True
        return False


class JobManager:
    """Driver-side job orchestration over GCS KV + supervisor actors."""

    def __init__(self):
        from ray_tpu import api
        if api._worker is None:
            raise RuntimeError("ray_tpu.init() first")
        self._gcs_address = api._worker.gcs_address
        # submission_id -> monotonic time of the last supervisor liveness
        # probe; polling endpoints (the UI hits list_jobs every 2s) must
        # not ping every running job's actor on every call.
        self._probe_at: Dict[str, float] = {}
        self._probe_interval_s = 5.0

    # -- submission --

    def submit_job(self, entrypoint: str, *,
                   runtime_env: Optional[Dict[str, Any]] = None,
                   metadata: Optional[Dict[str, str]] = None,
                   submission_id: Optional[str] = None) -> str:
        import cloudpickle

        import ray_tpu
        submission_id = submission_id or f"raysubmit_{uuid.uuid4().hex[:16]}"
        rec = {
            "submission_id": submission_id,
            "entrypoint": entrypoint,
            "status": JobStatus.PENDING,
            "message": None,
            "metadata": metadata or {},
            "runtime_env": {k: v for k, v in (runtime_env or {}).items()
                            if k == "env_vars"},
            "submit_time": time.time(),
            "start_time": None,
            "end_time": None,
        }
        # Atomic claim of the submission id: kv_put(overwrite=False)
        # reports whether the key already existed.
        existed = _kv_call("kv_put", {
            "ns": _KV_NS, "key": submission_id,
            "value": cloudpickle.dumps(rec), "overwrite": False})["existed"]
        if existed:
            raise ValueError(f"job {submission_id!r} already exists")
        opts = dict(name=_SUPERVISOR_PREFIX + submission_id,
                    lifetime="detached", num_cpus=0, max_restarts=0)
        if runtime_env:
            opts["runtime_env"] = runtime_env
        try:
            sup = ray_tpu.remote(JobSupervisor).options(**opts).remote(
                submission_id, entrypoint, metadata or {}, self._gcs_address)
            ray_tpu.get(sup.start.remote(), timeout=120)
        except Exception as e:
            # The supervisor may exist despite the failed start() (e.g. a
            # timeout after actor creation) — stop any already-spawned
            # entrypoint process group, then kill the actor so the terminal
            # FAILED record can't be overwritten by a phantom run later.
            sup2 = self._supervisor(submission_id)
            if sup2 is not None:
                try:
                    ray_tpu.get(sup2.stop.remote(), timeout=15)
                except Exception:
                    pass
                try:
                    ray_tpu.kill(sup2)
                except Exception:
                    pass
            rec["status"] = JobStatus.FAILED
            rec["message"] = f"failed to start supervisor: {e!r}"
            rec["end_time"] = time.time()
            _put_record(rec)
            raise
        return submission_id

    # -- introspection --

    def _supervisor(self, submission_id: str):
        import ray_tpu
        try:
            return ray_tpu.get_actor(_SUPERVISOR_PREFIX + submission_id)
        except Exception:
            return None

    def get_job_status(self, submission_id: str) -> Optional[Dict[str, Any]]:
        rec = _get_record(submission_id)
        if rec is not None:
            rec = self._maybe_reconcile(rec)
        return rec

    def _maybe_reconcile(self, rec: Dict[str, Any]) -> Dict[str, Any]:
        # PENDING gets a grace window: during submit_job the record exists
        # before the supervisor actor is nameable.
        if (rec["status"] == JobStatus.RUNNING
                or (rec["status"] == JobStatus.PENDING
                    and time.time() - (rec.get("submit_time") or 0) > 300)):
            now = time.monotonic()
            last = self._probe_at.get(rec["submission_id"], 0.0)
            if now - last < self._probe_interval_s:
                return rec
            self._probe_at[rec["submission_id"]] = now
            return self._reconcile(rec)
        return rec

    def _reconcile(self, rec: Dict[str, Any]) -> Dict[str, Any]:
        """A non-terminal record whose supervisor is gone (node died, GCS
        write raced the self-exit) would otherwise stay RUNNING forever —
        mark it FAILED (reference: JobManager._recover_running_jobs)."""
        import ray_tpu
        from ray_tpu.exceptions import ActorError
        sup = self._supervisor(rec["submission_id"])
        alive = False
        if sup is not None:
            try:
                ray_tpu.get(sup.running.remote(), timeout=30)
                alive = True
            except ActorError:
                alive = False
            except Exception:
                alive = True   # transient RPC trouble: don't condemn the job
        if not alive:
            # Supervisor death normally follows a successful terminal
            # persist (the self-exit path) — re-read and only condemn a
            # record that is STILL non-terminal, else we'd overwrite a
            # fresh SUCCEEDED with FAILED.
            latest = _get_record(rec["submission_id"]) or rec
            if latest["status"] in JobStatus.TERMINAL:
                return latest
            rec = latest
            rec["status"] = JobStatus.FAILED
            rec["message"] = "job supervisor died"
            rec["end_time"] = time.time()
            try:
                _put_record(rec)
            except Exception:
                pass
        return rec

    def list_jobs(self) -> List[Dict[str, Any]]:
        import pickle
        reply = _kv_call("kv_keys", {"ns": _KV_NS, "prefix": ""})
        jobs = []
        for key in reply["keys"]:
            key = key.decode() if isinstance(key, bytes) else key
            if key.endswith(".stop_intent"):
                continue
            blob = _kv_get(_KV_NS, key)
            if blob is not None:
                jobs.append(self._maybe_reconcile(pickle.loads(blob)))
        jobs.sort(key=lambda r: r.get("submit_time") or 0)
        return jobs

    def get_job_logs(self, submission_id: str) -> str:
        rec = _get_record(submission_id)
        if rec is None:
            raise KeyError(submission_id)
        if rec["status"] in JobStatus.TERMINAL:
            blob = _kv_get(_LOG_NS, submission_id)
            return (blob or b"").decode("utf-8", "replace")
        sup = self._supervisor(submission_id)
        if sup is None:
            return ""
        import ray_tpu
        try:
            return ray_tpu.get(sup.logs.remote(), timeout=30).decode(
                "utf-8", "replace")
        except Exception:
            blob = _kv_get(_LOG_NS, submission_id)
            return (blob or b"").decode("utf-8", "replace")

    # -- control --

    def stop_job(self, submission_id: str) -> bool:
        rec = _get_record(submission_id)
        if rec is None:
            raise KeyError(submission_id)
        if rec["status"] in JobStatus.TERMINAL:
            return False
        sup = self._supervisor(submission_id)
        if sup is None:
            if rec["status"] == JobStatus.PENDING:
                # Supervisor not nameable yet — persist the stop intent;
                # JobSupervisor.start honors a STOPPED record by never
                # launching (and tears down if the spawn raced us).  The
                # separate intent key survives a concurrent RUNNING write.
                _kv_put(_KV_NS, submission_id + ".stop_intent", b"1")
                rec["status"] = JobStatus.STOPPED
                rec["message"] = "stopped before start"
                rec["end_time"] = time.time()
                _put_record(rec)
                return True
            return False
        import ray_tpu
        try:
            return ray_tpu.get(sup.stop.remote(), timeout=30)
        except Exception:
            return False

    def delete_job(self, submission_id: str) -> bool:
        rec = _get_record(submission_id)
        if rec is None:
            return False
        if rec["status"] not in JobStatus.TERMINAL:
            raise RuntimeError("cannot delete a non-terminal job; stop it "
                               "first")
        _kv_call("kv_del", {"ns": _KV_NS, "key": submission_id})
        _kv_call("kv_del", {"ns": _KV_NS, "key": submission_id + ".stop_intent"})
        _kv_call("kv_del", {"ns": _LOG_NS, "key": submission_id})
        return True
