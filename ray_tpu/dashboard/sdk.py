"""Job submission SDK: HTTP client for the dashboard REST API.

Reference parity: dashboard/modules/job/sdk.py (JobSubmissionClient —
submit_job/stop_job/get_job_status/get_job_logs over the job REST
surface) and its CLI wrapper dashboard/modules/job/cli.py.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional


class JobSubmissionError(RuntimeError):
    pass


class JobSubmissionClient:
    def __init__(self, address: str):
        """address: the dashboard HTTP endpoint, e.g. http://127.0.0.1:8265"""
        if not address.startswith("http://") and \
                not address.startswith("https://"):
            address = "http://" + address
        self.address = address.rstrip("/")

    def _request(self, method: str, path: str, body: Optional[dict] = None,
                 raw: bool = False):
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self.address + path, data=data, method=method,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=180) as resp:
                payload = resp.read()
        except urllib.error.HTTPError as e:
            detail = e.read().decode("utf-8", "replace")
            try:
                detail = json.loads(detail).get("error", detail)
            except Exception:
                pass
            raise JobSubmissionError(f"{e.code}: {detail}") from None
        if raw:
            return payload.decode("utf-8", "replace")
        out = json.loads(payload)
        if "error" in out:
            raise JobSubmissionError(out["error"])
        return out["result"]

    def submit_job(self, *, entrypoint: str,
                   runtime_env: Optional[Dict[str, Any]] = None,
                   metadata: Optional[Dict[str, str]] = None,
                   submission_id: Optional[str] = None) -> str:
        body = {"entrypoint": entrypoint}
        if runtime_env:
            body["runtime_env"] = runtime_env
        if metadata:
            body["metadata"] = metadata
        if submission_id:
            body["submission_id"] = submission_id
        return self._request("POST", "/api/jobs", body)["submission_id"]

    def list_jobs(self) -> List[Dict[str, Any]]:
        return self._request("GET", "/api/jobs")

    def get_job_status(self, submission_id: str) -> Dict[str, Any]:
        return self._request("GET", f"/api/jobs/{submission_id}")

    def get_job_logs(self, submission_id: str) -> str:
        return self._request("GET", f"/api/jobs/{submission_id}/logs",
                             raw=True)

    def stop_job(self, submission_id: str) -> bool:
        return self._request(
            "POST", f"/api/jobs/{submission_id}/stop")["stopped"]

    def delete_job(self, submission_id: str) -> bool:
        return self._request("DELETE", f"/api/jobs/{submission_id}")["deleted"]

    def wait_until_finished(self, submission_id: str,
                            timeout: float = 300.0,
                            poll_s: float = 0.5) -> Dict[str, Any]:
        from ray_tpu.dashboard.job_manager import JobStatus
        deadline = time.time() + timeout
        while time.time() < deadline:
            rec = self.get_job_status(submission_id)
            if rec["status"] in JobStatus.TERMINAL:
                return rec
            time.sleep(poll_s)
        raise TimeoutError(
            f"job {submission_id} not finished after {timeout}s")
