"""Dashboard + job submission layer.

Reference parity: dashboard/ (head + http_server_head.py REST API) and
dashboard/modules/job/ (job_manager.py JobManager:490 / JobSupervisor:136,
REST job_head.py, SDK sdk.py, CLI cli.py).  The TPU build keeps the same
split: a head process serving REST + a static UI over the state API, and a
job manager that runs each submitted entrypoint under a detached supervisor
actor on the cluster.
"""

from ray_tpu.dashboard.job_manager import JobManager, JobStatus
from ray_tpu.dashboard.sdk import JobSubmissionClient

__all__ = ["JobManager", "JobStatus", "JobSubmissionClient"]
