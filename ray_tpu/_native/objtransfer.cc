// Native node-to-node object transfer data plane.
//
// Reference parity: src/ray/object_manager/ — ObjectManager's chunked
// push/pull moves object payloads between Plasma stores over gRPC
// (object_buffer_pool.cc chunk views, push_manager.h throttling).  The
// TPU build's control RPCs stay on the Python daemons, but the BULK DATA
// path is this C++ plane: a raw-TCP server that writes straight out of
// the shared-memory store's mmap, and a client that receives straight
// into a freshly-allocated (unsealed) local store object — zero
// user-space copies on either end, no Python in the loop.
//
// Wire protocol (one object per connection):
//   request:  u32 magic "TPX1" | u8 id[28]
//   response: i32 status | u64 data_size | u64 meta_size | data | meta
//
// Compiled into libtpuxfer.so together with objstore.cc (the tpus_*
// symbols below resolve within the same shared object).

#include <arpa/inet.h>
#include <errno.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <pthread.h>
#include <stdint.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/types.h>
#include <sys/uio.h>
#include <unistd.h>

#include <atomic>

extern "C" {
// objstore.cc C API (same .so).
int tpus_attach(const char* path, void** out);
void tpus_close(void* h);
unsigned char* tpus_base(void* h);
int tpus_obj_create(void* h, const uint8_t* id, uint64_t data_size,
                    uint64_t meta_size, uint64_t* data_off);
int tpus_obj_seal(void* h, const uint8_t* id);
int tpus_obj_abort(void* h, const uint8_t* id);
int tpus_obj_get(void* h, const uint8_t* id, int64_t timeout_ms,
                 uint64_t* data_off, uint64_t* data_size,
                 uint64_t* meta_size);
int tpus_obj_release(void* h, const uint8_t* id);
}

namespace {

constexpr uint32_t kMagic = 0x31585054;  // "TPX1" little-endian
constexpr uint32_t kIdSize = 28;
constexpr uint64_t kMaxObject = 1ULL << 40;
constexpr int kIoTimeoutSec = 300;
// Serving-side concurrency cap (reference: push_manager.h throttles
// in-flight pushes).  Excess connections are shed; the puller falls back
// to the chunk-RPC path.
constexpr int kMaxConns = 64;

enum {
  TPOT_OK = 0,
  TPOT_EXISTS = -1,
  TPOT_NOT_FOUND = -2,
  TPOT_OOM = -3,
  TPOT_SYS = -6,
  TPOT_PROTO = -7,
};

int read_full(int fd, void* buf, uint64_t n) {
  uint8_t* p = static_cast<uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = recv(fd, p, n, 0);
    if (r == 0) return -1;
    if (r < 0) {
      if (errno == EINTR) continue;
      return -1;
    }
    p += r;
    n -= uint64_t(r);
  }
  return 0;
}

int write_full(int fd, const void* buf, uint64_t n) {
  const uint8_t* p = static_cast<const uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = send(fd, p, n, MSG_NOSIGNAL);
    if (r < 0) {
      if (errno == EINTR) continue;
      return -1;
    }
    p += r;
    n -= uint64_t(r);
  }
  return 0;
}

void set_io_timeouts(int fd) {
  struct timeval tv;
  tv.tv_sec = kIoTimeoutSec;
  tv.tv_usec = 0;
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

struct Server {
  void* store;
  int listen_fd;
  pthread_t accept_thread;
  std::atomic<bool> stopping{false};
  // Detached connection threads use `store`; stop must wait for them or
  // they'd touch a closed handle (use-after-munmap).
  pthread_mutex_t mu = PTHREAD_MUTEX_INITIALIZER;
  pthread_cond_t cv = PTHREAD_COND_INITIALIZER;
  int active = 0;
};

void conn_done(Server* srv) {
  pthread_mutex_lock(&srv->mu);
  if (--srv->active == 0) pthread_cond_broadcast(&srv->cv);
  pthread_mutex_unlock(&srv->mu);
}

struct ConnArg {
  Server* srv;
  int fd;
};

void* conn_main(void* argv) {
  ConnArg* arg = static_cast<ConnArg*>(argv);
  int fd = arg->fd;
  Server* srv = arg->srv;
  delete arg;
  set_io_timeouts(fd);

  uint32_t magic = 0;
  uint8_t id[kIdSize];
  if (read_full(fd, &magic, 4) != 0 || magic != kMagic ||
      read_full(fd, id, kIdSize) != 0) {
    close(fd);
    conn_done(srv);
    return nullptr;
  }
  uint64_t off = 0, dsize = 0, msize = 0;
  // timeout 0: a not-yet-sealed or absent object is the caller's problem
  // (it falls back to the RPC pull path, which also handles spill
  // restores); the data plane never blocks holding a connection.
  int rc = tpus_obj_get(srv->store, id, 0, &off, &dsize, &msize);
  if (rc != 0) {
    int32_t status = TPOT_NOT_FOUND;
    uint64_t zero = 0;
    write_full(fd, &status, 4);
    write_full(fd, &zero, 8);
    write_full(fd, &zero, 8);
    close(fd);
    conn_done(srv);
    return nullptr;
  }
  int32_t status = TPOT_OK;
  const uint8_t* base = tpus_base(srv->store);
  bool ok = write_full(fd, &status, 4) == 0 &&
            write_full(fd, &dsize, 8) == 0 &&
            write_full(fd, &msize, 8) == 0 &&
            write_full(fd, base + off, dsize) == 0 &&
            write_full(fd, base + off + dsize, msize) == 0;
  (void)ok;
  tpus_obj_release(srv->store, id);
  close(fd);
  conn_done(srv);
  return nullptr;
}

void* accept_main(void* argv) {
  Server* srv = static_cast<Server*>(argv);
  for (;;) {
    int fd = accept(srv->listen_fd, nullptr, nullptr);
    if (fd < 0) {
      if (srv->stopping.load()) break;
      if (errno == EINTR || errno == ECONNABORTED) continue;
      if (errno == EMFILE || errno == ENFILE || errno == ENOBUFS ||
          errno == ENOMEM || errno == EAGAIN) {
        // Transient resource exhaustion must not kill the listener —
        // pullers would block against a dead port until daemon restart.
        usleep(50 * 1000);
        continue;
      }
      break;
    }
    if (srv->stopping.load()) {
      close(fd);
      break;
    }
    ConnArg* arg = new ConnArg{srv, fd};
    pthread_mutex_lock(&srv->mu);
    if (srv->active >= kMaxConns) {
      pthread_mutex_unlock(&srv->mu);
      close(fd);
      delete arg;
      continue;
    }
    srv->active++;
    pthread_mutex_unlock(&srv->mu);
    pthread_t t;
    if (pthread_create(&t, nullptr, conn_main, arg) == 0) {
      pthread_detach(t);
    } else {
      close(fd);
      delete arg;
      conn_done(srv);
    }
  }
  return nullptr;
}

}  // namespace

extern "C" {

// Start serving the store at `store_path` on `port` (0 = ephemeral).
// Returns TPOT_OK with *out_port / *out_srv set.
int tpot_server_start(const char* store_path, int port, int* out_port,
                      void** out_srv) {
  void* store = nullptr;
  if (tpus_attach(store_path, &store) != 0) return TPOT_SYS;

  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) {
    tpus_close(store);
    return TPOT_SYS;
  }
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(uint16_t(port));
  if (bind(fd, reinterpret_cast<struct sockaddr*>(&addr), sizeof(addr)) != 0 ||
      listen(fd, 128) != 0) {
    close(fd);
    tpus_close(store);
    return TPOT_SYS;
  }
  socklen_t alen = sizeof(addr);
  if (getsockname(fd, reinterpret_cast<struct sockaddr*>(&addr), &alen) != 0) {
    close(fd);
    tpus_close(store);
    return TPOT_SYS;
  }
  Server* srv = new Server();
  srv->store = store;
  srv->listen_fd = fd;
  if (pthread_create(&srv->accept_thread, nullptr, accept_main, srv) != 0) {
    close(fd);
    tpus_close(store);
    delete srv;
    return TPOT_SYS;
  }
  *out_port = ntohs(addr.sin_port);
  *out_srv = srv;
  return TPOT_OK;
}

void tpot_server_stop(void* srvv) {
  Server* srv = static_cast<Server*>(srvv);
  srv->stopping.store(true);
  shutdown(srv->listen_fd, SHUT_RDWR);
  close(srv->listen_fd);
  pthread_join(srv->accept_thread, nullptr);
  // Give in-flight connections a short grace to finish; a hung peer must
  // not turn daemon shutdown into a 300s wait.  If any remain, leak the
  // handle/mapping instead of closing under them (the caller is tearing
  // the process down; the robust store survives regardless).
  struct timespec deadline;
  clock_gettime(CLOCK_REALTIME, &deadline);
  deadline.tv_sec += 5;
  pthread_mutex_lock(&srv->mu);
  int rc = 0;
  while (srv->active > 0 && rc != ETIMEDOUT) {
    rc = pthread_cond_timedwait(&srv->cv, &srv->mu, &deadline);
  }
  bool drained = srv->active == 0;
  pthread_mutex_unlock(&srv->mu);
  if (drained) {
    tpus_close(srv->store);
    delete srv;
  }
}

// Attach a fetch client to the LOCAL store (one per process).
int tpot_attach(const char* store_path, void** out) {
  return tpus_attach(store_path, out);
}

void tpot_detach(void* h) { tpus_close(h); }

// Fetch object `id` from host:port directly into the local store (sealed
// on success).  TPOT_EXISTS means another puller beat us — treat as
// success and read the store.
int tpot_fetch(void* h, const char* host, int port, const uint8_t* id) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return TPOT_SYS;
  set_io_timeouts(fd);
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_port = htons(uint16_t(port));
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    close(fd);
    return TPOT_SYS;
  }
  if (connect(fd, reinterpret_cast<struct sockaddr*>(&addr),
              sizeof(addr)) != 0) {
    close(fd);
    return TPOT_SYS;
  }
  uint32_t magic = kMagic;
  if (write_full(fd, &magic, 4) != 0 || write_full(fd, id, kIdSize) != 0) {
    close(fd);
    return TPOT_SYS;
  }
  int32_t status = 0;
  uint64_t dsize = 0, msize = 0;
  if (read_full(fd, &status, 4) != 0 || read_full(fd, &dsize, 8) != 0 ||
      read_full(fd, &msize, 8) != 0) {
    close(fd);
    return TPOT_SYS;
  }
  if (status != TPOT_OK) {
    close(fd);
    return status;
  }
  if (dsize > kMaxObject || msize > kMaxObject) {
    close(fd);
    return TPOT_PROTO;
  }
  uint64_t off = 0;
  int rc = tpus_obj_create(h, id, dsize, msize, &off);
  if (rc != 0) {
    close(fd);
    if (rc == TPOT_EXISTS) {
      // A concurrent puller owns the allocation; EXISTS only means
      // "locally available" once that copy seals.  Poll rather than wait
      // on the seal condvar: if the racing puller ABORTS, the slot
      // disappears and a condvar wait would sit out its full timeout.
      for (int i = 0; i < 60 * 100; i++) {
        uint64_t o, d, m;
        int grc = tpus_obj_get(h, id, 0, &o, &d, &m);
        if (grc == 0) {
          tpus_obj_release(h, id);
          return TPOT_EXISTS;
        }
        if (grc == -2 /* TPUS_NOT_FOUND */) {
          return TPOT_NOT_FOUND;  // racing copy aborted/evicted
        }
        if (grc != -5 /* TPUS_BAD_STATE: created, unsealed */) {
          return TPOT_SYS;  // lock/store failure — not an absence signal
        }
        usleep(10 * 1000);
      }
      return TPOT_SYS;
    }
    return rc;  // TPOT_OOM etc. map 1:1 to tpus codes
  }
  uint8_t* base = tpus_base(h) + off;
  if (read_full(fd, base, dsize) != 0 ||
      read_full(fd, base + dsize, msize) != 0) {
    tpus_obj_abort(h, id);
    close(fd);
    return TPOT_SYS;
  }
  close(fd);
  if (tpus_obj_seal(h, id) != 0) {
    tpus_obj_abort(h, id);
    return TPOT_SYS;
  }
  return TPOT_OK;
}

}  // extern "C"
