// Native task-submission transport (the control-plane hot path).
//
// Reference parity: src/ray/core_worker/transport/direct_task_transport.h:75
// and direct_actor_transport.h:50 — the reference keeps task submission in
// C++ (gRPC PushTask pipelined onto leased workers, receiver-side ordered
// queues) precisely because a Python RPC layer caps the control plane at
// O(100) calls/s.  This is the TPU build's equivalent: a framed raw-TCP
// plane with
//   - client: persistent connections to worker processes, unbounded
//     pipelining, completions delivered to Python in batches (one GIL
//     crossing per batch, not per task);
//   - server: epoll reader preserving per-connection FIFO order (one TCP
//     connection per caller == per-caller submission order, the ordering
//     contract of actor_scheduling_queue.h), a task queue drained by a
//     Python executor thread through a blocking batched pop, and a writer
//     that streams replies back.
//
// Concurrency design: enqueue paths (tpt_send / tpt_server_reply) are
// called with the GIL held (PyDLL) and only append + flip an eventfd flag
// — they never issue socket syscalls.  The io thread swaps write queues
// out under the lock and performs all syscalls (writev-coalesced, one per
// connection per drain) with the lock RELEASED, so Python submitters
// never block behind kernel work.
//
// Wire format (both directions):
//   u32 frame_len | u64 req_id | u8 payload[frame_len - 8]
// Payload semantics (pickled task spec / reply) live entirely in Python;
// C++ sees opaque bytes.  Transport-level failures surface as completions
// with status != 0 and empty payloads.

#include <arpa/inet.h>
#include <errno.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <stdint.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <deque>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

constexpr uint32_t kMaxFrame = 1u << 30;
constexpr int kMaxIov = 64;

enum {
  TPT_OK = 0,
  TPT_ECONN = -1,   // connection closed / reset with requests in flight
  TPT_ESYS = -2,
  TPT_EARG = -3,
  TPT_EBUF = -4,    // head record exceeds caller buffer; *used = needed size
};

struct Buf {
  std::vector<uint8_t> data;
  size_t off = 0;
};

struct Record {
  uint64_t tag = 0;      // client: req_id; server: conn_tag
  uint64_t req_id = 0;   // server only
  int32_t status = TPT_OK;
  std::vector<uint8_t> payload;
};

// Pack records into a caller-supplied buffer:
//   u64 tag | u64 req_id | i32 status | u64 len | payload
// Returns the number of records packed; records that don't fit stay queued.
size_t pack_records(std::deque<Record>& q, uint8_t* buf, uint64_t cap,
                    uint64_t* used) {
  size_t n = 0;
  uint64_t w = 0;
  while (!q.empty()) {
    Record& r = q.front();
    uint64_t need = 8 + 8 + 4 + 8 + r.payload.size();
    if (w + need > cap) break;
    memcpy(buf + w, &r.tag, 8); w += 8;
    memcpy(buf + w, &r.req_id, 8); w += 8;
    memcpy(buf + w, &r.status, 4); w += 4;
    uint64_t len = r.payload.size();
    memcpy(buf + w, &len, 8); w += 8;
    if (len) memcpy(buf + w, r.payload.data(), len);
    w += len;
    q.pop_front();
    n++;
  }
  *used = w;
  return n;
}

struct Conn {
  int fd = -1;
  uint64_t tag = 0;
  std::vector<uint8_t> rbuf;   // io thread only
  std::deque<Buf> wq;          // guarded by endpoint mu
  bool want_write = false;     // io thread only
  bool closing = false;        // guarded by endpoint mu
};

void frame_into(std::vector<uint8_t>& out, uint64_t req_id,
                const uint8_t* payload, uint64_t len) {
  uint32_t flen = uint32_t(8 + len);
  out.resize(4 + flen);
  memcpy(out.data(), &flen, 4);
  memcpy(out.data() + 4, &req_id, 8);
  if (len) memcpy(out.data() + 12, payload, len);
}

template <typename F>
bool drain_frames(Conn* c, F&& on_frame) {
  size_t off = 0;
  while (c->rbuf.size() - off >= 4) {
    uint32_t flen;
    memcpy(&flen, c->rbuf.data() + off, 4);
    if (flen < 8 || flen > kMaxFrame) return false;
    if (c->rbuf.size() - off < 4 + size_t(flen)) break;
    uint64_t req_id;
    memcpy(&req_id, c->rbuf.data() + off + 4, 8);
    on_frame(req_id, c->rbuf.data() + off + 12, flen - 8);
    off += 4 + flen;
  }
  if (off) c->rbuf.erase(c->rbuf.begin(), c->rbuf.begin() + off);
  return true;
}

bool read_avail(Conn* c) {
  uint8_t tmp[1 << 16];
  for (;;) {
    ssize_t r = recv(c->fd, tmp, sizeof tmp, MSG_DONTWAIT);
    if (r > 0) {
      c->rbuf.insert(c->rbuf.end(), tmp, tmp + r);
      if (size_t(r) < sizeof tmp) return true;
      continue;
    }
    if (r == 0) return false;
    if (errno == EAGAIN || errno == EWOULDBLOCK) return true;
    if (errno == EINTR) continue;
    return false;
  }
}

// writev as much of `bufs` as the socket accepts.  Returns false on a hard
// error; drained bufs are popped, a partial write leaves its offset.
bool flush_bufs(int fd, std::deque<Buf>& bufs, bool* blocked) {
  *blocked = false;
  while (!bufs.empty()) {
    iovec iov[kMaxIov];
    int n = 0;
    for (auto it = bufs.begin(); it != bufs.end() && n < kMaxIov; ++it, ++n) {
      iov[n].iov_base = it->data.data() + it->off;
      iov[n].iov_len = it->data.size() - it->off;
    }
    ssize_t w = writev(fd, iov, n);
    if (w < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) { *blocked = true; return true; }
      if (errno == EINTR) continue;
      return false;
    }
    size_t left = size_t(w);
    while (left > 0 && !bufs.empty()) {
      Buf& b = bufs.front();
      size_t avail = b.data.size() - b.off;
      if (left >= avail) {
        left -= avail;
        bufs.pop_front();
      } else {
        b.off += left;
        left = 0;
      }
    }
  }
  return true;
}

void wake_fd(int fd) {
  uint64_t one = 1;
  ssize_t r = write(fd, &one, 8);
  (void)r;
}

// Shared endpoint machinery for client and server loops.
struct Endpoint {
  int epfd = -1;
  int wakefd = -1;
  std::thread io;
  std::atomic<bool> stop{false};
  std::atomic<bool> wake_pending{false};

  std::mutex mu;  // conns map, wq, closing flags
  std::unordered_map<uint64_t, Conn*> conns;
  uint64_t next_tag = 2;  // 0 = wake, 1 = listener

  void rearm(Conn* c) {
    epoll_event ev{};
    ev.events = EPOLLIN | (c->want_write ? EPOLLOUT : 0);
    ev.data.u64 = c->tag;
    epoll_ctl(epfd, EPOLL_CTL_MOD, c->fd, &ev);
  }

  // io thread only.  Caller must NOT hold mu.
  void destroy(Conn* c) {
    {
      std::lock_guard<std::mutex> g(mu);
      conns.erase(c->tag);
    }
    epoll_ctl(epfd, EPOLL_CTL_DEL, c->fd, nullptr);
    close(c->fd);
    delete c;
  }

  // Swap out every non-empty write queue under mu, then flush with the
  // lock released (one writev per conn per pass).  Returns conns that
  // died during the flush.
  std::vector<Conn*> flush_all() {
    std::vector<std::pair<Conn*, std::deque<Buf>>> work;
    std::vector<Conn*> dead;
    {
      std::lock_guard<std::mutex> g(mu);
      for (auto& kv : conns) {
        Conn* c = kv.second;
        if (c->closing) { dead.push_back(c); continue; }
        if (!c->wq.empty()) {
          work.emplace_back(c, std::move(c->wq));
          c->wq.clear();
        }
      }
    }
    for (auto& wc : work) {
      Conn* c = wc.first;
      bool blocked = false;
      if (!flush_bufs(c->fd, wc.second, &blocked)) {
        dead.push_back(c);
        continue;
      }
      if (!wc.second.empty()) {
        // Unsent remainder goes back to the FRONT (frames enqueued by
        // Python while we were flushing must stay behind it).
        std::lock_guard<std::mutex> g(mu);
        for (auto it = wc.second.rbegin(); it != wc.second.rend(); ++it)
          c->wq.push_front(std::move(*it));
      }
      bool was = c->want_write;
      c->want_write = blocked;
      if (blocked != was) rearm(c);
    }
    return dead;
  }
};

// ---------------------------------------------------------------------------
// Client
// ---------------------------------------------------------------------------

struct Client : Endpoint {
  std::unordered_map<uint64_t, uint64_t> inflight;  // req_id -> conn tag
                                                    // (guarded by mu)
  std::mutex cmu;
  std::condition_variable ccv;
  std::deque<Record> completions;

  void push_completion(uint64_t req_id, int32_t status, const uint8_t* p,
                       uint64_t len) {
    Record r;
    r.tag = req_id;
    r.status = status;
    if (len) r.payload.assign(p, p + len);
    {
      std::lock_guard<std::mutex> g(cmu);
      completions.push_back(std::move(r));
    }
    ccv.notify_one();
  }

  // io thread only, mu NOT held.
  void fail_conn(Conn* c) {
    std::vector<uint64_t> dead_reqs;
    {
      std::lock_guard<std::mutex> g(mu);
      for (auto& kv : inflight)
        if (kv.second == c->tag) dead_reqs.push_back(kv.first);
      for (uint64_t rid : dead_reqs) inflight.erase(rid);
    }
    for (uint64_t rid : dead_reqs)
      push_completion(rid, TPT_ECONN, nullptr, 0);
    destroy(c);
  }

  void loop() {
    epoll_event evs[64];
    while (!stop.load()) {
      int n = epoll_wait(epfd, evs, 64, 200);
      if (n < 0) {
        if (errno == EINTR) continue;
        break;
      }
      for (int i = 0; i < n; i++) {
        uint64_t tag = evs[i].data.u64;
        if (tag == 0) {
          uint64_t v;
          while (read(wakefd, &v, 8) == 8) {}
          wake_pending.store(false);
          continue;
        }
        Conn* c;
        {
          std::lock_guard<std::mutex> g(mu);
          auto it = conns.find(tag);
          if (it == conns.end()) continue;
          c = it->second;
        }
        bool ok = !(evs[i].events & (EPOLLHUP | EPOLLERR));
        std::vector<Record> got;
        if (ok && (evs[i].events & EPOLLIN)) {
          ok = read_avail(c);
          if (ok)
            ok = drain_frames(c, [&](uint64_t rid, const uint8_t* p,
                                     uint64_t len) {
              Record r;
              r.tag = rid;
              r.payload.assign(p, p + len);
              got.push_back(std::move(r));
            });
        }
        if (!got.empty()) {
          {
            std::lock_guard<std::mutex> g(mu);
            for (auto& r : got) inflight.erase(r.tag);
          }
          {
            std::lock_guard<std::mutex> g(cmu);
            for (auto& r : got) completions.push_back(std::move(r));
          }
          ccv.notify_one();
        }
        if (!ok) fail_conn(c);
      }
      for (Conn* c : flush_all()) fail_conn(c);
    }
  }
};

// ---------------------------------------------------------------------------
// Server
// ---------------------------------------------------------------------------

struct Server : Endpoint {
  int lfd = -1;
  int port = 0;

  std::mutex tmu;
  std::condition_variable tcv;
  std::deque<Record> tasks;

  void loop() {
    epoll_event evs[64];
    while (!stop.load()) {
      int n = epoll_wait(epfd, evs, 64, 200);
      if (n < 0) {
        if (errno == EINTR) continue;
        break;
      }
      for (int i = 0; i < n; i++) {
        uint64_t tag = evs[i].data.u64;
        if (tag == 0) {
          uint64_t v;
          while (read(wakefd, &v, 8) == 8) {}
          wake_pending.store(false);
          continue;
        }
        if (tag == 1) {
          for (;;) {
            int fd = accept4(lfd, nullptr, nullptr, SOCK_NONBLOCK);
            if (fd < 0) break;
            int one = 1;
            setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
            Conn* c = new Conn;
            c->fd = fd;
            {
              std::lock_guard<std::mutex> g(mu);
              c->tag = next_tag++;
              conns[c->tag] = c;
            }
            epoll_event ev{};
            ev.events = EPOLLIN;
            ev.data.u64 = c->tag;
            epoll_ctl(epfd, EPOLL_CTL_ADD, fd, &ev);
          }
          continue;
        }
        Conn* c;
        {
          std::lock_guard<std::mutex> g(mu);
          auto it = conns.find(tag);
          if (it == conns.end()) continue;
          c = it->second;
        }
        bool ok = !(evs[i].events & (EPOLLHUP | EPOLLERR));
        bool any = false;
        if (ok && (evs[i].events & EPOLLIN)) {
          ok = read_avail(c);
          if (ok) {
            std::lock_guard<std::mutex> tg(tmu);
            ok = drain_frames(c, [&](uint64_t rid, const uint8_t* p,
                                     uint64_t len) {
              Record r;
              r.tag = c->tag;
              r.req_id = rid;
              r.payload.assign(p, p + len);
              tasks.push_back(std::move(r));
              any = true;
            });
          }
        }
        if (any) tcv.notify_all();
        if (!ok) destroy(c);
      }
      for (Conn* c : flush_all()) destroy(c);
    }
  }
};

}  // namespace

// ---------------------------------------------------------------------------
// C API
// ---------------------------------------------------------------------------

extern "C" {

int tpt_client_new(void** out) {
  Client* c = new Client;
  c->epfd = epoll_create1(0);
  c->wakefd = eventfd(0, EFD_NONBLOCK);
  if (c->epfd < 0 || c->wakefd < 0) { delete c; return TPT_ESYS; }
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.u64 = 0;
  epoll_ctl(c->epfd, EPOLL_CTL_ADD, c->wakefd, &ev);
  c->io = std::thread([c] { c->loop(); });
  *out = c;
  return TPT_OK;
}

int tpt_connect(void* h, const char* host, int port, uint64_t* out_tag) {
  Client* cl = static_cast<Client*>(h);
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return TPT_ESYS;
  sockaddr_in sa{};
  sa.sin_family = AF_INET;
  sa.sin_port = htons(uint16_t(port));
  if (inet_pton(AF_INET, host, &sa.sin_addr) != 1) { close(fd); return TPT_EARG; }
  if (connect(fd, reinterpret_cast<sockaddr*>(&sa), sizeof sa) != 0) {
    close(fd);
    return TPT_ECONN;
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
  Conn* c = new Conn;
  c->fd = fd;
  {
    std::lock_guard<std::mutex> g(cl->mu);
    c->tag = cl->next_tag++;
    cl->conns[c->tag] = c;
  }
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.u64 = c->tag;
  epoll_ctl(cl->epfd, EPOLL_CTL_ADD, fd, &ev);
  *out_tag = c->tag;
  return TPT_OK;
}

int tpt_send(void* h, uint64_t conn_tag, uint64_t req_id,
             const uint8_t* payload, uint64_t len) {
  Client* cl = static_cast<Client*>(h);
  {
    std::lock_guard<std::mutex> g(cl->mu);
    auto it = cl->conns.find(conn_tag);
    if (it == cl->conns.end() || it->second->closing) return TPT_ECONN;
    Conn* c = it->second;
    Buf b;
    frame_into(b.data, req_id, payload, len);
    c->wq.push_back(std::move(b));
    cl->inflight[req_id] = conn_tag;
  }
  if (!cl->wake_pending.exchange(true)) wake_fd(cl->wakefd);
  return TPT_OK;
}

int tpt_send_raw(void* h, uint64_t conn_tag, const uint8_t* framed,
                 uint64_t len) {
  // Batched submission: `framed` is a concatenation of already-framed
  // requests (u32 frame_len | u64 req_id | payload), built by Python so a
  // whole dispatch burst costs ONE library call, one queue append and one
  // wakeup.  Frames are walked (no copy beyond the single buffer append)
  // to register req_ids for fail_conn's in-flight accounting.
  Client* cl = static_cast<Client*>(h);
  // Validate the whole buffer BEFORE mutating any state: a malformed
  // later frame must not leave earlier req_ids registered in-flight for
  // a batch that was never enqueued.
  {
    uint64_t off = 0;
    while (off + 12 <= len) {
      uint32_t flen;
      memcpy(&flen, framed + off, 4);
      if (flen < 8 || off + 4 + flen > len) return TPT_EARG;
      off += 4 + flen;
    }
    if (off != len) return TPT_EARG;
  }
  {
    std::lock_guard<std::mutex> g(cl->mu);
    auto it = cl->conns.find(conn_tag);
    if (it == cl->conns.end() || it->second->closing) return TPT_ECONN;
    Conn* c = it->second;
    uint64_t off = 0;
    while (off + 12 <= len) {
      uint32_t flen;
      memcpy(&flen, framed + off, 4);
      uint64_t req_id;
      memcpy(&req_id, framed + off + 4, 8);
      cl->inflight[req_id] = conn_tag;
      off += 4 + flen;
    }
    Buf b;
    b.data.assign(framed, framed + len);
    c->wq.push_back(std::move(b));
  }
  if (!cl->wake_pending.exchange(true)) wake_fd(cl->wakefd);
  return TPT_OK;
}

int tpt_close_conn(void* h, uint64_t conn_tag) {
  Client* cl = static_cast<Client*>(h);
  {
    std::lock_guard<std::mutex> g(cl->mu);
    auto it = cl->conns.find(conn_tag);
    if (it == cl->conns.end()) return TPT_ECONN;
    it->second->closing = true;
  }
  if (!cl->wake_pending.exchange(true)) wake_fd(cl->wakefd);
  return TPT_OK;
}

int tpt_poll(void* h, uint8_t* buf, uint64_t cap, uint64_t* used,
             int timeout_ms) {
  Client* cl = static_cast<Client*>(h);
  std::unique_lock<std::mutex> g(cl->cmu);
  if (cl->completions.empty()) {
    cl->ccv.wait_for(g, std::chrono::milliseconds(timeout_ms),
                     [&] { return !cl->completions.empty()
                                  || cl->stop.load(); });
  }
  int n = int(pack_records(cl->completions, buf, cap, used));
  if (n == 0 && !cl->completions.empty()) {
    // Head record alone exceeds `cap`: without this signal it would sit
    // at the queue head forever, wedging every later completion.
    *used = 28 + cl->completions.front().payload.size();
    return TPT_EBUF;
  }
  return n;
}

void tpt_client_close(void* h) {
  Client* cl = static_cast<Client*>(h);
  cl->stop.store(true);
  wake_fd(cl->wakefd);
  cl->ccv.notify_all();
  if (cl->io.joinable()) cl->io.join();
  {
    std::lock_guard<std::mutex> g(cl->mu);
    for (auto& kv : cl->conns) {
      close(kv.second->fd);
      delete kv.second;
    }
    cl->conns.clear();
  }
  close(cl->epfd);
  close(cl->wakefd);
  delete cl;
}

int tpt_server_new(const char* host, int port, void** out, int* bound_port) {
  Server* s = new Server;
  s->epfd = epoll_create1(0);
  s->wakefd = eventfd(0, EFD_NONBLOCK);
  s->lfd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
  if (s->epfd < 0 || s->wakefd < 0 || s->lfd < 0) { delete s; return TPT_ESYS; }
  int one = 1;
  setsockopt(s->lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  sockaddr_in sa{};
  sa.sin_family = AF_INET;
  sa.sin_port = htons(uint16_t(port));
  if (inet_pton(AF_INET, host, &sa.sin_addr) != 1) { delete s; return TPT_EARG; }
  if (bind(s->lfd, reinterpret_cast<sockaddr*>(&sa), sizeof sa) != 0 ||
      listen(s->lfd, 512) != 0) {
    close(s->lfd);
    delete s;
    return TPT_ESYS;
  }
  socklen_t slen = sizeof sa;
  getsockname(s->lfd, reinterpret_cast<sockaddr*>(&sa), &slen);
  s->port = ntohs(sa.sin_port);
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.u64 = 0;
  epoll_ctl(s->epfd, EPOLL_CTL_ADD, s->wakefd, &ev);
  epoll_event lv{};
  lv.events = EPOLLIN;
  lv.data.u64 = 1;
  epoll_ctl(s->epfd, EPOLL_CTL_ADD, s->lfd, &lv);
  s->io = std::thread([s] { s->loop(); });
  *out = s;
  *bound_port = s->port;
  return TPT_OK;
}

int tpt_server_pop(void* h, uint8_t* buf, uint64_t cap, uint64_t* used,
                   int timeout_ms) {
  Server* s = static_cast<Server*>(h);
  std::unique_lock<std::mutex> g(s->tmu);
  if (s->tasks.empty()) {
    s->tcv.wait_for(g, std::chrono::milliseconds(timeout_ms),
                    [&] { return !s->tasks.empty() || s->stop.load(); });
  }
  int n = int(pack_records(s->tasks, buf, cap, used));
  if (n == 0 && !s->tasks.empty()) {
    *used = 28 + s->tasks.front().payload.size();
    return TPT_EBUF;
  }
  return n;
}

int tpt_server_reply(void* h, uint64_t conn_tag, uint64_t req_id,
                     const uint8_t* payload, uint64_t len) {
  Server* s = static_cast<Server*>(h);
  {
    std::lock_guard<std::mutex> g(s->mu);
    auto it = s->conns.find(conn_tag);
    if (it == s->conns.end() || it->second->closing)
      return TPT_ECONN;  // caller gone; drop
    Conn* c = it->second;
    Buf b;
    frame_into(b.data, req_id, payload, len);
    c->wq.push_back(std::move(b));
  }
  if (!s->wake_pending.exchange(true)) wake_fd(s->wakefd);
  return TPT_OK;
}

int tpt_server_reply_raw(void* h, uint64_t conn_tag, const uint8_t* framed,
                         uint64_t len) {
  // Batched replies: one library call, one queue append and one io wakeup
  // for every reply produced by an execution batch (the per-reply eventfd
  // write costs a context switch on small hosts).
  Server* s = static_cast<Server*>(h);
  {
    std::lock_guard<std::mutex> g(s->mu);
    auto it = s->conns.find(conn_tag);
    if (it == s->conns.end() || it->second->closing) return TPT_ECONN;
    Buf b;
    b.data.assign(framed, framed + len);
    it->second->wq.push_back(std::move(b));
  }
  if (!s->wake_pending.exchange(true)) wake_fd(s->wakefd);
  return TPT_OK;
}

void tpt_server_close(void* h) {
  Server* s = static_cast<Server*>(h);
  s->stop.store(true);
  wake_fd(s->wakefd);
  s->tcv.notify_all();
  if (s->io.joinable()) s->io.join();
  {
    std::lock_guard<std::mutex> g(s->mu);
    for (auto& kv : s->conns) {
      close(kv.second->fd);
      delete kv.second;
    }
    s->conns.clear();
  }
  close(s->lfd);
  close(s->epfd);
  close(s->wakefd);
  delete s;
}

}  // extern "C"
