// Native task-submission transport (the control-plane hot path).
//
// Reference parity: src/ray/core_worker/transport/direct_task_transport.h:75
// and direct_actor_transport.h:50 — the reference keeps task submission in
// C++ (gRPC PushTask pipelined onto leased workers, receiver-side ordered
// queues) precisely because a Python RPC layer caps the control plane at
// O(100) calls/s.  This is the TPU build's equivalent: a framed raw-TCP
// plane with
//   - client: persistent connections to worker processes, unbounded
//     pipelining, completions delivered to Python in batches (one GIL
//     crossing per batch, not per task);
//   - server: epoll reader preserving per-connection FIFO order (one TCP
//     connection per caller == per-caller submission order, the ordering
//     contract of actor_scheduling_queue.h), a task queue drained by a
//     Python executor thread through a blocking batched pop, and a writer
//     that streams replies back.
//
// Concurrency design: enqueue paths (tpt_send / tpt_server_reply) are
// called with the GIL held (PyDLL) and only append + flip an eventfd flag
// — they never issue socket syscalls.  The io thread swaps write queues
// out under the lock and performs all syscalls (writev-coalesced, one per
// connection per drain) with the lock RELEASED, so Python submitters
// never block behind kernel work.
//
// Wire format (both directions):
//   u32 frame_len | u64 req_id | u8 payload[frame_len - 8]
// Payload semantics (pickled task spec / reply) live entirely in Python;
// C++ sees opaque bytes.  Transport-level failures surface as completions
// with status != 0 and empty payloads.

#include <arpa/inet.h>
#include <errno.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <stdint.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <deque>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

constexpr uint32_t kMaxFrame = 1u << 30;
constexpr int kMaxIov = 64;

enum {
  TPT_OK = 0,
  TPT_ECONN = -1,   // connection closed / reset with requests in flight
  TPT_ESYS = -2,
  TPT_EARG = -3,
  TPT_EBUF = -4,    // head record exceeds caller buffer; *used = needed size
};

struct Buf {
  std::vector<uint8_t> data;
  size_t off = 0;
};

struct Record {
  uint64_t tag = 0;      // client: req_id; server: conn_tag
  uint64_t req_id = 0;   // server only
  int32_t status = TPT_OK;
  std::vector<uint8_t> payload;
};

// Pack records into a caller-supplied buffer:
//   u64 tag | u64 req_id | i32 status | u64 len | payload
// Returns the number of records packed; records that don't fit stay queued.
size_t pack_records(std::deque<Record>& q, uint8_t* buf, uint64_t cap,
                    uint64_t* used) {
  size_t n = 0;
  uint64_t w = 0;
  while (!q.empty()) {
    Record& r = q.front();
    uint64_t need = 8 + 8 + 4 + 8 + r.payload.size();
    if (w + need > cap) break;
    memcpy(buf + w, &r.tag, 8); w += 8;
    memcpy(buf + w, &r.req_id, 8); w += 8;
    memcpy(buf + w, &r.status, 4); w += 4;
    uint64_t len = r.payload.size();
    memcpy(buf + w, &len, 8); w += 8;
    if (len) memcpy(buf + w, r.payload.data(), len);
    w += len;
    q.pop_front();
    n++;
  }
  *used = w;
  return n;
}

struct Conn {
  int fd = -1;
  uint64_t tag = 0;
  std::vector<uint8_t> rbuf;   // io thread only
  std::deque<Buf> wq;          // guarded by endpoint mu
  bool want_write = false;     // io thread only
  bool closing = false;        // guarded by endpoint mu
  // Exactly one thread may write the socket at a time (both guarded by
  // endpoint mu): an enqueuer doing an inline write, or the io thread
  // flushing with the lock released.
  bool inline_writing = false;
  bool io_writing = false;
};

void frame_into(std::vector<uint8_t>& out, uint64_t req_id,
                const uint8_t* payload, uint64_t len) {
  uint32_t flen = uint32_t(8 + len);
  out.resize(4 + flen);
  memcpy(out.data(), &flen, 4);
  memcpy(out.data() + 4, &req_id, 8);
  if (len) memcpy(out.data() + 12, payload, len);
}

template <typename F>
bool drain_frames(Conn* c, F&& on_frame) {
  size_t off = 0;
  while (c->rbuf.size() - off >= 4) {
    uint32_t flen;
    memcpy(&flen, c->rbuf.data() + off, 4);
    if (flen < 8 || flen > kMaxFrame) return false;
    if (c->rbuf.size() - off < 4 + size_t(flen)) break;
    uint64_t req_id;
    memcpy(&req_id, c->rbuf.data() + off + 4, 8);
    on_frame(req_id, c->rbuf.data() + off + 12, flen - 8);
    off += 4 + flen;
  }
  if (off) c->rbuf.erase(c->rbuf.begin(), c->rbuf.begin() + off);
  return true;
}

bool read_avail(Conn* c) {
  uint8_t tmp[1 << 16];
  for (;;) {
    ssize_t r = recv(c->fd, tmp, sizeof tmp, MSG_DONTWAIT);
    if (r > 0) {
      c->rbuf.insert(c->rbuf.end(), tmp, tmp + r);
      if (size_t(r) < sizeof tmp) return true;
      continue;
    }
    if (r == 0) return false;
    if (errno == EAGAIN || errno == EWOULDBLOCK) return true;
    if (errno == EINTR) continue;
    return false;
  }
}

// writev as much of `bufs` as the socket accepts.  Returns false on a hard
// error; drained bufs are popped, a partial write leaves its offset.
bool flush_bufs(int fd, std::deque<Buf>& bufs, bool* blocked) {
  *blocked = false;
  while (!bufs.empty()) {
    iovec iov[kMaxIov];
    int n = 0;
    for (auto it = bufs.begin(); it != bufs.end() && n < kMaxIov; ++it, ++n) {
      iov[n].iov_base = it->data.data() + it->off;
      iov[n].iov_len = it->data.size() - it->off;
    }
    ssize_t w = writev(fd, iov, n);
    if (w < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) { *blocked = true; return true; }
      if (errno == EINTR) continue;
      return false;
    }
    size_t left = size_t(w);
    while (left > 0 && !bufs.empty()) {
      Buf& b = bufs.front();
      size_t avail = b.data.size() - b.off;
      if (left >= avail) {
        left -= avail;
        bufs.pop_front();
      } else {
        b.off += left;
        left = 0;
      }
    }
  }
  return true;
}

void wake_fd(int fd) {
  uint64_t one = 1;
  ssize_t r = write(fd, &one, 8);
  (void)r;
}

// Shared endpoint machinery for client and server loops.
struct Endpoint {
  int epfd = -1;
  int wakefd = -1;
  std::thread io;
  std::atomic<bool> stop{false};
  std::atomic<bool> wake_pending{false};

  std::mutex mu;  // conns map, wq, closing flags
  std::unordered_map<uint64_t, Conn*> conns;
  uint64_t next_tag = 2;  // 0 = wake, 1 = listener

  void rearm(Conn* c) {
    epoll_event ev{};
    ev.events = EPOLLIN | (c->want_write ? EPOLLOUT : 0);
    ev.data.u64 = c->tag;
    epoll_ctl(epfd, EPOLL_CTL_MOD, c->fd, &ev);
  }

  // Enqueue `b` on `conn_tag`, writing the socket INLINE from the calling
  // thread when the connection is idle (no queued frames, no io-thread
  // write pending): on a one-core host the eventfd wake costs a context
  // switch per hop, and the submitting thread writing its own burst
  // removes it.  Falls back to queue + wake whenever the io thread (or
  // another enqueuer) owns the socket.  Returns TPT_ECONN if the conn is
  // gone; sets *wake if the io thread must be woken.
  int enqueue_or_write(uint64_t conn_tag, Buf&& b, bool* wake) {
    Conn* c;
    {
      std::lock_guard<std::mutex> g(mu);
      auto it = conns.find(conn_tag);
      if (it == conns.end() || it->second->closing) return TPT_ECONN;
      c = it->second;
      if (!c->wq.empty() || c->want_write || c->inline_writing
          || c->io_writing) {
        c->wq.push_back(std::move(b));
        *wake = true;
        return TPT_OK;
      }
      c->inline_writing = true;
    }
    std::deque<Buf> q;
    q.push_back(std::move(b));
    bool blocked = false;
    bool ok = flush_bufs(c->fd, q, &blocked);
    {
      std::lock_guard<std::mutex> g(mu);
      c->inline_writing = false;
      if (!ok) {
        // Socket error: let the io thread run its failure path (it owns
        // conn teardown and in-flight accounting).
        c->closing = true;
        *wake = true;
        return TPT_OK;
      }
      if (!q.empty()) {
        // Partial write: remainder goes to the FRONT (frames enqueued
        // while we were writing must stay behind it); the io thread
        // retries and arms EPOLLOUT on its own EAGAIN.
        for (auto qit = q.rbegin(); qit != q.rend(); ++qit)
          c->wq.push_front(std::move(*qit));
        *wake = true;
      } else if (!c->wq.empty()) {
        *wake = true;  // someone enqueued behind us while we wrote
      }
      if (c->closing) {
        // The io thread saw a read error mid-write and deferred the
        // teardown to us: wake it so the conn is reaped promptly.
        *wake = true;
      }
    }
    return TPT_OK;
  }

  // io thread only.  Caller must NOT hold mu.  If an inline writer owns
  // the socket right now, the conn is only MARKED closing — closing the
  // fd / freeing the Conn under a concurrent writev would be a
  // use-after-free; a later flush_all pass (the writer wakes us) reaps
  // it once the writer is out.
  void destroy(Conn* c) {
    {
      std::lock_guard<std::mutex> g(mu);
      if (c->inline_writing) {
        c->closing = true;
        return;
      }
      conns.erase(c->tag);
    }
    epoll_ctl(epfd, EPOLL_CTL_DEL, c->fd, nullptr);
    close(c->fd);
    delete c;
  }

  // Swap out every non-empty write queue under mu, then flush with the
  // lock released (one writev per conn per pass).  Returns conns that
  // died during the flush.
  std::vector<Conn*> flush_all() {
    std::vector<std::pair<Conn*, std::deque<Buf>>> work;
    std::vector<Conn*> dead;
    {
      std::lock_guard<std::mutex> g(mu);
      for (auto& kv : conns) {
        Conn* c = kv.second;
        // Skip BEFORE the closing check: a conn marked closing while an
        // inline writer holds the socket is reaped on a later pass.
        if (c->inline_writing) continue;
        if (c->closing) { dead.push_back(c); continue; }
        if (!c->wq.empty()) {
          c->io_writing = true;
          work.emplace_back(c, std::move(c->wq));
          c->wq.clear();
        }
      }
    }
    for (auto& wc : work) {
      Conn* c = wc.first;
      bool blocked = false;
      bool ok = flush_bufs(c->fd, wc.second, &blocked);
      bool changed = false;
      {
        std::lock_guard<std::mutex> g(mu);
        c->io_writing = false;
        if (ok && !wc.second.empty()) {
          // Unsent remainder goes back to the FRONT (frames enqueued by
          // Python while we were flushing must stay behind it).
          for (auto it = wc.second.rbegin(); it != wc.second.rend(); ++it)
            c->wq.push_front(std::move(*it));
        }
        if (ok) {
          changed = (c->want_write != blocked);
          c->want_write = blocked;   // under mu: inline writers read it
        }
      }
      if (!ok) {
        dead.push_back(c);
        continue;
      }
      if (changed) rearm(c);
    }
    return dead;
  }
};

// ---------------------------------------------------------------------------
// Client
// ---------------------------------------------------------------------------

struct Client : Endpoint {
  std::unordered_map<uint64_t, uint64_t> inflight;  // req_id -> conn tag
                                                    // (guarded by mu)
  // Spec-codec state (guarded by mu): immutable once registered, so
  // references handed out under the lock stay valid (unordered_map
  // mapped values are rehash-stable).
  std::unordered_map<uint64_t, std::vector<uint8_t>> templates;
  std::vector<uint8_t> caller_id;
  std::mutex cmu;
  std::condition_variable ccv;
  std::deque<Record> completions;
  // Completion signal consumable by an event loop (counting eventfd):
  // written once per delivered batch so a Python asyncio loop can
  // add_reader() it and drain completions with NO intermediate poller
  // thread (one fewer context switch per batch).
  int cfd = -1;

  void signal_completions() {
    ccv.notify_one();
    if (cfd >= 0) wake_fd(cfd);
  }

  void push_completion(uint64_t req_id, int32_t status, const uint8_t* p,
                       uint64_t len) {
    Record r;
    r.tag = req_id;
    r.status = status;
    if (len) r.payload.assign(p, p + len);
    {
      std::lock_guard<std::mutex> g(cmu);
      completions.push_back(std::move(r));
    }
    signal_completions();
  }

  // io thread only, mu NOT held.
  void fail_conn(Conn* c) {
    std::vector<uint64_t> dead_reqs;
    {
      std::lock_guard<std::mutex> g(mu);
      for (auto& kv : inflight)
        if (kv.second == c->tag) dead_reqs.push_back(kv.first);
      for (uint64_t rid : dead_reqs) inflight.erase(rid);
    }
    for (uint64_t rid : dead_reqs)
      push_completion(rid, TPT_ECONN, nullptr, 0);
    destroy(c);
  }

  void loop() {
    epoll_event evs[64];
    while (!stop.load()) {
      int n = epoll_wait(epfd, evs, 64, 200);
      if (n < 0) {
        if (errno == EINTR) continue;
        break;
      }
      for (int i = 0; i < n; i++) {
        uint64_t tag = evs[i].data.u64;
        if (tag == 0) {
          uint64_t v;
          while (read(wakefd, &v, 8) == 8) {}
          wake_pending.store(false);
          continue;
        }
        Conn* c;
        {
          std::lock_guard<std::mutex> g(mu);
          auto it = conns.find(tag);
          if (it == conns.end()) continue;
          c = it->second;
        }
        bool ok = !(evs[i].events & (EPOLLHUP | EPOLLERR));
        std::vector<Record> got;
        if (ok && (evs[i].events & EPOLLIN)) {
          ok = read_avail(c);
          if (ok)
            ok = drain_frames(c, [&](uint64_t rid, const uint8_t* p,
                                     uint64_t len) {
              Record r;
              r.tag = rid;
              r.payload.assign(p, p + len);
              got.push_back(std::move(r));
            });
        }
        if (!got.empty()) {
          {
            std::lock_guard<std::mutex> g(mu);
            for (auto& r : got) inflight.erase(r.tag);
          }
          {
            std::lock_guard<std::mutex> g(cmu);
            for (auto& r : got) completions.push_back(std::move(r));
          }
          signal_completions();
        }
        if (!ok) fail_conn(c);
      }
      for (Conn* c : flush_all()) fail_conn(c);
    }
  }
};

// ---------------------------------------------------------------------------
// Server
// ---------------------------------------------------------------------------

struct Server : Endpoint {
  int lfd = -1;
  int port = 0;

  std::mutex tmu;
  std::condition_variable tcv;
  std::deque<Record> tasks;

  void loop() {
    epoll_event evs[64];
    while (!stop.load()) {
      int n = epoll_wait(epfd, evs, 64, 200);
      if (n < 0) {
        if (errno == EINTR) continue;
        break;
      }
      for (int i = 0; i < n; i++) {
        uint64_t tag = evs[i].data.u64;
        if (tag == 0) {
          uint64_t v;
          while (read(wakefd, &v, 8) == 8) {}
          wake_pending.store(false);
          continue;
        }
        if (tag == 1) {
          for (;;) {
            int fd = accept4(lfd, nullptr, nullptr, SOCK_NONBLOCK);
            if (fd < 0) break;
            int one = 1;
            setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
            Conn* c = new Conn;
            c->fd = fd;
            {
              std::lock_guard<std::mutex> g(mu);
              c->tag = next_tag++;
              conns[c->tag] = c;
            }
            epoll_event ev{};
            ev.events = EPOLLIN;
            ev.data.u64 = c->tag;
            epoll_ctl(epfd, EPOLL_CTL_ADD, fd, &ev);
          }
          continue;
        }
        Conn* c;
        {
          std::lock_guard<std::mutex> g(mu);
          auto it = conns.find(tag);
          if (it == conns.end()) continue;
          c = it->second;
        }
        bool ok = !(evs[i].events & (EPOLLHUP | EPOLLERR));
        bool any = false;
        if (ok && (evs[i].events & EPOLLIN)) {
          ok = read_avail(c);
          if (ok) {
            std::lock_guard<std::mutex> tg(tmu);
            ok = drain_frames(c, [&](uint64_t rid, const uint8_t* p,
                                     uint64_t len) {
              Record r;
              r.tag = c->tag;
              r.req_id = rid;
              r.payload.assign(p, p + len);
              tasks.push_back(std::move(r));
              any = true;
            });
          }
        }
        if (any) tcv.notify_all();
        if (!ok) destroy(c);
      }
      for (Conn* c : flush_all()) destroy(c);
    }
  }
};

}  // namespace

// ---------------------------------------------------------------------------
// TaskSpec codec (the native encode half of the §2.1 hot path).
//
// Reference parity: src/ray/common/task/task_spec.h — the reference's
// TaskSpecBuilder produces the TaskSpec protobuf in C++; submission never
// serializes through Python.  Here Python registers a per-(fn, options)
// "template": the serialized constant fields of a TaskSpecP
// (protocol/raytpu.proto).  Per task it packs a flat binary descriptor
// (ids + args + seq) and the library splices template + varying fields
// into PushTaskRequest wire bytes — proto3 fields may appear in any
// order, so appending the varying fields after the template is a valid
// encoding.  One library call frames a whole dispatch burst.
//
// Packed descriptor stream (little-endian), one record per task:
//   u64 req_id | u64 tpl_id | u64 seq_no | u64 wire_seq
//   u8 tid_len | tid | u8 flags(bit0: trace present)
//   [u32 trace_len | trace]
//   u16 n_args, then per arg:
//     u8 kind (0 inline pickle5, 1 ref, 2 inline raw)
//     u16 name_len | name            (>0 marks a kwargs entry)
//     kind 0/2: u32 data_len | data | u32 meta_len | meta
//     kind 1:   u8 id_len | id | u16 owner_len | owner
// ---------------------------------------------------------------------------

namespace {

// Proto field tags (raytpu.proto): TaskSpecP{task_id=1, args=5, kwargs=6,
// seq_no=15, trace_ctx=23}; TaskArgP{id=1, value=2, owner_address=3};
// InlineValueP{data=1, metadata=2, codec=3}; PushTaskRequest{spec=1,
// caller_id=2, wire_seq=3}; map entry{key=1, value=2}.

size_t vlen(uint64_t v) {
  size_t n = 1;
  while (v >= 128) { v >>= 7; n++; }
  return n;
}

uint64_t zigzag(int64_t v) {
  return (uint64_t(v) << 1) ^ uint64_t(v >> 63);
}

void put_varint(std::vector<uint8_t>& o, uint64_t v) {
  while (v >= 128) { o.push_back(uint8_t(v) | 0x80); v >>= 7; }
  o.push_back(uint8_t(v));
}

void put_tag(std::vector<uint8_t>& o, uint32_t field, uint32_t wt) {
  put_varint(o, (uint64_t(field) << 3) | wt);
}

void put_bytes_field(std::vector<uint8_t>& o, uint32_t field,
                     const uint8_t* p, uint64_t n) {
  put_tag(o, field, 2);
  put_varint(o, n);
  o.insert(o.end(), p, p + n);
}

struct SpecReader {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;

  template <typename T>
  T num() {
    if (!ok || size_t(end - p) < sizeof(T)) { ok = false; return T(0); }
    T v;
    memcpy(&v, p, sizeof(T));
    p += sizeof(T);
    return v;
  }
  const uint8_t* blob(uint64_t n) {
    if (!ok || uint64_t(end - p) < n) { ok = false; return nullptr; }
    const uint8_t* r = p;
    p += n;
    return r;
  }
};

struct ArgView {
  uint8_t kind;
  const uint8_t* name; uint16_t name_len;
  const uint8_t* a; uint64_t alen;    // data / id
  const uint8_t* b; uint64_t blen;    // metadata / owner
};

constexpr const char kPickle5[] = "pickle5";
constexpr const char kRaw[] = "raw";

// Serialized size of one TaskArgP body for `v`.
uint64_t arg_body_len(const ArgView& v) {
  if (v.kind == 1)
    return 1 + vlen(v.alen) + v.alen + 1 + vlen(v.blen) + v.blen;
  uint64_t clen = (v.kind == 2) ? 3 : 7;
  uint64_t iv = 1 + vlen(v.alen) + v.alen + 1 + vlen(clen) + clen;
  if (v.blen) iv += 1 + vlen(v.blen) + v.blen;
  return 1 + vlen(iv) + iv;
}

void put_arg_body(std::vector<uint8_t>& o, const ArgView& v) {
  if (v.kind == 1) {
    put_bytes_field(o, 1, v.a, v.alen);            // TaskArgP.id
    put_bytes_field(o, 3, v.b, v.blen);            // TaskArgP.owner_address
    return;
  }
  const char* codec = (v.kind == 2) ? kRaw : kPickle5;
  uint64_t clen = (v.kind == 2) ? 3 : 7;
  uint64_t iv = 1 + vlen(v.alen) + v.alen + 1 + vlen(clen) + clen;
  if (v.blen) iv += 1 + vlen(v.blen) + v.blen;
  put_tag(o, 2, 2);                                // TaskArgP.value
  put_varint(o, iv);
  put_bytes_field(o, 1, v.a, v.alen);              // InlineValueP.data
  if (v.blen) put_bytes_field(o, 2, v.b, v.blen);  // InlineValueP.metadata
  put_bytes_field(o, 3, reinterpret_cast<const uint8_t*>(codec), clen);
}

}  // namespace

// ---------------------------------------------------------------------------
// C API
// ---------------------------------------------------------------------------

extern "C" {

int tpt_client_new(void** out) {
  Client* c = new Client;
  c->epfd = epoll_create1(0);
  c->wakefd = eventfd(0, EFD_NONBLOCK);
  c->cfd = eventfd(0, EFD_NONBLOCK);
  if (c->epfd < 0 || c->wakefd < 0 || c->cfd < 0) { delete c; return TPT_ESYS; }
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.u64 = 0;
  epoll_ctl(c->epfd, EPOLL_CTL_ADD, c->wakefd, &ev);
  c->io = std::thread([c] { c->loop(); });
  *out = c;
  return TPT_OK;
}

int tpt_connect(void* h, const char* host, int port, uint64_t* out_tag) {
  Client* cl = static_cast<Client*>(h);
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return TPT_ESYS;
  sockaddr_in sa{};
  sa.sin_family = AF_INET;
  sa.sin_port = htons(uint16_t(port));
  if (inet_pton(AF_INET, host, &sa.sin_addr) != 1) { close(fd); return TPT_EARG; }
  if (connect(fd, reinterpret_cast<sockaddr*>(&sa), sizeof sa) != 0) {
    close(fd);
    return TPT_ECONN;
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
  Conn* c = new Conn;
  c->fd = fd;
  {
    std::lock_guard<std::mutex> g(cl->mu);
    c->tag = cl->next_tag++;
    cl->conns[c->tag] = c;
  }
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.u64 = c->tag;
  epoll_ctl(cl->epfd, EPOLL_CTL_ADD, fd, &ev);
  *out_tag = c->tag;
  return TPT_OK;
}

int tpt_send(void* h, uint64_t conn_tag, uint64_t req_id,
             const uint8_t* payload, uint64_t len) {
  Client* cl = static_cast<Client*>(h);
  {
    std::lock_guard<std::mutex> g(cl->mu);
    auto it = cl->conns.find(conn_tag);
    if (it == cl->conns.end() || it->second->closing) return TPT_ECONN;
    Conn* c = it->second;
    Buf b;
    frame_into(b.data, req_id, payload, len);
    c->wq.push_back(std::move(b));
    cl->inflight[req_id] = conn_tag;
  }
  if (!cl->wake_pending.exchange(true)) wake_fd(cl->wakefd);
  return TPT_OK;
}

int tpt_send_raw(void* h, uint64_t conn_tag, const uint8_t* framed,
                 uint64_t len) {
  // Batched submission: `framed` is a concatenation of already-framed
  // requests (u32 frame_len | u64 req_id | payload), built by Python so a
  // whole dispatch burst costs ONE library call, one queue append and one
  // wakeup.  Frames are walked (no copy beyond the single buffer append)
  // to register req_ids for fail_conn's in-flight accounting.
  Client* cl = static_cast<Client*>(h);
  // Validate the whole buffer BEFORE mutating any state: a malformed
  // later frame must not leave earlier req_ids registered in-flight for
  // a batch that was never enqueued.
  {
    uint64_t off = 0;
    while (off + 12 <= len) {
      uint32_t flen;
      memcpy(&flen, framed + off, 4);
      if (flen < 8 || off + 4 + flen > len) return TPT_EARG;
      off += 4 + flen;
    }
    if (off != len) return TPT_EARG;
  }
  {
    std::lock_guard<std::mutex> g(cl->mu);
    auto it = cl->conns.find(conn_tag);
    if (it == cl->conns.end() || it->second->closing) return TPT_ECONN;
    Conn* c = it->second;
    uint64_t off = 0;
    while (off + 12 <= len) {
      uint32_t flen;
      memcpy(&flen, framed + off, 4);
      uint64_t req_id;
      memcpy(&req_id, framed + off + 4, 8);
      cl->inflight[req_id] = conn_tag;
      off += 4 + flen;
    }
    Buf b;
    b.data.assign(framed, framed + len);
    c->wq.push_back(std::move(b));
  }
  if (!cl->wake_pending.exchange(true)) wake_fd(cl->wakefd);
  return TPT_OK;
}

int tpt_completion_fd(void* h) {
  return static_cast<Client*>(h)->cfd;
}

int tpt_set_caller(void* h, const uint8_t* data, uint64_t len) {
  Client* cl = static_cast<Client*>(h);
  std::lock_guard<std::mutex> g(cl->mu);
  cl->caller_id.assign(data, data + len);
  return TPT_OK;
}

int tpt_register_template(void* h, uint64_t tpl_id, const uint8_t* data,
                          uint64_t len) {
  Client* cl = static_cast<Client*>(h);
  std::lock_guard<std::mutex> g(cl->mu);
  cl->templates[tpl_id].assign(data, data + len);
  return TPT_OK;
}

int tpt_send_specs(void* h, uint64_t conn_tag, const uint8_t* packed,
                   uint64_t len) {
  // Encode a burst of task descriptors into PushTaskRequest frames and
  // enqueue them in ONE buffer append + one io wakeup.  Validate-then-
  // commit like tpt_send_raw: a malformed later record must not leave
  // earlier req_ids registered for frames never sent.
  Client* cl = static_cast<Client*>(h);

  struct Rec {
    uint64_t req_id, seq_no;
    int64_t wire_seq;
    const uint8_t* tid; uint8_t tid_len;
    const uint8_t* trace; uint64_t trace_len;
    const std::vector<uint8_t>* tpl;
    size_t arg_begin, arg_end;     // into `args`
    uint64_t spec_len, body_len;
  };
  std::vector<Rec> recs;
  std::vector<ArgView> args;
  uint64_t caller_len;
  uint64_t total = 0;
  {
    std::lock_guard<std::mutex> g(cl->mu);
    caller_len = cl->caller_id.size();
    SpecReader r{packed, packed + len};
    while (r.ok && r.p < r.end) {
      Rec rec{};
      rec.req_id = r.num<uint64_t>();
      uint64_t tpl_id = r.num<uint64_t>();
      rec.seq_no = r.num<uint64_t>();
      rec.wire_seq = r.num<int64_t>();
      rec.tid_len = r.num<uint8_t>();
      rec.tid = r.blob(rec.tid_len);
      uint8_t flags = r.num<uint8_t>();
      if (flags & 1) {
        rec.trace_len = r.num<uint32_t>();
        rec.trace = r.blob(rec.trace_len);
      }
      uint16_t n_args = r.num<uint16_t>();
      rec.arg_begin = args.size();
      for (uint16_t i = 0; r.ok && i < n_args; i++) {
        ArgView v{};
        v.kind = r.num<uint8_t>();
        v.name_len = r.num<uint16_t>();
        v.name = r.blob(v.name_len);
        if (v.kind == 1) {
          v.alen = r.num<uint8_t>();
          v.a = r.blob(v.alen);
          v.blen = r.num<uint16_t>();
          v.b = r.blob(v.blen);
        } else if (v.kind == 0 || v.kind == 2) {
          v.alen = r.num<uint32_t>();
          v.a = r.blob(v.alen);
          v.blen = r.num<uint32_t>();
          v.b = r.blob(v.blen);
        } else {
          r.ok = false;
        }
        args.push_back(v);
      }
      rec.arg_end = args.size();
      if (!r.ok) break;
      auto it = cl->templates.find(tpl_id);
      if (it == cl->templates.end()) return TPT_EARG;
      rec.tpl = &it->second;

      uint64_t spec = rec.tpl->size();
      spec += 1 + vlen(rec.tid_len) + rec.tid_len;          // task_id (1)
      for (size_t a = rec.arg_begin; a < rec.arg_end; a++) {
        const ArgView& v = args[a];
        uint64_t body = arg_body_len(v);
        if (v.name_len) {                                   // kwargs (6)
          uint64_t entry = 1 + vlen(v.name_len) + v.name_len
                         + 1 + vlen(body) + body;
          spec += 1 + vlen(entry) + entry;
        } else {                                            // args (5)
          spec += 1 + vlen(body) + body;
        }
      }
      if (rec.seq_no) spec += 1 + vlen(rec.seq_no);         // seq_no (15)
      if (rec.trace_len)
        spec += 2 + vlen(rec.trace_len) + rec.trace_len;    // trace_ctx (23)
      rec.spec_len = spec;

      uint64_t body = 1 + vlen(spec) + spec;                // spec (1)
      if (caller_len) body += 1 + vlen(caller_len) + caller_len;
      if (rec.wire_seq)                                     // wire_seq (3)
        body += 1 + vlen(zigzag(rec.wire_seq));
      rec.body_len = body;
      total += 4 + 8 + body;                                // frame hdr
      recs.push_back(rec);
    }
    if (!r.ok || r.p != r.end) return TPT_EARG;
  }
  if (recs.empty()) return TPT_OK;

  Buf out;
  out.data.reserve(total);
  std::vector<uint8_t>& o = out.data;
  {
    // caller_id is only mutated before the first send; read without the
    // lock is safe for the lifetime of this call (same for templates).
    for (const Rec& rec : recs) {
      uint32_t flen = uint32_t(8 + rec.body_len);
      o.insert(o.end(), reinterpret_cast<uint8_t*>(&flen),
               reinterpret_cast<uint8_t*>(&flen) + 4);
      o.insert(o.end(), reinterpret_cast<const uint8_t*>(&rec.req_id),
               reinterpret_cast<const uint8_t*>(&rec.req_id) + 8);
      put_tag(o, 1, 2);                                     // spec
      put_varint(o, rec.spec_len);
      o.insert(o.end(), rec.tpl->begin(), rec.tpl->end());
      put_bytes_field(o, 1, rec.tid, rec.tid_len);
      for (size_t a = rec.arg_begin; a < rec.arg_end; a++) {
        const ArgView& v = args[a];
        uint64_t body = arg_body_len(v);
        if (v.name_len) {
          uint64_t entry = 1 + vlen(v.name_len) + v.name_len
                         + 1 + vlen(body) + body;
          put_tag(o, 6, 2);
          put_varint(o, entry);
          put_bytes_field(o, 1, v.name, v.name_len);
          put_tag(o, 2, 2);
          put_varint(o, body);
          put_arg_body(o, v);
        } else {
          put_tag(o, 5, 2);
          put_varint(o, body);
          put_arg_body(o, v);
        }
      }
      if (rec.seq_no) { put_tag(o, 15, 0); put_varint(o, rec.seq_no); }
      if (rec.trace_len) put_bytes_field(o, 23, rec.trace, rec.trace_len);
      if (caller_len)
        put_bytes_field(o, 2, cl->caller_id.data(), caller_len);
      if (rec.wire_seq) {
        put_tag(o, 3, 0);
        put_varint(o, zigzag(rec.wire_seq));
      }
    }
  }
  {
    // Register in-flight BEFORE the frame can hit the wire: a reply that
    // raced an inline write would otherwise leave a stale entry.
    std::lock_guard<std::mutex> g(cl->mu);
    auto it = cl->conns.find(conn_tag);
    if (it == cl->conns.end() || it->second->closing) return TPT_ECONN;
    for (const Rec& rec : recs) cl->inflight[rec.req_id] = conn_tag;
  }
  bool wake = false;
  int rc = cl->enqueue_or_write(conn_tag, std::move(out), &wake);
  if (rc != TPT_OK) {
    std::lock_guard<std::mutex> g(cl->mu);
    for (const Rec& rec : recs) cl->inflight.erase(rec.req_id);
    return rc;
  }
  if (wake && !cl->wake_pending.exchange(true)) wake_fd(cl->wakefd);
  return TPT_OK;
}

int tpt_close_conn(void* h, uint64_t conn_tag) {
  Client* cl = static_cast<Client*>(h);
  {
    std::lock_guard<std::mutex> g(cl->mu);
    auto it = cl->conns.find(conn_tag);
    if (it == cl->conns.end()) return TPT_ECONN;
    it->second->closing = true;
  }
  if (!cl->wake_pending.exchange(true)) wake_fd(cl->wakefd);
  return TPT_OK;
}

int tpt_poll(void* h, uint8_t* buf, uint64_t cap, uint64_t* used,
             int timeout_ms) {
  Client* cl = static_cast<Client*>(h);
  std::unique_lock<std::mutex> g(cl->cmu);
  if (cl->completions.empty()) {
    cl->ccv.wait_for(g, std::chrono::milliseconds(timeout_ms),
                     [&] { return !cl->completions.empty()
                                  || cl->stop.load(); });
  }
  int n = int(pack_records(cl->completions, buf, cap, used));
  if (n == 0 && !cl->completions.empty()) {
    // Head record alone exceeds `cap`: without this signal it would sit
    // at the queue head forever, wedging every later completion.
    *used = 28 + cl->completions.front().payload.size();
    return TPT_EBUF;
  }
  return n;
}

void tpt_client_close(void* h) {
  Client* cl = static_cast<Client*>(h);
  cl->stop.store(true);
  wake_fd(cl->wakefd);
  cl->ccv.notify_all();
  if (cl->io.joinable()) cl->io.join();
  {
    std::lock_guard<std::mutex> g(cl->mu);
    for (auto& kv : cl->conns) {
      close(kv.second->fd);
      delete kv.second;
    }
    cl->conns.clear();
  }
  close(cl->epfd);
  close(cl->wakefd);
  if (cl->cfd >= 0) close(cl->cfd);
  delete cl;
}

int tpt_server_new(const char* host, int port, void** out, int* bound_port) {
  Server* s = new Server;
  s->epfd = epoll_create1(0);
  s->wakefd = eventfd(0, EFD_NONBLOCK);
  s->lfd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
  if (s->epfd < 0 || s->wakefd < 0 || s->lfd < 0) { delete s; return TPT_ESYS; }
  int one = 1;
  setsockopt(s->lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  sockaddr_in sa{};
  sa.sin_family = AF_INET;
  sa.sin_port = htons(uint16_t(port));
  if (inet_pton(AF_INET, host, &sa.sin_addr) != 1) { delete s; return TPT_EARG; }
  if (bind(s->lfd, reinterpret_cast<sockaddr*>(&sa), sizeof sa) != 0 ||
      listen(s->lfd, 512) != 0) {
    close(s->lfd);
    delete s;
    return TPT_ESYS;
  }
  socklen_t slen = sizeof sa;
  getsockname(s->lfd, reinterpret_cast<sockaddr*>(&sa), &slen);
  s->port = ntohs(sa.sin_port);
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.u64 = 0;
  epoll_ctl(s->epfd, EPOLL_CTL_ADD, s->wakefd, &ev);
  epoll_event lv{};
  lv.events = EPOLLIN;
  lv.data.u64 = 1;
  epoll_ctl(s->epfd, EPOLL_CTL_ADD, s->lfd, &lv);
  s->io = std::thread([s] { s->loop(); });
  *out = s;
  *bound_port = s->port;
  return TPT_OK;
}

int tpt_server_pop(void* h, uint8_t* buf, uint64_t cap, uint64_t* used,
                   int timeout_ms) {
  Server* s = static_cast<Server*>(h);
  std::unique_lock<std::mutex> g(s->tmu);
  if (s->tasks.empty()) {
    s->tcv.wait_for(g, std::chrono::milliseconds(timeout_ms),
                    [&] { return !s->tasks.empty() || s->stop.load(); });
  }
  int n = int(pack_records(s->tasks, buf, cap, used));
  if (n == 0 && !s->tasks.empty()) {
    *used = 28 + s->tasks.front().payload.size();
    return TPT_EBUF;
  }
  return n;
}

int tpt_server_reply(void* h, uint64_t conn_tag, uint64_t req_id,
                     const uint8_t* payload, uint64_t len) {
  Server* s = static_cast<Server*>(h);
  {
    std::lock_guard<std::mutex> g(s->mu);
    auto it = s->conns.find(conn_tag);
    if (it == s->conns.end() || it->second->closing)
      return TPT_ECONN;  // caller gone; drop
    Conn* c = it->second;
    Buf b;
    frame_into(b.data, req_id, payload, len);
    c->wq.push_back(std::move(b));
  }
  if (!s->wake_pending.exchange(true)) wake_fd(s->wakefd);
  return TPT_OK;
}

int tpt_server_reply_raw(void* h, uint64_t conn_tag, const uint8_t* framed,
                         uint64_t len) {
  // Batched replies: one library call for every reply produced by an
  // execution batch, written inline by the executor thread when the
  // connection is idle (eventfd wake + io-thread handoff costs a context
  // switch per batch on small hosts).
  Server* s = static_cast<Server*>(h);
  Buf b;
  b.data.assign(framed, framed + len);
  bool wake = false;
  int rc = s->enqueue_or_write(conn_tag, std::move(b), &wake);
  if (rc != TPT_OK) return rc;
  if (wake && !s->wake_pending.exchange(true)) wake_fd(s->wakefd);
  return TPT_OK;
}

void tpt_server_close(void* h) {
  Server* s = static_cast<Server*>(h);
  s->stop.store(true);
  wake_fd(s->wakefd);
  s->tcv.notify_all();
  if (s->io.joinable()) s->io.join();
  {
    std::lock_guard<std::mutex> g(s->mu);
    for (auto& kv : s->conns) {
      close(kv.second->fd);
      delete kv.second;
    }
    s->conns.clear();
  }
  close(s->lfd);
  close(s->epfd);
  close(s->wakefd);
  delete s;
}

}  // extern "C"
