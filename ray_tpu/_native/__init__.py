"""Native components, built lazily with g++ on first use.

The shared library is rebuilt whenever the source is newer than the binary,
so a fresh checkout works without a separate build step.
"""

from __future__ import annotations

import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()

_LIBS = {
    "tpustore": ["objstore.cc"],
    # Transfer plane links the store's C API into the same .so; its
    # handles attach to the same /dev/shm segment independently.
    "tpuxfer": ["objstore.cc", "objtransfer.cc"],
    # Task-submission hot path (framed TCP client/server, batched
    # completion delivery) — see taskrpc.cc.
    "tpttask": ["taskrpc.cc"],
}


def lib_path(name: str) -> str:
    """Return the path to lib<name>.so, compiling it if missing/stale."""
    sources = _LIBS[name]
    so = os.path.join(_DIR, f"lib{name}.so")
    srcs = [os.path.join(_DIR, s) for s in sources]
    with _LOCK:
        if not os.path.exists(so) or any(
            os.path.getmtime(s) > os.path.getmtime(so) for s in srcs
        ):
            tmp = so + f".tmp.{os.getpid()}"
            cmd = ["g++", "-std=c++17", "-O2", "-fPIC", "-shared", "-pthread",
                   *srcs, "-o", tmp]
            proc = subprocess.run(cmd, capture_output=True, text=True)
            if proc.returncode != 0:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise RuntimeError(
                    f"native build failed: {' '.join(cmd)}\n{proc.stderr}")
            os.replace(tmp, so)  # atomic: concurrent builders race safely
    return so
