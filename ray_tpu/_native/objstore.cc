// Shared-memory object store ("tpustore").
//
// TPU-native equivalent of the reference's Plasma store
// (/root/reference/src/ray/object_manager/plasma/{store.cc,client.cc,dlmalloc.cc}).
// Design differences from Plasma, chosen for the TPU runtime:
//
//  * Plasma is a server: clients speak a flatbuffer protocol over a unix
//    socket and receive fds to mmap (fling.cc).  Here the WHOLE store state
//    (object table + allocator + client registry + locks) lives inside one
//    shared-memory segment, so create/seal/get/release are plain function
//    calls guarded by a process-shared robust mutex — no IPC round-trip on
//    the hot path.  On a TPU host every worker feeds the same chips; the
//    store's job is to hand zero-copy host buffers to jax.device_put as
//    fast as possible.
//
//  * Plasma tracks per-client references in the server and releases them on
//    disconnect.  Here each attached client claims a slot in a shared client
//    registry and records its refs there; when an allocation fails, a
//    reclaim pass drops the refs (and unsealed creations) of clients whose
//    pid no longer exists, so crashed workers cannot leak pinned capacity.
//
//  * Eviction is LRU over sealed, unreferenced objects, like Plasma's
//    eviction_policy.h, but runs inline in the allocating client.
//
//  * Object IDs are 28 bytes (TaskID(24) + return index(4)), matching the
//    Python layer's lineage-embedded IDs (ray_tpu/_private/ids.py).
//
// Build: g++ -O2 -fPIC -shared -pthread objstore.cc -o libtpustore.so

#include <cerrno>
#include <csignal>
#include <cstdint>
#include <cstring>
#include <ctime>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

// Format version 1: header carries max_clients (bump on layout change).
constexpr uint64_t kMagic = 0x545055535452314bULL;  // "TPUSTR1K"
constexpr uint32_t kIdSize = 28;
constexpr uint64_t kAlign = 64;  // payload alignment: cacheline, XLA-friendly
constexpr uint64_t kBlockHeader = 64;
constexpr uint32_t kMaxClients = 128;  // worker procs + transfer clients
constexpr uint32_t kRefsPerClient = 4096;  // open-addressed, so keep <70% full

// ---- error codes (mirrored in ray_tpu/_private/object_store.py) ----
enum {
  TPUS_OK = 0,
  TPUS_EXISTS = -1,
  TPUS_NOT_FOUND = -2,
  TPUS_OOM = -3,
  TPUS_TIMEOUT = -4,
  TPUS_BAD_STATE = -5,
  TPUS_SYS = -6,
};

enum ObjState : uint32_t {
  SLOT_EMPTY = 0,
  OBJ_CREATED = 1,
  OBJ_SEALED = 2,
  SLOT_TOMBSTONE = 3,  // deleted slot, keeps probe chains intact
};

struct Slot {
  uint8_t id[kIdSize];
  uint32_t state;
  int32_t refcount;
  uint32_t delete_pending;  // delete requested while refcount > 0
  uint32_t creator_client;  // registry index of the creating client + 1
  uint64_t generation;      // bumped on every reuse of this slot
  uint64_t data_off;        // offset of payload from segment base
  uint64_t data_size;       // user data bytes
  uint64_t meta_size;       // metadata bytes (stored right after data)
  uint64_t lru_tick;
};

// One client's record of a pinned object (open-addressed by slot index).
struct RefEnt {
  uint32_t used;
  uint32_t slot_idx;
  uint64_t generation;
  int64_t count;
};

struct ClientSlot {
  int32_t pid;      // 0 = free
  uint32_t nrefs;   // used RefEnt entries
  RefEnt refs[kRefsPerClient];
};

// Heap block header (boundary-tag allocator, first fit, coalescing).
struct Block {
  uint64_t size;       // total block size including this header
  uint64_t prev_size;  // size of the physically preceding block (0 if first)
  uint32_t free_;      // 1 if on the free list
  uint32_t pad_;
  uint64_t next_free;  // free-list links: heap offsets biased by +1 (0=null)
  uint64_t prev_free;
};

struct Header {
  uint64_t magic;
  uint64_t total_size;
  uint64_t table_off;
  uint32_t max_objects;
  uint32_t eviction_off;  // 1 = LRU eviction disabled
  uint32_t max_clients;   // client-slot capacity fixed at create time
  uint32_t pad_;
  uint64_t clients_off;
  uint64_t heap_off;
  uint64_t heap_size;
  pthread_mutex_t lock;
  pthread_cond_t seal_cv;
  uint64_t lru_tick;
  uint64_t generation;
  uint64_t bytes_in_use;   // payload bytes of live objects
  uint64_t num_objects;    // live (created+sealed) objects
  uint64_t num_evictions;
  uint64_t num_reclaims;   // dead clients reclaimed
  uint64_t free_head;      // biased offset (+1) of first free block
  uint64_t ready_seq;      // bumped on every seal, for cheap wakeup checks
};

struct Handle {
  uint8_t* base;
  uint64_t map_size;
  Header* hdr;
  int32_t client_idx;  // -1 if registry was full (untracked legacy mode)
};

inline Slot* table(Handle* h) {
  return reinterpret_cast<Slot*>(h->base + h->hdr->table_off);
}

inline ClientSlot* clients(Handle* h) {
  return reinterpret_cast<ClientSlot*>(h->base + h->hdr->clients_off);
}

inline Block* block_at(Handle* h, uint64_t heap_rel) {
  return reinterpret_cast<Block*>(h->base + h->hdr->heap_off + heap_rel);
}

inline uint64_t heap_rel_of(Handle* h, Block* b) {
  return reinterpret_cast<uint8_t*>(b) - (h->base + h->hdr->heap_off);
}

// ---------- locking (robust, process shared) ----------

void recover_lock(Handle* h) {
  // Previous owner died mid-critical-section.  All mutations are small and
  // ordered so the structures stay structurally valid; any leaked refs or
  // unsealed objects are swept by reclaim_dead_clients().
  pthread_mutex_consistent(&h->hdr->lock);
}

int lock_store(Handle* h) {
  int rc = pthread_mutex_lock(&h->hdr->lock);
  if (rc == EOWNERDEAD) {
    recover_lock(h);
    return 0;
  }
  return rc;
}

void unlock_store(Handle* h) { pthread_mutex_unlock(&h->hdr->lock); }

// ---------- hash table ----------

uint64_t id_hash(const uint8_t* id) {
  uint64_t x = 1469598103934665603ULL;  // FNV-1a
  for (uint32_t i = 0; i < kIdSize; i++) {
    x ^= id[i];
    x *= 1099511628211ULL;
  }
  return x;
}

Slot* find_slot(Handle* h, const uint8_t* id) {
  Slot* t = table(h);
  uint32_t n = h->hdr->max_objects;
  uint64_t i = id_hash(id) % n;
  for (uint32_t probes = 0; probes < n; probes++) {
    Slot* s = &t[(i + probes) % n];
    if (s->state == SLOT_EMPTY) return nullptr;
    if (s->state != SLOT_TOMBSTONE && memcmp(s->id, id, kIdSize) == 0) return s;
  }
  return nullptr;
}

Slot* insert_slot(Handle* h, const uint8_t* id) {
  Slot* t = table(h);
  uint32_t n = h->hdr->max_objects;
  uint64_t i = id_hash(id) % n;
  Slot* first_tomb = nullptr;
  for (uint32_t probes = 0; probes < n; probes++) {
    Slot* s = &t[(i + probes) % n];
    if (s->state == SLOT_EMPTY) return first_tomb ? first_tomb : s;
    if (s->state == SLOT_TOMBSTONE && !first_tomb) first_tomb = s;
  }
  return first_tomb;
}

// ---------- per-client ref registry ----------

RefEnt* ref_find(ClientSlot* c, uint32_t slot_idx, uint64_t gen, bool insert) {
  uint64_t i = (uint64_t(slot_idx) * 2654435761u) % kRefsPerClient;
  RefEnt* first_free = nullptr;
  for (uint32_t p = 0; p < kRefsPerClient; p++) {
    RefEnt* e = &c->refs[(i + p) % kRefsPerClient];
    if (e->used && e->slot_idx == slot_idx && e->generation == gen) return e;
    if (!e->used && !first_free) {
      first_free = e;
      if (!insert) return nullptr;  // free slot ends the probe chain
    }
  }
  if (insert && first_free) return first_free;
  return nullptr;
}

void client_track(Handle* h, Slot* s, int64_t delta) {
  if (h->client_idx < 0) return;
  ClientSlot* c = &clients(h)[h->client_idx];
  uint32_t idx = uint32_t(s - table(h));
  RefEnt* e = ref_find(c, idx, s->generation, delta > 0);
  if (!e) return;  // registry full or already gone: degrade to untracked
  if (!e->used) {
    e->used = 1;
    e->slot_idx = idx;
    e->generation = s->generation;
    e->count = 0;
    c->nrefs++;
  }
  e->count += delta;
  if (e->count <= 0) {
    e->used = 0;
    c->nrefs--;
  }
}

// ---------- allocator ----------

uint64_t align_up(uint64_t v, uint64_t a) { return (v + a - 1) & ~(a - 1); }

void freelist_push(Handle* h, Block* b) {
  b->free_ = 1;
  b->next_free = h->hdr->free_head;
  b->prev_free = 0;
  if (h->hdr->free_head) {
    block_at(h, h->hdr->free_head - 1)->prev_free = heap_rel_of(h, b) + 1;
  }
  h->hdr->free_head = heap_rel_of(h, b) + 1;
}

void freelist_remove(Handle* h, Block* b) {
  if (b->prev_free)
    block_at(h, b->prev_free - 1)->next_free = b->next_free;
  else
    h->hdr->free_head = b->next_free;
  if (b->next_free) block_at(h, b->next_free - 1)->prev_free = b->prev_free;
  b->free_ = 0;
  b->next_free = b->prev_free = 0;
}

Block* next_block(Handle* h, Block* b) {
  uint64_t rel = heap_rel_of(h, b) + b->size;
  if (rel >= h->hdr->heap_size) return nullptr;
  return block_at(h, rel);
}

Block* prev_block(Handle* h, Block* b) {
  if (b->prev_size == 0) return nullptr;
  return block_at(h, heap_rel_of(h, b) - b->prev_size);
}

uint64_t heap_alloc(Handle* h, uint64_t payload) {
  uint64_t need = align_up(payload, kAlign) + kBlockHeader;
  uint64_t cur = h->hdr->free_head;
  while (cur) {
    Block* b = block_at(h, cur - 1);
    if (b->size >= need) {
      freelist_remove(h, b);
      if (b->size - need >= kBlockHeader + kAlign) {
        uint64_t rest = b->size - need;
        b->size = need;
        Block* nb = next_block(h, b);
        nb->size = rest;
        nb->prev_size = need;
        nb->free_ = 0;
        nb->next_free = nb->prev_free = 0;
        Block* after = next_block(h, nb);
        if (after) after->prev_size = rest;
        freelist_push(h, nb);
      }
      return h->hdr->heap_off + heap_rel_of(h, b) + kBlockHeader;
    }
    cur = b->next_free;
  }
  return 0;
}

void heap_free(Handle* h, uint64_t payload_off) {
  Block* b = reinterpret_cast<Block*>(h->base + payload_off - kBlockHeader);
  Block* nb = next_block(h, b);
  if (nb && nb->free_) {
    freelist_remove(h, nb);
    b->size += nb->size;
    Block* after = next_block(h, b);
    if (after) after->prev_size = b->size;
  }
  Block* pb = prev_block(h, b);
  if (pb && pb->free_) {
    freelist_remove(h, pb);
    pb->size += b->size;
    Block* after = next_block(h, pb);
    if (after) after->prev_size = pb->size;
    b = pb;
  }
  freelist_push(h, b);
}

// Free an object's storage and clear its slot, compacting tombstones.
// Lock held.
void destroy_object(Handle* h, Slot* s) {
  if (s->data_off) heap_free(h, s->data_off);
  h->hdr->bytes_in_use -= s->data_size + s->meta_size;
  h->hdr->num_objects--;
  s->state = SLOT_TOMBSTONE;
  s->data_off = 0;
  // Linear-probing invariant: a tombstone whose successor is EMPTY is not on
  // any probe chain, so it (and any contiguous tombstones before it) can
  // revert to EMPTY.  Keeps misses O(1) under churn.
  Slot* t = table(h);
  uint32_t n = h->hdr->max_objects;
  uint32_t idx = uint32_t(s - t);
  if (t[(idx + 1) % n].state == SLOT_EMPTY) {
    uint32_t j = idx;
    for (uint32_t steps = 0; steps < n && t[j].state == SLOT_TOMBSTONE; steps++) {
      t[j].state = SLOT_EMPTY;
      j = (j + n - 1) % n;
    }
  }
}

// Evict the least-recently-used sealed unreferenced object.  Lock held.
bool evict_one(Handle* h) {
  if (h->hdr->eviction_off) return false;
  Slot* t = table(h);
  Slot* victim = nullptr;
  for (uint32_t i = 0; i < h->hdr->max_objects; i++) {
    Slot* s = &t[i];
    if (s->state == OBJ_SEALED && s->refcount == 0 &&
        (!victim || s->lru_tick < victim->lru_tick)) {
      victim = s;
    }
  }
  if (!victim) return false;
  destroy_object(h, victim);
  h->hdr->num_evictions++;
  return true;
}

// Drop refs held by clients whose pid is gone; destroy their unsealed
// creations.  Lock held.  Returns true if anything was reclaimed.
bool reclaim_dead_clients(Handle* h) {
  bool any = false;
  ClientSlot* cs = clients(h);
  for (uint32_t ci = 0; ci < h->hdr->max_clients; ci++) {
    ClientSlot* c = &cs[ci];
    if (c->pid == 0) continue;
    if (kill(c->pid, 0) == 0 || errno != ESRCH) continue;  // still alive
    for (uint32_t ri = 0; ri < kRefsPerClient && c->nrefs > 0; ri++) {
      RefEnt* e = &c->refs[ri];
      if (!e->used) continue;
      Slot* s = &table(h)[e->slot_idx];
      if (s->state != SLOT_EMPTY && s->state != SLOT_TOMBSTONE &&
          s->generation == e->generation) {
        s->refcount -= int32_t(e->count);
        if (s->refcount < 0) s->refcount = 0;
        if (s->state == OBJ_CREATED && s->creator_client == ci + 1) {
          destroy_object(h, s);  // creator died before sealing
        } else if (s->refcount == 0 && s->delete_pending) {
          destroy_object(h, s);
        }
      }
      e->used = 0;
      c->nrefs--;
      any = true;
    }
    c->pid = 0;
    h->hdr->num_reclaims++;
    any = true;
  }
  return any;
}

int32_t register_client(Handle* h) {
  ClientSlot* cs = clients(h);
  int32_t pid = int32_t(getpid());
  for (uint32_t i = 0; i < h->hdr->max_clients; i++) {
    if (cs[i].pid == 0 ||
        (kill(cs[i].pid, 0) != 0 && errno == ESRCH)) {
      memset(&cs[i], 0, sizeof(ClientSlot));
      cs[i].pid = pid;
      return int32_t(i);
    }
  }
  return -1;  // registry full: operate untracked
}

}  // namespace

extern "C" {

int tpus_create(const char* path, uint64_t heap_size, uint32_t max_objects,
                void** out) {
  heap_size = align_up(heap_size, kAlign);
  uint64_t table_off = align_up(sizeof(Header), kAlign);
  uint64_t clients_off =
      align_up(table_off + uint64_t(max_objects) * sizeof(Slot), kAlign);
  uint64_t heap_off =
      align_up(clients_off + uint64_t(kMaxClients) * sizeof(ClientSlot), 4096);
  uint64_t total = heap_off + heap_size;

  int fd = open(path, O_RDWR | O_CREAT | O_EXCL, 0600);
  if (fd < 0) return TPUS_SYS;
  if (ftruncate(fd, (off_t)total) != 0) {
    close(fd);
    unlink(path);
    return TPUS_SYS;
  }
  void* mem = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) {
    unlink(path);
    return TPUS_SYS;
  }

  Header* hdr = reinterpret_cast<Header*>(mem);
  memset(hdr, 0, sizeof(Header));
  hdr->total_size = total;
  hdr->table_off = table_off;
  hdr->max_objects = max_objects;
  hdr->max_clients = kMaxClients;
  hdr->clients_off = clients_off;
  hdr->heap_off = heap_off;
  hdr->heap_size = heap_size;

  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&hdr->lock, &ma);
  pthread_mutexattr_destroy(&ma);

  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_condattr_setclock(&ca, CLOCK_MONOTONIC);
  pthread_cond_init(&hdr->seal_cv, &ca);
  pthread_condattr_destroy(&ca);

  Handle* h = new Handle{reinterpret_cast<uint8_t*>(mem), total, hdr, -1};

  Block* b = block_at(h, 0);
  b->size = heap_size;
  b->prev_size = 0;
  b->free_ = 0;
  b->next_free = b->prev_free = 0;
  freelist_push(h, b);

  __sync_synchronize();
  hdr->magic = kMagic;  // publish: attachers spin until magic is set
  h->client_idx = register_client(h);
  *out = h;
  return TPUS_OK;
}

int tpus_attach(const char* path, void** out) {
  int fd = open(path, O_RDWR);
  if (fd < 0) return TPUS_SYS;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return TPUS_SYS;
  }
  void* mem = mmap(nullptr, st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return TPUS_SYS;
  Header* hdr = reinterpret_cast<Header*>(mem);
  for (int i = 0; i < 1000 && hdr->magic != kMagic; i++) usleep(1000);
  if (hdr->magic != kMagic) {
    munmap(mem, st.st_size);
    return TPUS_BAD_STATE;
  }
  Handle* h =
      new Handle{reinterpret_cast<uint8_t*>(mem), (uint64_t)st.st_size, hdr, -1};
  if (lock_store(h) == 0) {
    h->client_idx = register_client(h);
    unlock_store(h);
  }
  *out = h;
  return TPUS_OK;
}

void tpus_close(void* hv) {
  Handle* h = reinterpret_cast<Handle*>(hv);
  // Clean detach: drop any refs we still hold so we don't depend on a later
  // reclaim pass.
  if (h->client_idx >= 0 && lock_store(h) == 0) {
    ClientSlot* c = &clients(h)[h->client_idx];
    for (uint32_t ri = 0; ri < kRefsPerClient && c->nrefs > 0; ri++) {
      RefEnt* e = &c->refs[ri];
      if (!e->used) continue;
      Slot* s = &table(h)[e->slot_idx];
      if (s->state != SLOT_EMPTY && s->state != SLOT_TOMBSTONE &&
          s->generation == e->generation) {
        s->refcount -= int32_t(e->count);
        if (s->refcount < 0) s->refcount = 0;
        if (s->state == OBJ_CREATED) {
          destroy_object(h, s);
        } else if (s->refcount == 0 && s->delete_pending) {
          destroy_object(h, s);
        }
      }
      e->used = 0;
      c->nrefs--;
    }
    c->pid = 0;
    unlock_store(h);
  }
  munmap(h->base, h->map_size);
  delete h;
}

int tpus_destroy(const char* path) { return unlink(path) == 0 ? TPUS_OK : TPUS_SYS; }

unsigned char* tpus_base(void* hv) { return reinterpret_cast<Handle*>(hv)->base; }

int tpus_obj_create(void* hv, const uint8_t* id, uint64_t data_size,
                    uint64_t meta_size, uint64_t* data_off) {
  Handle* h = reinterpret_cast<Handle*>(hv);
  if (lock_store(h)) return TPUS_SYS;
  if (find_slot(h, id)) {
    unlock_store(h);
    return TPUS_EXISTS;
  }
  Slot* s = insert_slot(h, id);
  if (!s) {
    reclaim_dead_clients(h);
    s = insert_slot(h, id);
    if (!s) {
      unlock_store(h);
      return TPUS_OOM;  // table full
    }
  }
  uint64_t total = data_size + meta_size;
  uint64_t off = 0;
  if (total > 0) {
    bool reclaimed = false;
    while ((off = heap_alloc(h, total)) == 0) {
      if (evict_one(h)) continue;
      if (!reclaimed) {
        reclaimed = true;
        if (reclaim_dead_clients(h)) continue;
      }
      unlock_store(h);
      return TPUS_OOM;
    }
  }
  memcpy(s->id, id, kIdSize);
  s->state = OBJ_CREATED;
  s->refcount = 1;  // creator holds a ref until seal
  s->delete_pending = 0;
  s->creator_client = h->client_idx >= 0 ? uint32_t(h->client_idx) + 1 : 0;
  s->generation = ++h->hdr->generation;
  s->data_off = off;
  s->data_size = data_size;
  s->meta_size = meta_size;
  s->lru_tick = ++h->hdr->lru_tick;
  h->hdr->bytes_in_use += total;
  h->hdr->num_objects++;
  client_track(h, s, +1);
  *data_off = off;
  unlock_store(h);
  return TPUS_OK;
}

int tpus_obj_seal(void* hv, const uint8_t* id) {
  Handle* h = reinterpret_cast<Handle*>(hv);
  if (lock_store(h)) return TPUS_SYS;
  Slot* s = find_slot(h, id);
  if (!s) {
    unlock_store(h);
    return TPUS_NOT_FOUND;
  }
  if (s->state != OBJ_CREATED) {
    unlock_store(h);
    return TPUS_BAD_STATE;
  }
  s->state = OBJ_SEALED;
  s->refcount--;  // drop creator ref
  client_track(h, s, -1);
  h->hdr->ready_seq++;
  pthread_cond_broadcast(&h->hdr->seal_cv);
  unlock_store(h);
  return TPUS_OK;
}

int tpus_obj_abort(void* hv, const uint8_t* id) {
  Handle* h = reinterpret_cast<Handle*>(hv);
  if (lock_store(h)) return TPUS_SYS;
  Slot* s = find_slot(h, id);
  if (!s) {
    unlock_store(h);
    return TPUS_NOT_FOUND;
  }
  if (s->state != OBJ_CREATED) {
    unlock_store(h);
    return TPUS_BAD_STATE;
  }
  client_track(h, s, -1);
  destroy_object(h, s);
  unlock_store(h);
  return TPUS_OK;
}

int tpus_obj_get(void* hv, const uint8_t* id, int64_t timeout_ms,
                 uint64_t* data_off, uint64_t* data_size, uint64_t* meta_size) {
  Handle* h = reinterpret_cast<Handle*>(hv);
  struct timespec deadline;
  if (timeout_ms > 0) {
    clock_gettime(CLOCK_MONOTONIC, &deadline);
    deadline.tv_sec += timeout_ms / 1000;
    deadline.tv_nsec += (timeout_ms % 1000) * 1000000L;
    if (deadline.tv_nsec >= 1000000000L) {
      deadline.tv_sec++;
      deadline.tv_nsec -= 1000000000L;
    }
  }
  if (lock_store(h)) return TPUS_SYS;
  for (;;) {
    Slot* s = find_slot(h, id);
    if (s && s->state == OBJ_SEALED) {
      s->refcount++;
      s->lru_tick = ++h->hdr->lru_tick;
      client_track(h, s, +1);
      *data_off = s->data_off;
      *data_size = s->data_size;
      *meta_size = s->meta_size;
      unlock_store(h);
      return TPUS_OK;
    }
    if (timeout_ms == 0) {
      unlock_store(h);
      return s ? TPUS_BAD_STATE : TPUS_NOT_FOUND;
    }
    int rc;
    if (timeout_ms > 0) {
      rc = pthread_cond_timedwait(&h->hdr->seal_cv, &h->hdr->lock, &deadline);
    } else {
      rc = pthread_cond_wait(&h->hdr->seal_cv, &h->hdr->lock);
    }
    if (rc == EOWNERDEAD) {
      recover_lock(h);  // waiter inherited a dead owner's mutex
    } else if (rc == ETIMEDOUT) {
      unlock_store(h);
      return TPUS_TIMEOUT;
    }
  }
}

int tpus_obj_release(void* hv, const uint8_t* id) {
  Handle* h = reinterpret_cast<Handle*>(hv);
  if (lock_store(h)) return TPUS_SYS;
  Slot* s = find_slot(h, id);
  if (!s) {
    unlock_store(h);
    return TPUS_NOT_FOUND;
  }
  if (s->refcount > 0) {
    s->refcount--;
    client_track(h, s, -1);
  }
  if (s->refcount == 0 && s->delete_pending) destroy_object(h, s);
  unlock_store(h);
  return TPUS_OK;
}

int tpus_obj_delete(void* hv, const uint8_t* id) {
  Handle* h = reinterpret_cast<Handle*>(hv);
  if (lock_store(h)) return TPUS_SYS;
  Slot* s = find_slot(h, id);
  if (!s) {
    unlock_store(h);
    return TPUS_NOT_FOUND;
  }
  if (s->refcount > 0) {
    s->delete_pending = 1;
  } else {
    destroy_object(h, s);
  }
  unlock_store(h);
  return TPUS_OK;
}

int tpus_obj_contains(void* hv, const uint8_t* id) {
  Handle* h = reinterpret_cast<Handle*>(hv);
  if (lock_store(h)) return TPUS_SYS;
  Slot* s = find_slot(h, id);
  int rc = (s && s->state == OBJ_SEALED) ? 1 : 0;
  unlock_store(h);
  return rc;
}

// Sweep dead clients now (daemon periodic hygiene).
int tpus_reclaim(void* hv) {
  Handle* h = reinterpret_cast<Handle*>(hv);
  if (lock_store(h)) return TPUS_SYS;
  bool any = reclaim_dead_clients(h);
  unlock_store(h);
  return any ? 1 : 0;
}

// Toggle LRU eviction (spilling daemons disable it and reclaim space by
// spilling to disk instead; reference: plasma pinned primary copies).
int tpus_set_eviction(void* hv, int enabled) {
  Handle* h = reinterpret_cast<Handle*>(hv);
  if (lock_store(h)) return TPUS_SYS;
  h->hdr->eviction_off = enabled ? 0 : 1;
  unlock_store(h);
  return TPUS_OK;
}

// Enumerate live objects into caller arrays (each sized max_n).  Returns
// the number of entries written, or a negative TPUS_* error.
int tpus_list(void* hv, uint8_t* ids, uint64_t* sizes, int32_t* refcounts,
              uint32_t* states, uint64_t* lru_ticks, uint32_t max_n) {
  Handle* h = reinterpret_cast<Handle*>(hv);
  if (lock_store(h)) return TPUS_SYS;
  Slot* t = table(h);
  uint32_t out = 0;
  for (uint32_t i = 0; i < h->hdr->max_objects && out < max_n; i++) {
    Slot* s = &t[i];
    if (s->state != OBJ_CREATED && s->state != OBJ_SEALED) continue;
    memcpy(ids + uint64_t(out) * kIdSize, s->id, kIdSize);
    sizes[out] = s->data_size + s->meta_size;
    refcounts[out] = s->refcount;
    states[out] = s->state;
    lru_ticks[out] = s->lru_tick;
    out++;
  }
  unlock_store(h);
  return int(out);
}

int tpus_stats(void* hv, uint64_t* capacity, uint64_t* used, uint64_t* count,
               uint64_t* evictions) {
  Handle* h = reinterpret_cast<Handle*>(hv);
  if (lock_store(h)) return TPUS_SYS;
  *capacity = h->hdr->heap_size;
  *used = h->hdr->bytes_in_use;
  *count = h->hdr->num_objects;
  *evictions = h->hdr->num_evictions;
  unlock_store(h);
  return TPUS_OK;
}

}  // extern "C"
