"""Bin-packing: unfulfilled demand -> nodes to launch.

Reference parity: python/ray/autoscaler/_private/
resource_demand_scheduler.py:103 (get_nodes_to_launch:171).  TPU-specific
semantics: a NodeTypeConfig with slice_hosts > 1 is an ATOMIC slice —
launches happen in whole-slice multiples and a STRICT_PACK placement group
asking for the slice's combined shape maps onto one slice (SURVEY P1: a
v5p-128 is an atomic scaling unit, unlike GPU nodes).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Tuple


@dataclass
class NodeTypeConfig:
    name: str
    resources: Dict[str, float]
    max_workers: int = 10
    # Atomic slice: scaling unit = this many hosts of `resources` each.
    slice_hosts: int = 1


def _fits(avail: Dict[str, float], demand: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) + 1e-9 >= v
               for k, v in demand.items() if v > 0)


def _sub(avail: Dict[str, float], demand: Dict[str, float]) -> None:
    for k, v in demand.items():
        if v > 0:
            avail[k] = avail.get(k, 0.0) - v


class ResourceDemandScheduler:
    def __init__(self, node_types: Dict[str, NodeTypeConfig]):
        self.node_types = node_types

    def get_nodes_to_launch(
            self, existing: List[Dict[str, float]],
            existing_counts: Dict[str, int],
            demands: List[Dict[str, float]],
            pg_demands: List[Tuple[str, List[Dict[str, float]]]],
    ) -> Dict[str, int]:
        """existing: available-resource dicts of alive nodes;
        existing_counts: node_type -> current count (launch caps);
        demands: flat resource demands (pending actors/tasks);
        pg_demands: (strategy, bundles) for pending placement groups.
        Returns node_type -> count to launch (slice types in whole-slice
        multiples)."""
        virtual = [dict(a) for a in existing]
        to_launch: Dict[str, int] = {}
        counts = dict(existing_counts)

        def capacity_left(cfg: NodeTypeConfig) -> int:
            return max(0, cfg.max_workers - counts.get(cfg.name, 0))

        def launch(cfg: NodeTypeConfig, hosts: int) -> int:
            """Launch enough slices/hosts to add >= hosts; returns added."""
            if cfg.slice_hosts > 1:
                slices = math.ceil(hosts / cfg.slice_hosts)
                hosts = slices * cfg.slice_hosts
            hosts = min(hosts, capacity_left(cfg))
            if hosts <= 0:
                return 0
            if cfg.slice_hosts > 1:
                hosts = (hosts // cfg.slice_hosts) * cfg.slice_hosts
                if hosts <= 0:
                    return 0
            to_launch[cfg.name] = to_launch.get(cfg.name, 0) + hosts
            counts[cfg.name] = counts.get(cfg.name, 0) + hosts
            for _ in range(hosts):
                virtual.append(dict(cfg.resources))
            return hosts

        def place(demand: Dict[str, float]) -> bool:
            for avail in virtual:
                if _fits(avail, demand):
                    _sub(avail, demand)
                    return True
            return False

        # Placement groups first (gang semantics: all bundles or nothing).
        for strategy, bundles in pg_demands:
            snapshot = [dict(a) for a in virtual]
            placed_all = all(place(b) for b in bundles)
            if placed_all:
                continue
            # Roll back partial placement, then launch for the whole gang.
            del virtual[:]
            virtual.extend(snapshot)
            for cfg in self._types_for(bundles):
                hosts_needed = self._hosts_for_bundles(cfg, bundles, strategy)
                if hosts_needed and launch(cfg, hosts_needed):
                    if all(place(b) for b in bundles):
                        break
            # else: demand stays unfulfilled (caps/infeasible) — reported
            # by the autoscaler, matching the reference's behavior.

        for demand in demands:
            if place(demand):
                continue
            for cfg in self._types_for([demand]):
                if launch(cfg, 1) and place(demand):
                    break
        return to_launch

    def _types_for(self, bundles: List[Dict[str, float]]):
        """Node types that can host the largest bundle, smallest first."""
        biggest = {}
        for b in bundles:
            for k, v in b.items():
                biggest[k] = max(biggest.get(k, 0.0), v)
        fitting = [c for c in self.node_types.values()
                   if _fits(c.resources, biggest)]
        return sorted(fitting,
                      key=lambda c: sum(c.resources.values()) * c.slice_hosts)

    def _hosts_for_bundles(self, cfg: NodeTypeConfig,
                           bundles: List[Dict[str, float]],
                           strategy: str) -> int:
        """How many `cfg` hosts the bundle set needs (first-fit-decreasing
        per host; STRICT_SPREAD = one bundle per host)."""
        if strategy == "STRICT_SPREAD":
            return len(bundles)
        hosts: List[Dict[str, float]] = []
        order = sorted(bundles, key=lambda b: -sum(b.values()))
        for b in order:
            for h in hosts:
                if _fits(h, b):
                    _sub(h, b)
                    break
            else:
                h = dict(cfg.resources)
                if not _fits(h, b):
                    return 0  # this type can never host the bundle
                _sub(h, b)
                hosts.append(h)
        return len(hosts)
