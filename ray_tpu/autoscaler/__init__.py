"""ray_tpu.autoscaler — demand-driven cluster scaling with TPU-pod
awareness.

Reference parity: python/ray/autoscaler/ (StandardAutoscaler
_private/autoscaler.py:172, LoadMetrics _private/load_metrics.py:65,
bin-packing ResourceDemandScheduler _private/resource_demand_scheduler.py:103,
pluggable NodeProvider node_provider.py:13 incl. fake_multi_node for
tests).  TPU twist (SURVEY P1): a node type can declare an atomic
slice — a v5p pod slice scales as a unit of N hosts, never host-by-host.
"""

from ray_tpu.autoscaler.autoscaler import StandardAutoscaler  # noqa: F401
from ray_tpu.autoscaler.load_metrics import LoadMetrics  # noqa: F401
from ray_tpu.autoscaler.node_provider import (  # noqa: F401
    FakeNodeProvider,
    NodeProvider,
)
from ray_tpu.autoscaler.resource_demand_scheduler import (  # noqa: F401
    NodeTypeConfig,
    ResourceDemandScheduler,
)

__all__ = [
    "FakeNodeProvider", "LoadMetrics", "NodeProvider", "NodeTypeConfig",
    "ResourceDemandScheduler", "StandardAutoscaler",
]
