"""LoadMetrics: the autoscaler's view of cluster load and pending demand.

Reference parity: python/ray/autoscaler/_private/load_metrics.py:65 — but
where the reference receives raylet load pushes through the monitor, this
pulls the GCS tables directly: node resource usage, PENDING placement
groups (bundle lists + strategy), and PENDING actors (their resource
demand).
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List


@dataclass
class PendingPG:
    strategy: str
    bundles: List[Dict[str, float]]


@dataclass
class LoadSnapshot:
    nodes: List[Any] = field(default_factory=list)        # NodeInfo, alive
    pending_pgs: List[PendingPG] = field(default_factory=list)
    pending_actor_demands: List[Dict[str, float]] = field(
        default_factory=list)
    idle_node_ids: List[str] = field(default_factory=list)
    at: float = 0.0


class LoadMetrics:
    def __init__(self, gcs_address: str):
        self.gcs_address = gcs_address

    async def _fetch(self) -> LoadSnapshot:
        from ray_tpu._private.rpc import RpcClient
        gcs = RpcClient(self.gcs_address)
        try:
            nodes = (await gcs.call("Gcs", "get_nodes", {}))["nodes"]
            pgs = (await gcs.call("Gcs", "list_placement_groups",
                                  {}))["placement_groups"]
            actors = (await gcs.call("Gcs", "list_actors", {}))["actors"]
        finally:
            await gcs.close()
        snap = LoadSnapshot(at=time.monotonic())
        snap.nodes = [n for n in nodes if n.alive]
        for p in pgs:
            if p.state in ("PENDING", "RESCHEDULING"):
                snap.pending_pgs.append(
                    PendingPG(p.strategy, [dict(b) for b in p.bundles]))
        for a in actors:
            if a.state == "PENDING":
                demand = a.resources.to_dict() if a.resources else {}
                snap.pending_actor_demands.append(
                    {k: v for k, v in demand.items() if v > 0})
        # Idle = all resources free (no leases, no actors placed there).
        for n in snap.nodes:
            if n.is_head:
                continue
            if all(abs(n.resources_available.get(k, 0.0) - v) < 1e-9
                   for k, v in n.resources_total.items()):
                snap.idle_node_ids.append(n.node_id.hex())
        return snap

    def snapshot(self) -> LoadSnapshot:
        return asyncio.run(self._fetch())
