"""Command runners: how the cluster launcher reaches a machine.

Reference parity: python/ray/autoscaler/_private/command_runner.py —
SSHCommandRunner (ssh/rsync with ControlMaster options) and the docker
wrapper.  Here: a LocalCommandRunner executes on this host (single-host
clusters, tests — the fake provider's analogue), and SSHCommandRunner
shells out to ssh/scp for real multi-host clusters.  Both speak the same
three verbs the launcher needs: run, run_detached, put.
"""

from __future__ import annotations

import os
import shlex
import subprocess
from typing import Optional, Tuple


class CommandRunner:
    def run(self, cmd: str, timeout: Optional[float] = None,
            env: Optional[dict] = None) -> Tuple[int, str]:
        """Run `cmd` through a shell; returns (rc, combined output)."""
        raise NotImplementedError

    def run_detached(self, cmd: str, log_path: str,
                     env: Optional[dict] = None) -> None:
        """Start `cmd` so it outlives this process (daemon start)."""
        raise NotImplementedError

    def put(self, local_path: str, remote_path: str) -> None:
        """Copy a local file onto the target machine."""
        raise NotImplementedError


class LocalCommandRunner(CommandRunner):
    """Executes on this host (provider type `local`)."""

    def run(self, cmd, timeout=None, env=None):
        e = dict(os.environ)
        if env:
            e.update(env)
        proc = subprocess.run(cmd, shell=True, env=e, timeout=timeout,
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
        return proc.returncode, proc.stdout

    def run_detached(self, cmd, log_path, env=None):
        e = dict(os.environ)
        if env:
            e.update(env)
        os.makedirs(os.path.dirname(log_path) or ".", exist_ok=True)
        with open(log_path, "ab") as log:
            subprocess.Popen(cmd, shell=True, env=e, stdout=log,
                             stderr=subprocess.STDOUT,
                             start_new_session=True)

    def put(self, local_path, remote_path):
        if os.path.abspath(local_path) == os.path.abspath(remote_path):
            return
        os.makedirs(os.path.dirname(remote_path) or ".", exist_ok=True)
        import shutil
        shutil.copy2(local_path, remote_path)


class SSHCommandRunner(CommandRunner):
    """Drives a remote host over ssh/scp (reference: command_runner.py
    SSHCommandRunner, incl. the ControlMaster multiplexing options)."""

    _SSH_OPTS = [
        "-o", "StrictHostKeyChecking=no",
        "-o", "UserKnownHostsFile=/dev/null",
        "-o", "LogLevel=ERROR",
        "-o", "ControlMaster=auto",
        "-o", "ControlPath=/tmp/ray_tpu_ssh_%C",
        "-o", "ControlPersist=60s",
    ]

    def __init__(self, ip: str, user: str = "",
                 key_path: Optional[str] = None, port: int = 22):
        self.ip = ip
        self.user = user
        self.key_path = key_path
        self.port = port

    def _target(self) -> str:
        return f"{self.user}@{self.ip}" if self.user else self.ip

    def _base(self, scp: bool = False) -> list:
        cmd = ["scp" if scp else "ssh", *self._SSH_OPTS]
        if self.key_path:
            cmd += ["-i", os.path.expanduser(self.key_path)]
        cmd += (["-P", str(self.port)] if scp else ["-p", str(self.port)])
        return cmd

    def run(self, cmd, timeout=None, env=None):
        envs = ""
        if env:
            envs = " ".join(f"{k}={shlex.quote(str(v))}"
                            for k, v in env.items()) + " "
        full = self._base() + [self._target(), envs + cmd]
        proc = subprocess.run(full, timeout=timeout,
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
        return proc.returncode, proc.stdout

    def run_detached(self, cmd, log_path, env=None):
        envs = ""
        if env:
            envs = " ".join(f"{k}={shlex.quote(str(v))}"
                            for k, v in env.items()) + " "
        wrapped = (f"mkdir -p $(dirname {shlex.quote(log_path)}); "
                   f"nohup {envs}{cmd} > {shlex.quote(log_path)} 2>&1 "
                   f"< /dev/null &")
        full = self._base() + [self._target(), wrapped]
        subprocess.run(full, timeout=60, stdout=subprocess.DEVNULL,
                       stderr=subprocess.DEVNULL)

    def put(self, local_path, remote_path):
        self.run(f"mkdir -p $(dirname {shlex.quote(remote_path)})",
                 timeout=60)
        full = self._base(scp=True) + [
            local_path, f"{self._target()}:{remote_path}"]
        subprocess.run(full, check=True, timeout=300)
