"""StandardAutoscaler: the update loop gluing load -> bin-packing ->
provider.

Reference parity: python/ray/autoscaler/_private/autoscaler.py:172
(update:370 — read load, launch for unfulfilled demand, terminate idle
nodes past the timeout) driven by the monitor daemon
(_private/monitor.py:126); here `update()` is called by a loop or a test
directly.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, Optional

from ray_tpu.autoscaler.load_metrics import LoadMetrics
from ray_tpu.autoscaler.node_provider import NodeProvider
from ray_tpu.autoscaler.resource_demand_scheduler import (
    NodeTypeConfig,
    ResourceDemandScheduler,
)

logger = logging.getLogger("ray_tpu.autoscaler")


class StandardAutoscaler:
    def __init__(self, provider: NodeProvider,
                 node_types: Dict[str, NodeTypeConfig],
                 gcs_address: str, *,
                 idle_timeout_s: float = 60.0,
                 max_launch_batch: int = 16):
        self.provider = provider
        self.node_types = node_types
        self.scheduler = ResourceDemandScheduler(node_types)
        self.load = LoadMetrics(gcs_address)
        self.idle_timeout_s = idle_timeout_s
        self.max_launch_batch = max_launch_batch
        self._idle_since: Dict[str, float] = {}   # runtime node id -> t0
        self.launched_total: Dict[str, int] = {}
        self.terminated_total = 0

    def update(self) -> Dict[str, int]:
        """One reconciliation pass; returns node_type -> launched count."""
        snap = self.load.snapshot()
        existing_avail = [dict(n.resources_available) for n in snap.nodes]
        counts: Dict[str, int] = {}
        for ptype in self.provider.non_terminated_nodes().values():
            counts[ptype] = counts.get(ptype, 0) + 1

        demands = list(snap.pending_actor_demands)
        pg_demands = [(p.strategy, p.bundles) for p in snap.pending_pgs]
        plan = self.scheduler.get_nodes_to_launch(
            existing_avail, counts, demands, pg_demands)

        launched: Dict[str, int] = {}
        for node_type, count in plan.items():
            count = min(count, self.max_launch_batch)
            logger.info("scaling up: %d x %s", count, node_type)
            self.provider.create_nodes(node_type, count)
            launched[node_type] = count
            self.launched_total[node_type] = (
                self.launched_total.get(node_type, 0) + count)

        self._terminate_idle(snap)
        return launched

    def _terminate_idle(self, snap) -> None:
        now = time.monotonic()
        idle = set(snap.idle_node_ids)
        for nid in list(self._idle_since):
            if nid not in idle:
                del self._idle_since[nid]
        by_runtime = {}
        for pid in self.provider.non_terminated_nodes():
            rid = self.provider.runtime_node_id(pid)
            if rid:
                by_runtime[rid] = pid
        runtime_of = {pid: rid for rid, pid in by_runtime.items()}
        terminated: set = set()
        for nid in idle:
            if nid not in by_runtime or by_runtime[nid] in terminated:
                continue  # not ours (e.g. the head) or already gone
            t0 = self._idle_since.setdefault(nid, now)
            if now - t0 >= self.idle_timeout_s:
                pid = by_runtime[nid]
                # A TPU slice is atomic in BOTH directions: only terminate
                # when EVERY host of the slice has been idle past the
                # timeout, then take the whole slice down together.
                members = self.provider.slice_members(pid)
                def _expired(member_pid):
                    rid = runtime_of.get(member_pid)
                    return (rid in idle and now - self._idle_since.get(
                        rid, now) >= self.idle_timeout_s)
                if not all(_expired(m) for m in members):
                    continue
                logger.info("scaling down idle %s (%d hosts)",
                            nid[:12], len(members))
                for m in members:
                    self.provider.terminate_node(m)
                    terminated.add(m)
                    rid = runtime_of.get(m)
                    if rid:
                        self._idle_since.pop(rid, None)
                    self.terminated_total += 1


TPU_POD_TYPES = {
    # Atomic TPU slices: one entry = one host's resources, slice_hosts =
    # hosts per slice (4 chips/host).  Scaling unit = the whole slice.
    "tpu-v5p-8": NodeTypeConfig(
        "tpu-v5p-8", {"CPU": 100.0, "TPU": 4.0, "TPU-v5p-head": 1.0},
        max_workers=64, slice_hosts=1),
    "tpu-v5p-32": NodeTypeConfig(
        "tpu-v5p-32", {"CPU": 100.0, "TPU": 4.0},
        max_workers=64, slice_hosts=4),
    "tpu-v5p-128": NodeTypeConfig(
        "tpu-v5p-128", {"CPU": 100.0, "TPU": 4.0},
        max_workers=128, slice_hosts=16),
    "cpu-worker": NodeTypeConfig(
        "cpu-worker", {"CPU": 16.0}, max_workers=100, slice_hosts=1),
}
