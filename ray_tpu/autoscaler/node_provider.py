"""NodeProvider: the pluggable boundary to actual machines.

Reference parity: python/ray/autoscaler/node_provider.py:13 (create_node /
terminate_node / non_terminated_nodes) and the in-process
fake_multi_node provider used for tests — here the fake provider drives
cluster_utils.Cluster, adding/removing real hostd daemons.
"""

from __future__ import annotations

import uuid
from typing import Any, Dict, List, Optional


class NodeProvider:
    """Interface. node_type names index the autoscaler's NodeTypeConfig."""

    def create_nodes(self, node_type: str, count: int) -> List[str]:
        """Launch `count` nodes of `node_type`; returns provider node ids."""
        raise NotImplementedError

    def terminate_node(self, provider_node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> Dict[str, str]:
        """provider node id -> node_type."""
        raise NotImplementedError

    def runtime_node_id(self, provider_node_id: str) -> Optional[str]:
        """The GCS NodeID hex once the node joined, else None."""
        raise NotImplementedError

    def slice_members(self, provider_node_id: str) -> List[str]:
        """Provider node ids forming this node's atomic slice (just the
        node itself for non-slice types).  A slice terminates as a unit."""
        return [provider_node_id]


class FakeNodeProvider(NodeProvider):
    """Drives an in-process cluster_utils.Cluster — every 'launched' node
    is a real hostd daemon (reference: fake_multi_node provider)."""

    def __init__(self, cluster, node_types: Dict[str, Any]):
        self.cluster = cluster
        self.node_types = node_types
        self._nodes: Dict[str, dict] = {}   # provider id -> cluster node
        self._types: Dict[str, str] = {}
        self._slices: Dict[str, str] = {}   # provider id -> slice group id

    def create_nodes(self, node_type: str, count: int) -> List[str]:
        cfg = self.node_types[node_type]
        slice_hosts = getattr(cfg, "slice_hosts", 1)
        out = []
        slice_id = None
        for i in range(count):
            if slice_hosts > 1 and i % slice_hosts == 0:
                slice_id = f"slice-{uuid.uuid4().hex[:8]}"
            resources = dict(cfg.resources)
            cpus = resources.pop("CPU", 1)
            tpus = resources.pop("TPU", None)
            node = self.cluster.add_node(
                num_cpus=cpus, num_tpus=tpus, resources=resources or None)
            pid = f"fake-{node_type}-{uuid.uuid4().hex[:8]}"
            self._nodes[pid] = node
            self._types[pid] = node_type
            if slice_hosts > 1:
                self._slices[pid] = slice_id
            out.append(pid)
        self.cluster.wait_for_nodes()
        return out

    def slice_members(self, provider_node_id: str) -> List[str]:
        sid = self._slices.get(provider_node_id)
        if sid is None:
            return [provider_node_id]
        return [p for p, g in self._slices.items() if g == sid]

    def terminate_node(self, provider_node_id: str) -> None:
        node = self._nodes.pop(provider_node_id, None)
        self._types.pop(provider_node_id, None)
        self._slices.pop(provider_node_id, None)
        if node is not None:
            self.cluster.remove_node(node, allow_graceful=True)

    def non_terminated_nodes(self) -> Dict[str, str]:
        return dict(self._types)

    def runtime_node_id(self, provider_node_id: str) -> Optional[str]:
        node = self._nodes.get(provider_node_id)
        return node["node_id"] if node else None
