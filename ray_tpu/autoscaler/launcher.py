"""Cluster launcher: `up` / `down` / `exec` / `submit` over a config file.

Reference parity: python/ray/scripts/scripts.py:1247 (ray up/down/attach/
exec/submit/rsync) + autoscaler/_private/commands.py — a YAML/JSON config
names the machines, the launcher reaches them through a CommandRunner
(ssh for real hosts, local for this host), starts the head, joins the
workers, and records the cluster state so later commands find it.

Config (YAML or JSON):

    cluster_name: demo
    provider:
      type: local            # local | ssh
      head_ip: 127.0.0.1
      worker_ips: []         # one hostd joins per entry
    auth:                    # ssh only
      ssh_user: ubuntu
      ssh_private_key: ~/.ssh/key.pem
    head_options: "--num-cpus 8"
    worker_options: ""
    setup_commands: []       # run on every node before start
    python: python3          # interpreter on the nodes

State lives in ~/.ray_tpu/clusters/<name>.json (head address, node ips).
"""

from __future__ import annotations

import json
import os
import shlex
import time
from typing import Optional

from ray_tpu.autoscaler.command_runner import (
    CommandRunner,
    LocalCommandRunner,
    SSHCommandRunner,
)

_STATE_DIR = os.path.expanduser("~/.ray_tpu/clusters")


def load_config(path: str) -> dict:
    with open(path) as f:
        text = f.read()
    try:
        cfg = json.loads(text)
    except ValueError:
        try:
            import yaml
            cfg = yaml.safe_load(text)
        except ImportError as e:
            raise ValueError(
                "config is not JSON and pyyaml is unavailable") from e
    cfg.setdefault("cluster_name", "default")
    cfg.setdefault("provider", {"type": "local", "head_ip": "127.0.0.1"})
    cfg.setdefault("setup_commands", [])
    cfg.setdefault("python", "python3")
    cfg.setdefault("env", {})   # extra env for every launched/exec'd cmd
    return cfg


def _runner(cfg: dict, ip: str) -> CommandRunner:
    ptype = cfg["provider"].get("type", "local")
    if ptype == "local":
        return LocalCommandRunner()
    if ptype == "ssh":
        auth = cfg.get("auth", {})
        return SSHCommandRunner(
            ip, user=auth.get("ssh_user", ""),
            key_path=auth.get("ssh_private_key"),
            port=int(auth.get("ssh_port", 22)))
    raise ValueError(f"unknown provider type {ptype!r}")


def _state_path(name: str) -> str:
    return os.path.join(_STATE_DIR, f"{name}.json")


def _save_state(cfg: dict, state: dict) -> None:
    os.makedirs(_STATE_DIR, exist_ok=True)
    with open(_state_path(cfg["cluster_name"]), "w") as f:
        json.dump(state, f, indent=2)


def load_state(name: str) -> Optional[dict]:
    try:
        with open(_state_path(name)) as f:
            return json.load(f)
    except OSError:
        return None


def _log_dir(cfg: dict) -> str:
    return f"/tmp/ray_tpu/launcher/{cfg['cluster_name']}"


def create_or_update_cluster(config_path: str,
                             no_restart: bool = False) -> dict:
    """`ray up`: setup + start head, join workers, record state."""
    cfg = load_config(config_path)
    prov = cfg["provider"]
    head_ip = prov.get("head_ip", "127.0.0.1")
    py = cfg["python"]
    head = _runner(cfg, head_ip)

    for cmd in cfg["setup_commands"]:
        rc, out = head.run(cmd, timeout=600)
        if rc != 0:
            raise RuntimeError(f"setup command failed on head: {cmd}\n{out}")

    state = load_state(cfg["cluster_name"]) or {}
    gcs_address = state.get("gcs_address")
    if gcs_address and no_restart and _alive(gcs_address):
        print(f"head already running at {gcs_address}")
    else:
        port = int(prov.get("gcs_port", 0)) or 46379
        head_opts = cfg.get("head_options", "")
        log = os.path.join(_log_dir(cfg), "head.log")
        head.run_detached(
            f"{py} -m ray_tpu.scripts.cli start --head --block "
            f"--gcs-port {port} {head_opts}", log, env=cfg["env"])
        gcs_address = f"{head_ip}:{port}"
        deadline = time.time() + 60
        while time.time() < deadline:
            if _alive(gcs_address):
                break
            time.sleep(0.5)
        else:
            raise RuntimeError(
                f"head did not come up at {gcs_address}; see {log}")
        print(f"head started: {gcs_address}")

    worker_ips = list(prov.get("worker_ips", []))
    for i, ip in enumerate(worker_ips):
        w = _runner(cfg, ip)
        for cmd in cfg["setup_commands"]:
            w.run(cmd, timeout=600)
        wlog = os.path.join(_log_dir(cfg), f"worker-{i}.log")
        w.run_detached(
            f"{py} -m ray_tpu.scripts.cli start --block "
            f"--address {gcs_address} {cfg.get('worker_options', '')}",
            wlog, env=cfg["env"])
        print(f"worker {ip} joining {gcs_address}")

    state = {"gcs_address": gcs_address, "head_ip": head_ip,
             "worker_ips": worker_ips, "config_path": os.path.abspath(
                 config_path)}
    _save_state(cfg, state)
    _wait_for_nodes(gcs_address, 1 + len(worker_ips))
    return state


def _alive(gcs_address: str) -> bool:
    from ray_tpu import state as st
    try:
        st.list_nodes(gcs_address)
        return True
    except Exception:
        return False


def _wait_for_nodes(gcs_address: str, n: int, timeout: float = 60):
    from ray_tpu import state as st
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            alive = [x for x in st.list_nodes(gcs_address) if x["alive"]]
            if len(alive) >= n:
                print(f"{len(alive)} node(s) alive")
                return
        except Exception:
            pass
        time.sleep(0.5)
    print(f"warning: expected {n} nodes within {timeout}s")


def teardown_cluster(config_path: str) -> None:
    """`ray down`: stop every daemon, drop the state record."""
    cfg = load_config(config_path)
    state = load_state(cfg["cluster_name"])
    if state and state.get("gcs_address"):
        # Shutdown rides the RPC control plane, not a command runner.
        import asyncio

        from ray_tpu._private.rpc import RpcClient

        async def stop():
            c = RpcClient(state["gcs_address"])
            try:
                await c.call("Gcs", "shutdown_cluster", {}, timeout=10)
            except Exception:
                pass
            finally:
                await c.close()
        try:
            asyncio.run(stop())
        except Exception:
            pass
        print(f"cluster {cfg['cluster_name']} shutdown requested")
    try:
        os.unlink(_state_path(cfg["cluster_name"]))
    except OSError:
        pass


def exec_cluster(config_path: str, cmd: str,
                 timeout: Optional[float] = None) -> int:
    """`ray exec`: run a shell command on the head with RAY_TPU_ADDRESS
    pointing at the cluster."""
    cfg = load_config(config_path)
    state = load_state(cfg["cluster_name"])
    if not state:
        raise RuntimeError(f"cluster {cfg['cluster_name']} is not up")
    head = _runner(cfg, state["head_ip"])
    rc, out = head.run(cmd, timeout=timeout,
                       env={**cfg["env"],
                            "RAY_TPU_ADDRESS": state["gcs_address"]})
    print(out, end="")
    return rc


def submit(config_path: str, script: str, args: Optional[list] = None,
           timeout: Optional[float] = None) -> int:
    """`ray submit`: ship a local script to the head and run it there."""
    cfg = load_config(config_path)
    state = load_state(cfg["cluster_name"])
    if not state:
        raise RuntimeError(f"cluster {cfg['cluster_name']} is not up")
    head = _runner(cfg, state["head_ip"])
    remote = f"/tmp/ray_tpu/launcher/{cfg['cluster_name']}/job_{int(time.time())}_{os.path.basename(script)}"
    head.put(script, remote)
    argstr = " ".join(shlex.quote(a) for a in (args or []))
    rc, out = head.run(f"{cfg['python']} {shlex.quote(remote)} {argstr}",
                       timeout=timeout,
                       env={**cfg["env"],
                            "RAY_TPU_ADDRESS": state["gcs_address"]})
    print(out, end="")
    return rc


def attach_command(config_path: str) -> list:
    """`ray attach`: argv for an interactive shell on the head (the CLI
    exec()s it so the user lands in a live session)."""
    cfg = load_config(config_path)
    state = load_state(cfg["cluster_name"])
    if not state:
        raise RuntimeError(f"cluster {cfg['cluster_name']} is not up")
    if cfg["provider"].get("type") == "local":
        return [os.environ.get("SHELL", "/bin/bash")]
    auth = cfg.get("auth", {})
    r = SSHCommandRunner(state["head_ip"], user=auth.get("ssh_user", ""),
                         key_path=auth.get("ssh_private_key"),
                         port=int(auth.get("ssh_port", 22)))
    return r._base() + ["-t", r._target()]
