"""Parallelism layer: meshes, sharding rules, pipeline schedules.

First-class DP/FSDP/TP/SP/EP/PP over one jax.sharding.Mesh (the reference
delegates all of this to hosted frameworks — SURVEY.md §2.5)."""

from ray_tpu.parallel.mesh import (  # noqa: F401
    AXES,
    MeshConfig,
    create_mesh,
    create_two_level_mesh,
    dcn_cut_edges,
    mesh_axis_size,
    pipeline_placement_resources,
    single_device_mesh,
    slice_index_of,
    stage_slice_plan,
)
from ray_tpu.parallel.sharding import (  # noqa: F401
    DEFAULT_RULES,
    global_batch,
    logical_to_spec,
    named_sharding,
    shard_batch,
    shard_opt_state,
    tree_shardings,
    with_logical_constraint,
)
from ray_tpu.parallel.pipeline import (  # noqa: F401
    chunk_assignment,
    pipeline_apply,
    pipeline_loss_dryrun,
    stack_stage_params,
)
