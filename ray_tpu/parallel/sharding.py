"""Logical-axis sharding rules: annotate arrays by meaning, not mesh axis.

Parameters and activations carry *logical* axis names ("embed", "mlp",
"heads", "batch", "length", "experts", ...).  A rule table maps logical →
mesh axes; changing the parallelism strategy is a rule-table swap, never a
model edit.  This is the GSPMD/pjit idiom (scaling-book recipe): annotate,
let XLA insert the collectives.

No reference counterpart — Ray delegates sharding to hosted frameworks
(SURVEY.md §2.5); here it is a core subsystem.
"""

from __future__ import annotations

from typing import Any, Mapping, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

LogicalSpec = Tuple[Optional[str], ...]

# Default rule table: logical axis -> mesh axis (or tuple of mesh axes).
# Covers dense transformer + MoE.  "embed" deliberately maps to fsdp so that
# ZeRO-3 style weight sharding engages when the fsdp axis is >1.
DEFAULT_RULES: Mapping[str, Union[str, Tuple[str, ...], None]] = {
    "batch": ("data", "fsdp"),   # global batch split over both DP axes
    "length": "seq",             # sequence dim: context parallelism
    "embed": "fsdp",             # param embed dim: FSDP shard
    "act_embed": None,           # activation embed dim: full (batch already
                                 # covers fsdp; XLA all-gathers params JIT)
    "mlp": "tensor",             # ffn hidden: megatron column/row split
    "heads": "tensor",           # attention heads: megatron split
    "kv_heads": "tensor",        # GQA key/value head groups (llama)
    "kv": None,                  # per-head dim: never sharded
    # Vocab dim carries BOTH the tensor and fsdp shards of the embedding
    # table.  Sharding the table's embed dim over fsdp instead forces the
    # partitioner to move the fsdp shard from the gather output's embed dim
    # onto the activations' batch dim — a transposed-device-order reshard
    # XLA can only do by full rematerialization (observed in the r1
    # multichip dryrun).  Vocab-side sharding keeps the gather output
    # unsharded on embed and still splits table memory 4 ways.
    "vocab": ("tensor", "fsdp"),  # embedding/logits vocab dim
    "experts": "expert",         # MoE expert dim
    "expert_mlp": "tensor",      # ffn hidden inside an expert
    "layers": None,              # scanned layer dim (stacked params)
    "stage": "stage",            # pipeline stage dim
}


def logical_to_spec(logical: LogicalSpec,
                    rules: Optional[Mapping] = None,
                    mesh: Optional[Mesh] = None) -> P:
    """Translate a logical spec like ("batch", "length", "embed") to a
    PartitionSpec using `rules`.  Mesh axes of size 1 (or absent) are dropped
    so the same rules work on any mesh shape."""
    rules = DEFAULT_RULES if rules is None else rules
    out = []
    used: set = set()
    for name in logical:
        mapped = rules.get(name) if name is not None else None
        if mapped is None:
            out.append(None)
            continue
        axes = (mapped,) if isinstance(mapped, str) else tuple(mapped)
        if mesh is not None:
            axes = tuple(a for a in axes if mesh.shape.get(a, 1) > 1)
        # A mesh axis may shard at most one dim: first logical axis wins
        # (e.g. logits ("batch","length","vocab") where batch takes fsdp
        # and vocab falls back to tensor only).
        axes = tuple(a for a in axes if a not in used)
        used.update(axes)
        if not axes:
            out.append(None)
        elif len(axes) == 1:
            out.append(axes[0])
        else:
            out.append(axes)
    # Trailing Nones are redundant in a PartitionSpec.
    while out and out[-1] is None:
        out.pop()
    return P(*out)


def named_sharding(mesh: Mesh, logical: LogicalSpec,
                   rules: Optional[Mapping] = None) -> NamedSharding:
    return NamedSharding(mesh, logical_to_spec(logical, rules, mesh))


def tree_shardings(mesh: Mesh, logical_tree: Any,
                   rules: Optional[Mapping] = None) -> Any:
    """Map a pytree of logical specs to a pytree of NamedShardings."""
    return jax.tree.map(
        lambda spec: named_sharding(mesh, spec, rules),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple),
    )


def with_logical_constraint(x, logical: LogicalSpec,
                            rules: Optional[Mapping] = None,
                            mesh: Optional[Mesh] = None):
    """Inside jit: constrain an intermediate to its logical sharding.
    Outside a mesh context (single chip) this is a no-op."""
    if mesh is None or all(s <= 1 for s in mesh.shape.values()):
        return x
    spec = logical_to_spec(logical, rules, mesh)
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def shard_opt_state(opt_state: Any, params: Any, param_shardings: Any,
                    mesh: Mesh) -> Any:
    """Place optimizer state on the mesh: any subtree congruent with the
    params tree (Adam mu/nu, momentum, ...) inherits the param shardings
    leaf-for-leaf; everything else (step counts, scalars) is replicated.
    This is the ZeRO-3 half that `shard_params` alone misses."""
    from jax.tree_util import default_registry

    pstruct = jax.tree.structure(params)
    replicated = NamedSharding(mesh, P())

    def place(node):
        if jax.tree.structure(node) == pstruct and pstruct.num_leaves > 1:
            return jax.tree.map(jax.device_put, node, param_shardings)
        try:
            flat = default_registry.flatten_one_level(node)
        except ValueError:
            flat = None
        if flat is None:  # a leaf (array or scalar)
            return (jax.device_put(node, replicated)
                    if hasattr(node, "shape") else node)
        children, _ = flat
        one_level = jax.tree.structure(node,
                                       is_leaf=lambda x: x is not node)
        return jax.tree.unflatten(one_level, [place(c) for c in children])

    return place(opt_state)


def _batch_logical(x) -> LogicalSpec:
    if x.ndim >= 2:
        return ("batch", "length") + (None,) * (x.ndim - 2)
    return ("batch",) + (None,) * (x.ndim - 1)


def batch_shardings(mesh: Mesh, batch: Any,
                    rules: Optional[Mapping] = None) -> Any:
    """Per-leaf NamedShardings for a host batch pytree with the
    ("batch", "length") layout — the placement half of `shard_batch`,
    without the device_put.  The device-feed ingest path
    (data.ingest.DeviceBatchIterator) resolves a bare Mesh argument
    through this, so `iter_device_batches(sharding=mesh)` lands every
    column split over the data axes."""
    return jax.tree.map(
        lambda x: named_sharding(mesh, _batch_logical(x), rules), batch)


def shard_batch(mesh: Mesh, batch: Any,
                rules: Optional[Mapping] = None) -> Any:
    """Device-put a host batch pytree with ("batch", "length") layout onto
    the mesh, splitting over the data axes.  Single-controller form — every
    process must hold the full global batch; use `global_batch` in
    multi-controller (one-process-per-host) programs."""
    def put(x):
        return jax.device_put(x, named_sharding(mesh, _batch_logical(x),
                                                rules))
    return jax.tree.map(put, batch)


def global_batch(mesh: Mesh, local_batch: Any,
                 rules: Optional[Mapping] = None) -> Any:
    """Multi-controller batch assembly: each process contributes its LOCAL
    shard (stacked on dim 0) of a global batch sharded over the data axes.
    The global batch dim = local batch dim * process_count."""
    nproc = jax.process_count()

    def put(x):
        sharding = named_sharding(mesh, _batch_logical(x), rules)
        global_shape = (x.shape[0] * nproc,) + tuple(x.shape[1:])
        return jax.make_array_from_process_local_data(sharding, x,
                                                      global_shape)
    return jax.tree.map(put, local_batch)
