"""Pipeline parallelism: GPipe-style microbatch rotation over a mesh axis.

No reference counterpart — Ray hosts frameworks that do PP externally
(SURVEY.md §2.5 lists PP as "NO first-class").  TPU-native design: the
`stage` mesh axis holds one pipeline stage per device group; microbatches
circulate stage-to-stage with `jax.lax.ppermute` (a single-hop ICI transfer),
and the whole schedule is one `lax.scan` inside `shard_map`, so XLA overlaps
the permute with each stage's compute.

Layout convention: stage-local layer parameters are stacked on a leading
"stage" dim of every param leaf; inputs arrive with microbatches on a leading
dim of size `n_micro` and are fed one per scan step.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.parallel.mesh import shard_map_compat


def pipeline_apply(stage_fn: Callable[[Any, jax.Array], jax.Array],
                   mesh: Mesh,
                   stage_params: Any,
                   microbatches: jax.Array,
                   axis: str = "stage") -> jax.Array:
    """Run `stage_fn(params_for_stage, x) -> y` as a pipeline over mesh
    `axis`.

    Args:
      stage_fn: computes one stage on one microbatch (same shape in/out).
      stage_params: pytree whose leaves have leading dim = n_stages (sharded
        over `axis`).
      microbatches: [n_micro, micro_batch, ...] input, replicated over
        `axis` (only stage 0 consumes it; replication keeps the shard_map
        specs simple and the input small relative to activations).

    Returns [n_micro, micro_batch, ...] output from the final stage,
    replicated over `axis`.
    """
    n_stages = mesh.shape[axis]
    n_micro = microbatches.shape[0]
    total_steps = n_micro + n_stages - 1

    param_spec = P(axis)
    # Microbatch stream: replicated over the stage axis, but the per-
    # microbatch batch dim stays sharded over the data axes (each data
    # slice pipelines its own batch shard; P() here would make every
    # slice redundantly compute the global batch).
    from ray_tpu.parallel.mesh import mesh_axis_size
    batch_axes = tuple(a for a in ("data", "fsdp")
                       if mesh_axis_size(mesh, a) > 1)
    io_spec = P(None, batch_axes if batch_axes else None)

    def per_stage(params, mb):
        # Inside shard_map: params leaves have leading dim 1 (this stage's
        # slice); mb is the full [n_micro, ...] stream.
        params = jax.tree.map(lambda p: p[0], params)
        stage = jax.lax.axis_index(axis)

        state = jnp.zeros_like(mb[0])          # activation held by this stage
        outputs = jnp.zeros_like(mb)

        def step(carry, t):
            state, outputs = carry
            # Stage 0 ingests microbatch t (when still available).
            feed = mb[jnp.minimum(t, n_micro - 1)]
            x = jnp.where(stage == 0, feed, state)
            y = stage_fn(params, x)
            # Rotate: stage i -> i+1 (last stage's output is collected).
            nxt = jax.lax.ppermute(
                y, axis, [(i, (i + 1) % n_stages) for i in range(n_stages)])
            # Last stage finishes microbatch (t - (n_stages-1)) at step t.
            out_idx = t - (n_stages - 1)
            valid = (stage == n_stages - 1) & (out_idx >= 0)
            outputs = jax.lax.cond(
                valid,
                lambda o: o.at[jnp.maximum(out_idx, 0)].set(y),
                lambda o: o,
                outputs)
            return (nxt, outputs), None

        (state, outputs), _ = jax.lax.scan(
            step, (state, outputs), jnp.arange(total_steps))
        # Replicate the final outputs (held only by the last stage) to all
        # stages: zero elsewhere, then psum — callers can apply loss anywhere.
        outputs = jnp.where(stage == n_stages - 1, outputs,
                            jnp.zeros_like(outputs))
        return jax.lax.psum(outputs, axis)

    fn = shard_map_compat(
        per_stage, mesh,
        in_specs=(jax.tree.map(lambda _: param_spec, stage_params,
                               is_leaf=lambda x: x is None),
                  io_spec),
        out_specs=io_spec)
    return fn(stage_params, microbatches)


def chunk_assignment(n_chunks: int, n_gangs: int) -> list:
    """Round-robin chunk ownership for the interleaved (looping) MPMD
    schedule: gang g owns chunks ``g, g+n_gangs, ...`` — non-adjacent by
    construction, so every gang has work during warmup/drain and the
    pipeline bubble shrinks ~1/v for ``v = n_chunks // n_gangs`` chunks
    per gang.  Shared between the MPMD trainer and tests so the dryrun
    parity checks assert against the exact ownership the trainer uses.

    Returns a list of length `n_gangs`: assignment[g] = sorted chunk ids.
    """
    if n_gangs <= 0 or n_chunks % n_gangs:
        raise ValueError(
            f"{n_chunks} chunks not divisible across {n_gangs} gangs")
    return [list(range(g, n_chunks, n_gangs)) for g in range(n_gangs)]


def stack_stage_params(per_stage_params: list) -> Any:
    """Stack a list of per-stage param pytrees along a new leading dim."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def pipeline_loss_dryrun(stage_fn: Callable, loss_fn: Callable,
                         mesh: Mesh, stage_params: Any,
                         microbatches: jax.Array, targets: jax.Array,
                         axis: str = "stage") -> jax.Array:
    """Mean microbatch loss of the single-program GPipe dryrun — the
    reference value the MPMD trainer (train/pipeline_trainer.py) must
    match to fp tolerance on the same schedule (the standing parity
    gate, tests/test_pipeline_mpmd.py).

    `loss_fn(y, target) -> scalar` is applied per microbatch to the
    final stage's outputs; `targets` has the same [n_micro, ...] leading
    layout as `microbatches`."""
    outputs = pipeline_apply(stage_fn, mesh, stage_params, microbatches,
                             axis=axis)
    losses = jax.vmap(loss_fn)(outputs, targets)
    return jnp.mean(losses)
