"""Device-mesh construction for multi-dimensional parallelism.

The reference (Ray) has no first-class mesh concept — DP/TP/PP live in the
hosted frameworks (SURVEY.md §2.5, reference release/alpa_tests/).  Here the
mesh IS the first-class object: every parallelism strategy is an axis of one
`jax.sharding.Mesh` and XLA/GSPMD compiles the collectives onto ICI.

Axis vocabulary (MaxText-style, one mesh for the whole program):
  data    — pure data parallelism (batch split, gradients psum over ICI/DCN)
  fsdp    — data parallelism with sharded params/optimizer (ZeRO-3 style;
            params all-gathered per layer, grads reduce-scattered)
  expert  — expert parallelism for MoE layers (experts split across devices,
            tokens routed via all-to-all)
  seq     — sequence/context parallelism (ring attention over this axis)
  tensor  — tensor (megatron) parallelism within attention/mlp blocks
  stage   — pipeline stage axis (used by parallel.pipeline, not by GSPMD)
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("data", "fsdp", "expert", "seq", "tensor", "stage")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Sizes for each parallelism axis; -1 means "absorb remaining devices".

    At most one axis may be -1.  The product of resolved sizes must equal the
    device count.
    """

    data: int = -1
    fsdp: int = 1
    expert: int = 1
    seq: int = 1
    tensor: int = 1
    stage: int = 1

    def resolve(self, n_devices: int) -> dict:
        sizes = {a: getattr(self, a) for a in AXES}
        wild = [a for a, s in sizes.items() if s == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one axis may be -1, got {wild}")
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if wild:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes ({fixed})")
            sizes[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh {sizes} needs {fixed} devices, have {n_devices}")
        return sizes


def create_mesh(config: Optional[MeshConfig] = None,
                devices: Optional[Sequence[jax.Device]] = None,
                axis_names: Sequence[str] = AXES) -> Mesh:
    """Build a Mesh over `devices` (default: all) per `config`.

    Device order follows jax.devices(), which JAX arranges so that adjacent
    devices are ICI neighbours on TPU; trailing (fastest-varying) mesh axes
    therefore get the best ICI locality — put `tensor` and `seq` last, which
    the default axis order already does.
    """
    devices = list(devices if devices is not None else jax.devices())
    config = config or MeshConfig()
    sizes = config.resolve(len(devices))
    shape = tuple(sizes[a] for a in axis_names)
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, axis_names)


def single_device_mesh() -> Mesh:
    """A 1-chip mesh with all axes size 1 — lets one jitted program serve
    both single-chip and pod runs without branching."""
    return create_mesh(MeshConfig(data=1), devices=jax.devices()[:1])


def mesh_axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape.get(axis, 1)


def shard_map_compat(f, mesh: Mesh, in_specs, out_specs):
    """shard_map with replication checking off, across jax versions
    (the flag was renamed check_rep -> check_vma around jax 0.8)."""
    import inspect

    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map
    kw = ("check_rep" if "check_rep"
          in inspect.signature(shard_map).parameters else "check_vma")
    return shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     **{kw: False})
